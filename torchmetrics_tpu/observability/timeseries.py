"""Telemetry history plane: telescoping retention + time-travel queries.

Every other observability surface answers "what is true NOW"; a breach that
resolved before anyone looked, a slow leak, or a p99 degrading over an hour
is invisible to a point-in-time scrape. :class:`TelemetryHistory` retains the
session's own telemetry — counter DELTAS and per-kind histogram vector
deltas per retained block — in a telescoping level hierarchy
(:class:`~torchmetrics_tpu.streaming.telescope.TelescopingFold`, default
1s → 10s → 1m → 1h): recent time at fine resolution, old time folded coarse,
total memory O(levels) instead of O(sum of windows). Both payloads are plain
mergeable integer vectors (the DrJAX-style reduction contract the fleet
rollup rides), so the fold IS exact elementwise addition and a retained
block is the exact telemetry delta over its time range.

The recorder feeds it at its sample choke points (every ``record_sync``
heartbeat — the same cadence the SLO engine samples on — plus session
close); ``history.at(t)`` / ``history.range(t0, t1, level=)`` answer
point-in-time queries over the retained boundaries, ``/historyz`` serves the
same answers over HTTP, and :meth:`TelemetryHistory.export_block` emits the
deterministic last-N-boundaries block that rides ``SoakReport.history`` and
flight-recorder artifacts (virtual-clock keyed in soaks, wall-clock counters
stripped — same byte-identical same-seed contract as the causal block).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, FrozenSet, Iterable, List, Optional, Sequence, Tuple

from .counters import COUNTER_FIELDS
from .histograms import _KIND_VEC_LEN, FLEET_HISTOGRAM_KINDS, FLEET_VECTOR_LEN, Histogram

# level spans the recorder retains by default: ten 1s blocks, six 10s blocks,
# sixty 1m blocks, twenty-four 1h blocks — ~100 blocks covering a full day,
# vs 86_400 for a naive 1s ring over the same span
DEFAULT_SPANS: Tuple[float, ...] = (1.0, 10.0, 60.0, 3600.0)

# one retained sample: (counter delta vector, fleet histogram delta vector)
_Sample = Tuple[List[int], List[int]]


def _merge_sample(a: _Sample, b: _Sample) -> _Sample:
    return (
        [x + y for x, y in zip(a[0], b[0])],
        [x + y for x, y in zip(a[1], b[1])],
    )


class TelemetryHistory:
    """Multi-resolution retention of one session's telemetry deltas.

    ``clock`` is the determinism seam: soak/fleet runs inject their virtual
    clock so block boundaries (and therefore the whole retained history) are
    a pure function of the seeded run; outside a soak it defaults to the
    monotonic clock every event timestamp already uses. Thread-safe — the
    training thread feeds while health-server request threads query.
    """

    def __init__(
        self,
        spans: Sequence[float] = DEFAULT_SPANS,
        keep: Optional[Sequence[int]] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        from ..streaming.telescope import TelescopingFold  # runtime import: the
        # streaming package pulls jax + metric at module level, and observability
        # must stay importable mid-package-init (metric.py imports it first)

        self._lock = threading.Lock()
        self._fold = TelescopingFold(spans=spans, keep=keep, merge=_merge_sample)
        self._clock = clock
        self._last: Optional[Tuple[List[int], List[int]]] = None
        self._last_t: Optional[float] = None
        self.samples = 0

    @property
    def spans(self) -> Tuple[float, ...]:
        return self._fold.spans

    @property
    def folds(self) -> int:
        return self._fold.folds

    def _now(self) -> float:
        if self._clock is not None:
            return float(self._clock())
        from . import tracing

        return tracing.monotonic()

    # ------------------------------------------------------------------ feed

    def due(self, now: Optional[float] = None) -> bool:
        """Whether a NEW finest-span block has started since the last
        observation — the recorder's per-``record_sync`` heartbeat gates on
        this so the expensive vector snapshot is built at most once per
        finest block (deltas are cumulative: activity inside a skipped
        interval rides the next boundary observation, nothing is lost)."""
        t = self._now() if now is None else float(now)
        span = self.spans[0]
        with self._lock:
            return self._last_t is None or (t // span) != (self._last_t // span)

    def observe(
        self,
        counter_vec: Sequence[int],
        hist_vec: Sequence[int],
        now: Optional[float] = None,
    ) -> int:
        """Feed one ABSOLUTE sample (the live ``counts_vector()`` +
        ``fleet_vector()``); the history retains the delta since the previous
        observation, so a block's vectors are exactly the activity inside its
        time range. Returns how many blocks the feed closed (folds)."""
        cvec = [int(v) for v in counter_vec]
        hvec = [int(v) for v in hist_vec]
        if len(cvec) != len(COUNTER_FIELDS) or len(hvec) != FLEET_VECTOR_LEN:
            raise ValueError(
                f"history sample has {len(cvec)}/{len(hvec)} entries, expected "
                f"{len(COUNTER_FIELDS)}/{FLEET_VECTOR_LEN}"
            )
        t = self._now() if now is None else float(now)
        with self._lock:
            if self._last is None:
                delta = (cvec, hvec)  # first observation: delta vs session zero
            else:
                delta = (
                    [a - b for a, b in zip(cvec, self._last[0])],
                    [a - b for a, b in zip(hvec, self._last[1])],
                )
            self._last = (cvec, hvec)
            self._last_t = t
            self.samples += 1
            return self._fold.feed(t, delta)

    # --------------------------------------------------------------- queries

    @staticmethod
    def _block_doc(level: int, span: float, start: float, end: float, value: _Sample) -> Dict[str, Any]:
        cvec, hvec = value
        counters = {f: int(v) for f, v in zip(COUNTER_FIELDS, cvec) if v}
        hists: Dict[str, Any] = {}
        for i, kind in enumerate(FLEET_HISTOGRAM_KINDS):
            section = hvec[i * _KIND_VEC_LEN : (i + 1) * _KIND_VEC_LEN]
            if section[0]:
                hists[kind] = Histogram.from_vector(section).summary()
        return {
            "level": level,
            "span": span,
            "start": round(start, 6),
            "end": round(end, 6),
            "counters": counters,
            "histograms": hists,
        }

    def at(self, t: float) -> Optional[Dict[str, Any]]:
        """The finest retained block covering time ``t`` (counter deltas +
        histogram summaries over that block's range), or ``None`` when the
        history has telescoped past ``t`` or ``t`` is in the future."""
        with self._lock:
            hit = self._fold.at(float(t))
            if hit is None:
                return None
            level, start, end, value = hit
            return self._block_doc(level, self.spans[level], start, end, value)

    def range(self, t0: float, t1: float, level: int = 0) -> List[Dict[str, Any]]:
        """Blocks of one level overlapping ``[t0, t1)``, time-ordered."""
        with self._lock:
            span = self.spans[level]
            return [
                self._block_doc(level, span, s, e, v)
                for s, e, v in self._fold.range(float(t0), float(t1), level=level)
            ]

    def levels(self) -> Dict[str, Any]:
        """The whole retained hierarchy as one JSON document — ``/historyz``'s
        default body. Bounded by construction (O(levels) blocks), so serving
        it whole is cheap."""
        with self._lock:
            out_levels = []
            for i, span in enumerate(self.spans):
                blocks = [
                    self._block_doc(i, span, s, e, v) for s, e, v in self._fold.blocks(i)
                ]
                out_levels.append({"level": i, "span": span, "keep": self._fold.keep[i], "blocks": blocks})
            return {
                "spans": list(self.spans),
                "samples": self.samples,
                "folds": self._fold.folds,
                "blocks": self._fold.block_count(),
                "levels": out_levels,
            }

    def block_count(self) -> int:
        """Total retained blocks — the O(levels) memory pin."""
        with self._lock:
            return self._fold.block_count()

    # ------------------------------------------------------------ contractual

    def export_block(
        self, last_n: int = 8, drop: Iterable[str] = ()
    ) -> Dict[str, Any]:
        """The DETERMINISTIC history block for ``SoakReport.history`` and
        flight-recorder artifacts: per level, the last ``last_n`` retained
        boundaries with their counter deltas (minus the wall-clock fields in
        ``drop`` — ``flightrec.NONDETERMINISTIC_COUNTERS``) and per-kind
        EVENT COUNTS only (histogram totals/buckets hold wall-clock latency
        values; the counts are seed-deterministic). Under an injected virtual
        clock this block is a pure function of (config, seed) — two same-seed
        runs serialize byte-identically, same contract as ``causal``."""
        dropset: FrozenSet[str] = frozenset(drop)
        with self._lock:
            levels_out = []
            for i, span in enumerate(self.spans):
                blocks = []
                for start, end, value in self._fold.blocks(i)[-max(0, int(last_n)):]:
                    cvec, hvec = value
                    counters = {
                        f: int(v)
                        for f, v in zip(COUNTER_FIELDS, cvec)
                        if v and f not in dropset
                    }
                    events = {
                        kind: int(hvec[j * _KIND_VEC_LEN])
                        for j, kind in enumerate(FLEET_HISTOGRAM_KINDS)
                        if hvec[j * _KIND_VEC_LEN]
                    }
                    blocks.append({
                        "start": round(start, 6),
                        "end": round(end, 6),
                        "counters": counters,
                        "events": events,
                    })
                levels_out.append({"span": span, "blocks": blocks})
            return {
                "spans": list(self.spans),
                "samples": self.samples,
                "folds": self._fold.folds,
                "levels": levels_out,
            }
