"""Per-dispatch-key compiled cost accounting.

The compile counters (``counters.py``) answer *how many* XLA programs a run
built; this module answers *what each one costs*: FLOPs and bytes accessed from
XLA's ``cost_analysis()``, and argument/output/temp HBM footprints from
``memory_analysis()`` — both harvested from an AOT re-lowering of the jitted
update (``jitted.lower(avals).compile()``) at the moment the dispatch counters
record a fresh compile. Harvesting uses **avals only** (``jax.ShapeDtypeStruct``
built from shape/dtype metadata), so it never reads device memory — an
instrumented hot loop stays D2H-free even with cost accounting on.

The registry reconciles 1:1 with the compile counters: every ``(key,
signature)`` pair the counters count as a compile gets exactly one
:class:`CostRecord` — a placeholder with ``available=False`` when the program
cannot be lowered (``jit=False`` metrics) or the backend declines analysis —
so ``cost_snapshot().keys() == per-key compile keys`` always holds.

The registry itself is pure stdlib (the bench driver reads snapshots without a
runtime); only :func:`harvest_compiled` touches jax, lazily, and only inside an
opted-in telemetry session.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, Optional

#: cost_analysis scalars we extract, in reporting order
COST_FIELDS = ("flops", "bytes_accessed", "transcendentals")
#: memory_analysis scalars we extract (per-program HBM footprint)
MEMORY_FIELDS = (
    "argument_bytes", "output_bytes", "temp_bytes", "alias_bytes", "generated_code_bytes",
)


@dataclasses.dataclass(frozen=True)
class CostRecord:
    """Compiled cost of ONE XLA program — a ``(dispatch key, signature)`` pair.

    ``available=False`` marks a placeholder: the compile was counted but its
    cost could not be harvested (eager ``jit=False`` path, or a backend without
    ``cost_analysis``/``memory_analysis`` support); ``error`` says why. The
    placeholder keeps the registry reconciling 1:1 with the compile counters.
    """

    key: str
    signature: str
    available: bool
    flops: float = 0.0
    bytes_accessed: float = 0.0
    transcendentals: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0
    generated_code_bytes: int = 0
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"available": self.available}
        for f in COST_FIELDS + MEMORY_FIELDS:
            out[f] = getattr(self, f)
        if self.error is not None:
            out["error"] = self.error
        return out


def make_lowerer(jitted: Any, tensors: Dict[str, Any], n_prev: Any, inputs: Optional[tuple]) -> Optional[Callable[[], Any]]:
    """Zero-arg thunk that AOT-lowers and compiles ``jitted`` for this dispatch's
    shapes — or ``None`` when the function is not lowerable (eager path).

    Everything is LAZY: the thunk only captures references, and the recorder
    invokes it solely for fresh compiles — the ~100% cache-hit steady state
    pays one closure allocation per dispatch, no aval construction. Laziness is
    safe even though the dispatch donates (and deletes) the live buffers before
    the thunk runs: deleted jax arrays keep their ``shape``/``dtype`` metadata,
    which is all the avals read.
    """
    if jitted is None or not hasattr(jitted, "lower"):
        return None

    def lower() -> Any:
        import jax

        def to_aval(x: Any) -> Any:
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
            return x

        t_avals = {k: to_aval(v) for k, v in tensors.items()}
        args, kwargs = inputs if inputs else ((), {})
        a_avals = jax.tree.map(to_aval, args)
        k_avals = jax.tree.map(to_aval, kwargs)
        return jitted.lower(t_avals, to_aval(n_prev), *a_avals, **k_avals).compile()

    return lower


def harvest_compiled(key: str, signature: str, lower: Optional[Callable[[], Any]]) -> CostRecord:
    """Harvest one program's cost; never raises (a placeholder records why not).

    ``cost_analysis()`` returns one dict per computation on older jax (a list)
    and a flat dict on newer — both shapes are accepted. Backends report
    unavailable scalars as negative values; those clamp to zero so totals stay
    additive.
    """
    if lower is None:
        return CostRecord(key=key, signature=signature, available=False,
                          error="program not lowerable (eager/jit-disabled dispatch path)")
    try:
        compiled = lower()
    except Exception as err:  # noqa: BLE001 — accounting must never break a dispatch
        return CostRecord(key=key, signature=signature, available=False,
                          error=f"lower/compile failed: {err!r}"[:240])
    ca: Dict[str, Any] = {}
    try:
        raw = compiled.cost_analysis()
        if isinstance(raw, (list, tuple)):
            raw = raw[0] if raw else {}
        ca = dict(raw or {})
    except Exception as err:  # noqa: BLE001
        return CostRecord(key=key, signature=signature, available=False,
                          error=f"cost_analysis failed: {err!r}"[:240])
    clamp = lambda v: max(0.0, float(v or 0.0))
    fields: Dict[str, Any] = {
        "flops": clamp(ca.get("flops")),
        "bytes_accessed": clamp(ca.get("bytes accessed")),
        "transcendentals": clamp(ca.get("transcendentals")),
    }
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — memory stats are best-effort per backend
        ma = None
    if ma is not None:
        fields.update(
            argument_bytes=int(getattr(ma, "argument_size_in_bytes", 0) or 0),
            output_bytes=int(getattr(ma, "output_size_in_bytes", 0) or 0),
            temp_bytes=int(getattr(ma, "temp_size_in_bytes", 0) or 0),
            alias_bytes=int(getattr(ma, "alias_size_in_bytes", 0) or 0),
            generated_code_bytes=int(getattr(ma, "generated_code_size_in_bytes", 0) or 0),
        )
    return CostRecord(key=key, signature=signature, available=True, **fields)


class CostRegistry:
    """Thread-safe per-session store of :class:`CostRecord`s, keyed like the
    compile counters: ``ClassName#n.tag`` → signature → record."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._per_key: Dict[str, Dict[str, CostRecord]] = {}

    def harvest(self, key: str, signature: str, lower: Optional[Callable[[], Any]]) -> CostRecord:
        """Harvest and record one program (idempotent per ``(key, signature)``)."""
        with self._lock:
            existing = self._per_key.get(key, {}).get(signature)
        if existing is not None:
            return existing
        record = harvest_compiled(key, signature, lower)
        with self._lock:
            self._per_key.setdefault(key, {})[signature] = record
        return record

    def snapshot(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """``{key: {signature: record_dict}}`` — JSON-friendly, immutable copy."""
        with self._lock:
            return {
                key: {sig: rec.to_dict() for sig, rec in sigs.items()}
                for key, sigs in self._per_key.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._per_key = {}
