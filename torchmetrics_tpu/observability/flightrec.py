"""Crash flight recorder: a bounded black box with an atomic postmortem dump.

The :class:`FlightRecorder` is a telemetry :class:`~.events.Sink` that keeps
the last ``capacity`` events in a ring (O(1) emit — it can sit on the
instrumented dispatch path for the life of a soak) and, on a terminal
condition, writes one self-contained JSON artifact: the recent-event ring,
the causal trace tree linking those events (``trace_id``/``span_id``/
``parent_id`` from ``observability/spans.py``), a counters snapshot, and the
current fleet seating if a :class:`FleetController` is live.

Dump triggers:

- **automatic** (``auto_dump=True``): any ``failover``, ``quarantine`` or
  ``retry_exhausted`` event the ring sees;
- **explicit** (:meth:`FlightRecorder.dump`): the chaos soak calls it on a
  ``StateCorruptionError`` and on unrecovered faults at close-out; any
  harness may call it with its own reason.

Artifact discipline mirrors the SnapshotStore: written to a temp file,
flushed, fsynced, then :func:`os.replace`'d into place — a crash mid-dump
never leaves a torn artifact. Filenames are deterministic
(``flightrec-<reason>-<seq>.json``).

Determinism contract: the ``causal`` and ``counters`` blocks of the artifact
are pure functions of the event stream — timestamps, durations and
wall-clock-measured counters are stripped into the non-contractual
``runtime`` block — so two same-seed soak runs dump byte-identical
contractual blocks (the fleet-soak test pins this).
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Any, Dict, List, Mapping, Optional, Tuple

from .events import Sink, TelemetryEvent

__all__ = ["DUMP_KINDS", "FlightRecorder"]

# event kinds that auto-trigger a dump (terminal/containment moments)
DUMP_KINDS: Tuple[str, ...] = ("failover", "quarantine", "retry_exhausted")

# counters measured in wall-clock (or derived from wall-clock windows) — kept
# out of the contractual block; everything else is seed-deterministic
NONDETERMINISTIC_COUNTERS = frozenset({
    "sync_time_us",
    "aot_deserialize_us",
    "tenant_spill_us",
    "migration_us",
    "async_sync_wait_us",
    "alerts",
    "burn_alerts",  # SLO evaluation (and thus burn paging) rides the real clock
})

# payload keys whose values depend on wall-clock or on-disk encoding details
# (snapshot byte sizes embed wall-clock stats in their JSON header)
_NONDET_PAYLOAD_KEYS = frozenset({"bytes", "delay_s"})


def _contractual_event(event: TelemetryEvent) -> Dict[str, Any]:
    """The deterministic projection of one event (no clocks, no byte sizes)."""
    out = event.to_dict()
    out.pop("timestamp", None)
    out.pop("duration_s", None)
    payload = out.get("payload")
    if payload:
        payload = {k: v for k, v in payload.items() if k not in _NONDET_PAYLOAD_KEYS}
        if payload:
            out["payload"] = payload
        else:
            del out["payload"]
    return out


def build_causal_tree(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Group span-carrying event dicts into per-trace span trees.

    Returns one entry per ``trace_id`` (sorted), each a list of root span
    nodes ``{"span", "parent", "events", "children"}`` — a span whose parent
    never emitted inside the ring becomes a root, so a truncated ring still
    renders a useful (if shallower) tree.
    """
    by_trace: "collections.OrderedDict[str, collections.OrderedDict]" = collections.OrderedDict()
    for ev in events:
        trace_id = ev.get("trace_id")
        span_id = ev.get("span_id")
        if trace_id is None or span_id is None:
            continue
        spans = by_trace.setdefault(trace_id, collections.OrderedDict())
        node = spans.get(span_id)
        if node is None:
            node = {"span": span_id, "parent": ev.get("parent_id"),
                    "events": [], "children": []}
            spans[span_id] = node
        node["events"].append([ev.get("kind"), ev.get("metric"), ev.get("tag")])
    trees: List[Dict[str, Any]] = []
    for trace_id in sorted(by_trace):
        spans = by_trace[trace_id]
        roots: List[Dict[str, Any]] = []
        for node in spans.values():
            parent = node["parent"]
            if parent is not None and parent in spans and spans[parent] is not node:
                spans[parent]["children"].append(node)
            else:
                roots.append(node)
        trees.append({"trace": trace_id, "spans": roots})
    return trees


class FlightRecorder(Sink):
    """Always-cheap bounded event ring + atomic crash-dump artifact."""

    def __init__(self, dump_dir: Optional[str] = None, capacity: int = 512,
                 auto_dump: bool = True) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.dump_dir = str(dump_dir) if dump_dir is not None else None
        self.capacity = capacity
        self.auto_dump = auto_dump
        self._ring: "collections.deque[TelemetryEvent]" = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.dumps: List[Dict[str, Any]] = []  # the artifacts, in dump order

    def emit(self, event: TelemetryEvent) -> None:
        with self._lock:
            self._ring.append(event)
        if self.auto_dump and event.kind in DUMP_KINDS:
            self.dump(event.kind)

    @property
    def events(self) -> Tuple[TelemetryEvent, ...]:
        with self._lock:
            return tuple(self._ring)

    def dump(self, reason: str, extra: Optional[Mapping[str, Any]] = None) -> Dict[str, Any]:
        """Build (and, with ``dump_dir``, atomically write) one artifact."""
        with self._lock:
            ring = list(self._ring)
            self._seq += 1
            seq = self._seq
        contractual = [_contractual_event(e) for e in ring]
        artifact: Dict[str, Any] = {
            "version": 1,
            "reason": str(reason),
            "seq": seq,
            "causal": {
                "events": contractual,
                "tree": build_causal_tree(contractual),
            },
            "counters": {},
            "history": None,
            "runtime": {},
        }
        if extra is not None:
            artifact["extra"] = dict(extra)

        import torchmetrics_tpu.observability as _obs  # late: package imports us

        rec = _obs._ACTIVE
        if rec is not None and not rec._closed:
            counts = dict(rec.counters.snapshot().counts)
            artifact["counters"] = {
                k: v for k, v in counts.items() if k not in NONDETERMINISTIC_COUNTERS
            }
            # contractual like ``causal``/``counters``: the retained level
            # boundaries are byte-identical across same-seed virtual-clock runs
            artifact["history"] = rec.history_block()
            artifact["runtime"] = {
                "counters_wall_clock": {
                    k: counts[k] for k in sorted(NONDETERMINISTIC_COUNTERS) if k in counts
                },
                "latency": rec.latency_summary(),
                "slo": rec.slo_snapshot(),
            }
        artifact["seating"] = self._fleet_seating()

        path = None
        if self.dump_dir is not None:
            path = self._write(artifact, reason, seq)
            artifact["runtime"]["path"] = path
        if rec is not None and not rec._closed:
            rec.counters.record_flightrec_dump()
            rec._event(
                "flightrec", "<flightrec>", str(reason),
                payload={"seq": seq, "events": len(ring),
                         **({"path": os.path.basename(path)} if path else {})},
            )
        self.dumps.append(artifact)
        return artifact

    @staticmethod
    def _fleet_seating() -> Optional[Dict[str, Any]]:
        """Per-host tenant rosters from the live controller, if any."""
        try:
            from torchmetrics_tpu.fleet import controller as _fleet
        except Exception:
            return None
        fc = _fleet.active_controller()
        if fc is None:
            return None
        seating: Dict[str, Any] = {}
        try:
            for host_id, engine in sorted(fc.engines().items()):
                roster = engine.tenants()
                seating[host_id] = {
                    repr(tid): {"resident": info["resident"],
                                "quarantined": info["quarantined"],
                                "updates": info["update_count"]}
                    for tid, info in sorted(roster.items(), key=lambda kv: repr(kv[0]))
                }
        except Exception:  # a half-torn controller must not block the dump
            return None
        return seating

    def _write(self, artifact: Mapping[str, Any], reason: str, seq: int) -> str:
        os.makedirs(self.dump_dir, exist_ok=True)
        safe = "".join(c if (c.isalnum() or c in "-_") else "-" for c in str(reason))[:48]
        path = os.path.join(self.dump_dir, f"flightrec-{safe}-{seq:04d}.json")
        tmp = f"{path}.tmp-{os.getpid()}"
        data = json.dumps(artifact, indent=2, sort_keys=True, default=str)
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(data + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):  # pragma: no cover - only on a failed write
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        return path
