"""Canonical log2-bucket quantile estimation — the ONE copy of the walk.

``histograms.py`` (the runtime estimator), ``tools/trace_report.py`` (the
offline trace renderer) and ``bench.py`` (the driver's probe columns) all
need the same bucket→percentile math; before this module each tool mirrored
it by hand, and the mirrors drifted exactly the way mirrors do. This module
is deliberately free of package-relative imports and anything beyond the
stdlib, so the tools load it by file path
(``importlib.util.spec_from_file_location``) without importing
``torchmetrics_tpu`` — which would initialize jax — while ``histograms.py``
imports it relatively and re-exports the names its callers already use.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence, Tuple, Union

# Bucket b counts values v with 2^b <= v < 2^(b+1) (bucket 0 also absorbs 0).
# 32 buckets cover 1 us .. ~71 minutes for latencies and 1 byte .. 4 GiB for
# per-sync payloads — beyond either end the exact magnitude stops mattering.
N_BUCKETS = 32


def bucket_index(value: int) -> int:
    """Bucket for a non-negative integer value: ``floor(log2(value))`` clamped
    to the table (0 and 1 land in bucket 0; the top bucket is open-ended)."""
    if value < 2:
        return 0
    return min(value.bit_length() - 1, N_BUCKETS - 1)


def bucket_bounds(index: int) -> Tuple[int, int]:
    """``[lower, upper)`` of bucket ``index`` (lower of bucket 0 is 0)."""
    return (0 if index == 0 else 1 << index), 1 << (index + 1)


def percentile_from_buckets(
    buckets: Union[Mapping[int, int], Sequence[int]],
    count: int,
    q: float,
    lo: Optional[int] = None,
    hi: Optional[int] = None,
) -> Optional[float]:
    """Estimate the ``q``-quantile (``0 < q <= 1``) of a log2-bucketed
    distribution by walking the bucket cumulative and interpolating linearly
    inside the target bucket — exact to within the bucket's width (a factor
    of 2, the resolution that distinguishes "p99 moved from 2 ms to 200 ms"
    from noise).

    ``buckets`` is either the dense per-bucket count list a
    :class:`~torchmetrics_tpu.observability.histograms.Histogram` holds or
    the sparse ``{bucket_index: count}`` mapping JSONL ``hist`` payloads
    carry; ``count`` is the total observation count. ``lo``/``hi`` clamp the
    estimate to exactly-observed extrema when the caller knows them (local
    histograms; merged/vector histograms don't, and pass ``None``)."""
    if count <= 0:
        return None
    if isinstance(buckets, Mapping):
        items = sorted((int(b), int(c)) for b, c in buckets.items() if c)
    else:
        items = [(b, int(c)) for b, c in enumerate(buckets) if c]
    if not items:
        return None
    target = q * count
    cum = 0
    est: Optional[float] = None
    for b, c in items:
        if cum + c >= target:
            lower, upper = bucket_bounds(b)
            est = lower + (upper - lower) * (target - cum) / c
            break
        cum += c
    if est is None:  # float rounding pushed target past the last count
        est = float(bucket_bounds(items[-1][0])[1])
    if lo is not None:
        est = max(est, float(lo))
    if hi is not None:
        est = min(est, float(hi))
    return est
