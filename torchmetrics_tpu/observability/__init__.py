"""Observability layer: dispatch tracing, retrace/D2H counters, sync timing,
profiler integration.

Opt-in and zero-overhead when disabled: the runtime's hot paths read one module
attribute (``_ACTIVE``) and take the plain branch when it is ``None`` — no
event objects, no signature hashing, no clock reads (guarded by a test). With a
session active, every jitted dispatch is counted (compile vs cache hit per
``_jit_cache`` key, by input shape/dtype signature), retrace churn trips a
rank-zero sentinel naming the offending shapes, instrumented device→host
readback sites increment a counter the hot loop must keep at zero, and
``process_sync`` reports invocations plus payload bytes. The reliability
layer's retry/quarantine decisions — previously visible only as warnings —
land in the same event stream.

Typical session::

    from torchmetrics_tpu import observability as obs

    with obs.telemetry_session() as rec:            # in-memory ring buffer
        run_eval()
    print(rec.counters.snapshot().summary(brief=True))
    retries = rec.events_of("retry")

    obs.enable(obs.TelemetryConfig(sinks=(obs.JSONLSink("trace.jsonl"),)))
    run_eval()                                      # then: tools/trace_report.py trace.jsonl
    obs.disable()

See ``docs/observability.md`` for the event model, counter semantics, the
xprof workflow, and overhead notes.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..utilities.prints import rank_zero_warn
from . import costs as costs_module
from . import events
from . import flightrec as flightrec_module
from . import histograms as histograms_module
from . import memory as memory_module
from . import slo as slo_module
from . import spans
from . import timeseries as timeseries_module
from . import tracing
from .costs import CostRecord, CostRegistry
from .counters import (
    COUNTER_FIELDS,
    Counters,
    CountersSnapshot,
    FleetSnapshot,
    aggregate_counters,
)
from .events import (
    EVENT_KINDS,
    CallbackSink,
    JSONLSink,
    RingBufferSink,
    Sink,
    TelemetryEvent,
)
from .flightrec import FlightRecorder
from .histograms import (
    FLEET_HISTOGRAM_KINDS,
    Histogram,
    HistogramRegistry,
    aggregate_histograms,
)
from .memory import StateMemoryTracker, state_memory
from .slo import SloEngine, SloRule, default_rules
from .timeseries import TelemetryHistory
from . import export  # noqa: E402 — needs histograms imported first
from .export import HealthServer, MetricsFlusher, render_prometheus

__all__ = [
    "COUNTER_FIELDS",
    "EVENT_KINDS",
    "FLEET_HISTOGRAM_KINDS",
    "CallbackSink",
    "CostRecord",
    "CostRegistry",
    "Counters",
    "CountersSnapshot",
    "FleetSnapshot",
    "FlightRecorder",
    "HealthServer",
    "Histogram",
    "HistogramRegistry",
    "JSONLSink",
    "MetricsFlusher",
    "RingBufferSink",
    "Sink",
    "SloEngine",
    "SloRule",
    "StateMemoryTracker",
    "TelemetryConfig",
    "TelemetryEvent",
    "TelemetryHistory",
    "TelemetryRecorder",
    "active",
    "aggregate_counters",
    "aggregate_histograms",
    "cost_snapshot",
    "default_rules",
    "disable",
    "enable",
    "enabled",
    "export",
    "gather_counters",
    "gather_histograms",
    "render_prometheus",
    "spans",
    "state_memory",
    "telemetry_session",
    "tracing",
]


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Knobs for one telemetry session.

    Args:
        sinks: event sinks. Empty (the default) gets one in-memory
            :class:`RingBufferSink` of ``ring_buffer_size`` so a bare
            ``telemetry_session()`` is already inspectable.
        ring_buffer_size: capacity of that default ring buffer.
        block_until_ready: blocking-timing mode — ``jax.block_until_ready``
            after every dispatch/compute so ``duration_s`` is honest device
            wall-clock instead of async enqueue latency. Serializes the
            pipeline; for attribution runs, never for production loops.
        retrace_warn_threshold: the retrace sentinel fires a rank-zero warning
            when a single metric's dispatch key accumulates MORE than this many
            distinct input shape/dtype signatures (shape-instability recompile
            churn). Warned once per key.
        cost_accounting: harvest FLOPs/HBM cost analysis for every fresh
            compile (an AOT re-lower+compile per new signature — compile-time
            cost only, aval-based, zero device traffic). Disable for sessions
            where even compile time matters.
        track_state_memory: track per-metric state bytes (metadata-only) after
            every instrumented update, keeping peaks and arming the
            unbounded-growth sentinel.
        state_growth_warn_bytes: the growth sentinel rank-zero-warns (once per
            metric/state) when a single list/cat state exceeds this many bytes
            — cat states are the one unbounded growth axis in the runtime and
            the #1 silent OOM cause in long evals.
        slo_rules: declarative health rules (``observability/slo.py``) the
            session evaluates over rolling counter/histogram windows — start
            from :func:`slo.default_rules`. Empty (the default) arms nothing.
        slo_eval_on_sync: evaluate the rules at every recorded sync boundary
            (low-frequency, already collective-shaped — the natural heartbeat
            of a training/eval loop). The export layer's background flusher
            and the health server evaluate on their own cadence regardless.
        history_spans: level spans (seconds) of the session's telemetry
            history (``observability/timeseries.py``) — telescoping retention
            of counter/histogram deltas, fed at the same sync heartbeat the
            SLO window rides, queried via ``history.at(t)`` / ``/historyz``.
            ``None`` disables retention entirely.
        history_keep: per-level closed-block retention caps (defaults to
            tiling the next level + 24 at the top — see
            :class:`~torchmetrics_tpu.streaming.TelescopingFold`).
        history_clock: the history's time source — the determinism seam.
            Soak/fleet runs inject their virtual clock so same-seed runs
            retain byte-identical history blocks; defaults to the monotonic
            clock the event timestamps already use.
    """

    sinks: Tuple[Sink, ...] = ()
    ring_buffer_size: int = 4096
    block_until_ready: bool = False
    retrace_warn_threshold: int = 8
    cost_accounting: bool = True
    track_state_memory: bool = True
    state_growth_warn_bytes: int = 256 * 2**20
    slo_rules: Tuple[SloRule, ...] = ()
    slo_eval_on_sync: bool = True
    history_spans: Optional[Tuple[float, ...]] = timeseries_module.DEFAULT_SPANS
    history_keep: Optional[Tuple[int, ...]] = None
    history_clock: Optional[Any] = None  # Callable[[], float]; Any keeps the dataclass hashable-friendly


class TelemetryRecorder:
    """The live session object: counters registry + event fan-out.

    Runtime code never talks to sinks directly — it calls the ``record_*``
    methods below, which bump counters and construct exactly one event. All
    inputs are host metadata (shapes, dtypes, monotonic clocks, byte counts
    derived from ``.size``/``.itemsize``): recording never reads device memory,
    so an instrumented hot loop stays D2H-free.
    """

    def __init__(self, config: Optional[TelemetryConfig] = None) -> None:
        self.config = config or TelemetryConfig()
        self.counters = Counters()
        self.costs = CostRegistry()
        self.counters.attach_costs(self.costs)  # cost entries ride along in snapshots
        self.memory = StateMemoryTracker(self.config.state_growth_warn_bytes)
        self.histograms = HistogramRegistry()
        self.slo = SloEngine(self.config.slo_rules)
        self.history: Optional[TelemetryHistory] = (
            TelemetryHistory(
                spans=self.config.history_spans,
                keep=self.config.history_keep,
                clock=self.config.history_clock,
            )
            if self.config.history_spans
            else None
        )
        self.sinks: Tuple[Sink, ...] = self.config.sinks or (
            RingBufferSink(self.config.ring_buffer_size),
        )
        self._epoch = next(_SESSION_EPOCHS)
        self._ids = itertools.count()
        self._retrace_warned: set = set()
        self._drift: Dict[str, float] = {}  # last score per DriftMonitor name
        self._drift_warned: set = set()
        self._quant_norm = 0.0  # latest error-feedback residual L2 (gauge)
        self._closed = False

    # ------------------------------------------------------------- identities

    def _metric_name(self, metric: Any) -> str:
        """Stable per-instance identity ``ClassName#n``, assigned on first sight
        within THIS session. The stamp carries the session epoch so a metric
        that outlives its session (or arrives pickled from another process)
        gets a fresh id instead of colliding with an unrelated metric's
        counters. Clones deepcopy the stamp and merge with their origin —
        documented approximation."""
        stamp = metric.__dict__.get("_telemetry_id")
        if not (isinstance(stamp, tuple) and stamp[0] == self._epoch):
            stamp = (self._epoch, next(self._ids))
            metric._telemetry_id = stamp
        return f"{type(metric).__name__}#{stamp[1]}"

    @staticmethod
    def _signature(inputs: Optional[tuple]) -> str:
        """Shape/dtype key of a dispatch's inputs — metadata only, no device
        access. Mirrors what ``jax.jit`` keys its own trace cache on. The
        implementation is shared with the AOT compile cache
        (``aot.keys.dispatch_signature``): counters and cache entries keying
        on the same signature is what makes ``aot_cache_hits`` reconcile
        exactly against ``dispatches``."""
        from ..aot import keys as _aot_keys

        return _aot_keys.dispatch_signature(inputs)

    # ---------------------------------------------------------------- fan-out

    def emit(self, event: TelemetryEvent) -> None:
        for sink in self.sinks:
            sink.emit(event)

    def _event(self, kind: str, metric: str, tag: str, **kw: Any) -> None:
        ctx = spans.current()
        if ctx is not None and "trace_id" not in kw:
            kw["trace_id"] = ctx.trace_id
            kw["span_id"] = ctx.span_id
            kw["parent_id"] = ctx.parent_id
        self.emit(TelemetryEvent(kind=kind, metric=metric, tag=tag, timestamp=tracing.monotonic(), **kw))

    # --------------------------------------------------------- runtime seams

    def finish(self, result: Any, t0: float) -> float:
        """Duration of a span started at ``t0``; blocking-timing mode waits for
        the dispatched work first (honest wall-clock)."""
        if self.config.block_until_ready:
            tracing.block_for_timing(result)
        return tracing.monotonic() - t0

    def record_dispatch(
        self,
        metric: Any,
        tag: str,
        inputs: Optional[tuple],
        duration_s: float,
        lower: Optional[Any] = None,
        aot_loaded: bool = False,
        signature: Optional[str] = None,
    ) -> None:
        """One successful jitted donated dispatch (``update``/``forward``).

        ``lower`` is the cost-accounting hook (``costs.make_lowerer``): a thunk
        that AOT-compiles this dispatch's program from avals. It runs only when
        the signature is fresh — i.e. exactly when the compile counter ticks —
        so the cost registry reconciles 1:1 with ``jit_compiles`` per key.
        ``aot_loaded`` marks a dispatch served by a deserialized executable
        from the AOT cache: a fresh signature then counts as ``aot_cache_hits``
        instead of a compile (the lower thunk for such a dispatch returns the
        loaded executable, so its cost entry still harvests without compiling).
        ``signature`` accepts the plane's precomputed signature so the hot
        path never flattens the same inputs twice.
        """
        name = self._metric_name(metric)
        key = f"{name}.{tag}"
        sig = signature if signature is not None else self._signature(inputs)
        if self.config.cost_accounting and not self.counters.has_signature(key, sig):
            # harvest BEFORE the compile counter ticks: a concurrent snapshot
            # must never see a counted compile without its cost entry
            self.costs.harvest(key, sig, lower)
        is_new, n_compiles = self.counters.record_dispatch(key, sig, aot_loaded=aot_loaded)
        self.histograms.record_duration(tag, name, duration_s)
        self._event(
            "dispatch", name, tag, duration_s=duration_s, signature=sig, cache_hit=not is_new,
            payload={"aot": True} if aot_loaded else {},
        )
        # retrace events/sentinel track actual RECOMPILES (the key's compiles
        # beyond its first), mirroring the retraces counter exactly — an
        # AOT-served fresh signature recompiled nothing, and a service that
        # deliberately precompiled many shapes is warm, not churning
        if is_new and not aot_loaded and n_compiles > 1:
            self._event("retrace", name, tag, signature=sig, payload={"n_compiles": n_compiles})
        if is_new and not aot_loaded and n_compiles > self.config.retrace_warn_threshold and key not in self._retrace_warned:
            self._retrace_warned.add(key)
            shapes = self.counters.signatures(key)
            rank_zero_warn(
                f"Retrace sentinel: {key} has compiled for {n_compiles} distinct input "
                f"shape/dtype signatures (> {self.config.retrace_warn_threshold}) — every new "
                f"signature is a fresh XLA trace+compile. Pad or bucket inputs to a stable "
                f"shape. Signatures seen: {shapes}.",
                UserWarning,
            )

    def record_aot_load(
        self, metric: Any, tag: str, duration_s: float, nbytes: int, key: str, codec: str
    ) -> None:
        """One serialized executable loaded from the AOT compile cache for
        this metric's ``tag`` program (``aot/``): deserialize wall-clock into
        the ``aot_deserialize_us`` counter and the ``aot_load`` histogram
        kind, plus one ``aot_load`` event carrying entry size, codec, and the
        cache entry's content address."""
        import hashlib

        name = self._metric_name(metric)
        self.counters.record_aot_deserialize(duration_s)
        self.histograms.record_duration("aot_load", name, duration_s)
        self._event(
            "aot_load", name, tag, duration_s=duration_s,
            # the entry field is the cache file's content address (prefix),
            # not the raw key — keys are long and carry config reprs
            payload={"nbytes": int(nbytes), "codec": codec,
                     "entry": hashlib.sha256(key.encode("utf-8")).hexdigest()[:16]},
        )

    def record_aot_miss(self) -> None:
        """The AOT plane probed the disk for a first-seen signature and found
        nothing usable — the dispatch fell back to a fresh compile."""
        self.counters.record_aot_miss()

    def record_host_dispatch(self, metric: Any, tag: str, duration_s: float) -> None:
        """A HostMetric eager dispatch (never jitted — no compile/hit split)."""
        name = self._metric_name(metric)
        self.counters.record_host_dispatch()
        self.histograms.record_duration(tag, name, duration_s)
        self._event("dispatch", name, tag, duration_s=duration_s, payload={"jitted": False})

    def record_compute(self, metric: Any, duration_s: float) -> None:
        name = self._metric_name(metric)
        self.counters.record_compute()
        self.histograms.record_duration("compute", name, duration_s)
        self._event("compute", name, "compute", duration_s=duration_s)

    def record_sync(
        self,
        metric: Any,
        duration_s: float,
        payload_bytes: int,
        collectives: int = 0,
        coalesced_leaves: int = 0,
    ) -> None:
        """One ``Metric.sync``/``MetricCollection.sync`` through the sync
        planes (gather/bucket counts and byte totals land in the counters from
        ``parallel/sync.py``; the duration feeds the fleet rollup's straggler
        attribution). ``collectives`` is how many collectives this sync
        launched and ``coalesced_leaves`` how many state leaves rode a
        coalesced bucket — the per-sync view of the K·L → buckets reduction."""
        name = self._metric_name(metric)
        self.counters.record_sync_time(duration_s)
        self.histograms.record_duration("sync", name, duration_s)
        self.histograms.record("sync_payload", name, int(payload_bytes))
        self._event(
            "sync", name, "sync", duration_s=duration_s,
            payload={
                "payload_bytes": int(payload_bytes),
                "collectives": int(collectives),
                "coalesced_leaves": int(coalesced_leaves),
            },
        )
        # sync boundaries are the loop's natural low-frequency heartbeat — the
        # place a rolling SLO window gets fed without touching the update path
        if self.config.slo_eval_on_sync and self.slo.rules:
            self.slo.observe_and_evaluate(self)
        # ... and the telemetry history telescopes the same heartbeat into its
        # multi-resolution retention levels — gated on a new finest block
        # having started, so the vector snapshots are built at most once per
        # block span and the per-sync cost stays a clock compare
        if self.history is not None and self.history.due():
            self.observe_history()

    def record_gather_payload(self, plane: str, nbytes: int) -> None:
        """Size of one sync-plane collective payload (``plane`` is
        ``"coalesced"`` or ``"per_leaf"``) — the distribution that shows
        whether bucketing is actually producing few-large instead of
        many-small collectives. Metadata-derived bytes, never a device read."""
        self.histograms.record("gather_bytes", plane, int(nbytes))

    def record_state_memory(self, metric: Any) -> None:
        """Refresh a metric's state-memory footprint after an update (metadata
        only — shape×itemsize, never a device read). Fires the unbounded-growth
        sentinel the first time a list/cat state crosses the configured
        threshold: one rank-zero warning + one ``state_growth`` event per
        (metric, state)."""
        if not self.config.track_state_memory:
            return
        name = self._metric_name(metric)
        for sname, info in self.memory.observe(name, metric._state):
            self.counters.record_state_growth()
            self._event(
                "state_growth", name, sname,
                payload={"nbytes": info["nbytes"], "elements": info["elements"],
                         "threshold_bytes": self.config.state_growth_warn_bytes},
            )
            rank_zero_warn(
                f"State growth sentinel: {name}.{sname} is a list ('cat') state holding "
                f"{info['nbytes']} bytes across {info['elements']} appended batches "
                f"(> {self.config.state_growth_warn_bytes}). Cat states grow without bound "
                f"until compute() — consider a binned/sufficient-statistic variant, "
                f"compute_on_cpu=True to keep growth off HBM, or periodic compute+reset.",
                UserWarning,
            )

    def record_serve_dispatch(
        self, metric: Any, rows: int, padded: int = 0,
        links: Optional[List[str]] = None,
    ) -> None:
        """One megabatched serving dispatch (``torchmetrics_tpu/serving``):
        ``rows`` real tenant rows updated by a single vmapped program (plus
        ``padded`` scratch rows keeping the dispatch signature fixed). The
        dispatch latency itself was already recorded by :meth:`record_dispatch`
        under the ``vupdate`` tag — this adds the tenant-amortization view the
        derived ``tenants_per_dispatch`` headline reports. ``links`` carries
        the (bounded) trace ids of the seated rows' admission spans — a
        megabatch folds many requests, so the serve event fans IN."""
        name = self._metric_name(metric)
        self.counters.record_serve_dispatch(rows, padded)
        payload: Dict[str, Any] = {"tenant_rows": int(rows), "padded_rows": int(padded)}
        if links:
            payload["links"] = list(links)
        self._event("serve", name, "vupdate", payload=payload)

    def record_tenant_spill(
        self, metric: Any, duration_s: float, nbytes: int, readmit: bool = False
    ) -> None:
        """One LRU spill of a cold tenant's state rows to host memory (or,
        ``readmit=True``, the upload back into a stack slot). Wall-clock lands
        in ``tenant_spill_us`` and the ``tenant_spill`` histogram kind; bytes
        come from array metadata (the spill itself is the D2H — accounted
        separately via :meth:`record_d2h` at the call site)."""
        name = self._metric_name(metric)
        self.counters.record_tenant_spill(duration_s, readmit=readmit)
        self.histograms.record_duration("tenant_spill", name, duration_s)
        self._event(
            "tenant_spill", name, "readmit" if readmit else "spill",
            duration_s=duration_s, payload={"nbytes": int(nbytes)},
        )

    def record_window_roll(self, metric: Any, window: int, filled: int, wrapped: bool,
                           tier: str = "ring", rotated: bool = False) -> None:
        """One SlidingWindow update (streaming plane). The ``window_rolls``
        counter ticks on every update and ``window_rotations`` on every dual
        block rotation / two-stack pane completion (``rotated``); the
        ``window_roll`` EVENT fires only when the window wrapped (a full
        window of updates completed) so the stream stays low-rate — the
        per-update dispatch latency already rides the ``wupdate``/``wdual``/
        ``wstack`` dispatch events/histograms."""
        name = self._metric_name(metric)
        self.counters.record_window_roll(rotated=rotated)
        if wrapped:
            self._event(
                "window_roll", name,
                {"ring": "wupdate", "dual": "wdual", "two_stack": "wstack"}.get(tier, "wupdate"),
                payload={"window": int(window), "filled": int(filled), "tier": tier},
            )

    def record_async_sync(
        self,
        label: str,
        gather_s: float,
        wait_s: float,
        payload_bytes: int,
        collectives: int = 0,
        fallback: bool = False,
    ) -> None:
        """One committed double-buffered background sync
        (``parallel.AsyncSyncHandle``). ``gather_s`` is the gather's full
        wall-clock (what a blocking sync would have cost the caller — it
        feeds ``sync_time_us`` and the ``sync`` histogram like any sync);
        ``wait_s`` is how long ``commit()`` actually blocked. The difference,
        reported as ``overlap_pct``, is the sync latency the overlap hid —
        the direct observable of the double-buffered plane."""
        self.counters.record_async_sync(wait_s)
        self.counters.record_sync_time(gather_s)
        self.histograms.record_duration("sync", label, gather_s)
        overlap = max(0.0, 1.0 - (wait_s / gather_s)) * 100.0 if gather_s > 0 else 0.0
        self._event(
            "async_sync", label, "sync", duration_s=gather_s,
            payload={
                "wait_s": round(wait_s, 6),
                "overlap_pct": round(overlap, 2),
                "payload_bytes": int(payload_bytes),
                "collectives": int(collectives),
                "fallback": bool(fallback),
            },
        )

    def record_drift(
        self,
        name: str,
        score: float,
        breached: bool,
        threshold: float,
        severity: str = "warning",
    ) -> None:
        """One DriftMonitor evaluation. The latest score lands in the SLO
        expression namespace as ``drift(name)``; a breach additionally rides
        the ``alert`` event kind (plus the ``alerts`` counter and a once-per-
        name rank-zero warning), exactly like an SLO rule breach — drift IS a
        health signal, so it shares the alerting channel."""
        self.counters.record_drift(breached)
        self._drift[name] = float(score)
        if not breached:
            return
        self.counters.record_alert()
        self._event(
            "alert", name, "drift",
            payload={
                "kind": "drift",
                "severity": severity,
                "score": round(float(score), 6),
                "threshold": float(threshold),
            },
        )
        if name not in self._drift_warned:
            self._drift_warned.add(name)
            rank_zero_warn(
                f"Drift breach [{severity}] {name}: score {float(score):.6g} over threshold "
                f"{float(threshold):.6g} (test window vs reference window diverged).",
                UserWarning,
            )

    def drift_score(self, name: str) -> float:
        """Latest score a DriftMonitor recorded under ``name`` (0.0 when none
        ran) — the value the SLO namespace's ``drift(name)`` reads."""
        return self._drift.get(name, 0.0)

    def drift_scores(self) -> Dict[str, float]:
        return dict(self._drift)

    def record_quant(
        self,
        label: str,
        codec: str,
        buckets: int,
        leaves: int,
        raw_bytes: int,
        shipped_bytes: int,
        feedback_norm: float = 0.0,
    ) -> None:
        """One coalesced sync that shipped quantized buckets
        (``parallel/quantize.py``). ``raw_bytes`` is what the exact plane
        would have put on the wire for those buckets, ``shipped_bytes`` what
        the codec actually shipped (scale metadata included); the difference
        feeds the ``sync_bytes_saved`` counter and the per-event compression
        ratio ``tools/trace_report.py`` renders. ``feedback_norm`` is the
        residual store's L2 after the sync — the ``quant_error_feedback_norm``
        gauge (``quant_feedback_norm`` in the SLO namespace): a norm that
        climbs sync over sync means the codec is too coarse for the data."""
        self.counters.record_quant(buckets, raw_bytes - shipped_bytes)
        self._quant_norm = float(feedback_norm)
        ratio = (raw_bytes / shipped_bytes) if shipped_bytes > 0 else 0.0
        self._event(
            "quant", label, codec,
            payload={
                "buckets": int(buckets),
                "leaves": int(leaves),
                "raw_bytes": int(raw_bytes),
                "shipped_bytes": int(shipped_bytes),
                "bytes_saved": int(raw_bytes - shipped_bytes),
                "compression_x": round(ratio, 3),
                "feedback_norm": round(float(feedback_norm), 9),
            },
        )

    def quant_feedback_norm(self) -> float:
        """Latest ``quant_error_feedback_norm`` gauge value (0.0 before any
        quantized sync) — the SLO namespace exposes it by the same name."""
        return self._quant_norm

    def record_serve_rejected(self, metric: Any, tenant_id: Any) -> None:
        """One tenant batch shed by the serving admission rate limit — the
        overload signal an autoscaler watches instead of LRU-spill churn."""
        name = self._metric_name(metric)
        self.counters.record_serve_rejected()
        self._event(
            "serve_rejected", name, "admission",
            payload={"tenant": repr(tenant_id)[:80]},
        )

    def record_snapshot(
        self, metric: Any, op: str, duration_s: float, nbytes: int, generation: int
    ) -> None:
        """One durability-plane snapshot ``op`` (``"write"`` or
        ``"restore"``) of a serving engine: the whole-fleet state landed in
        (or loaded from) one content-addressed generation."""
        name = self._metric_name(metric)
        self.counters.record_snapshot(restore=(op == "restore"))
        self._event(
            "snapshot", name, op,
            duration_s=duration_s,
            payload={"bytes": int(nbytes), "generation": int(generation)},
        )

    def record_journal_replay(self, metric: Any, records: int, duration_s: float) -> None:
        """``records`` write-ahead journal entries rolled forward into a
        restored engine (the failover tail between the snapshot point and the
        crash)."""
        name = self._metric_name(metric)
        self.counters.record_journal_replay(records)
        self._event(
            "journal", name, "replay",
            duration_s=duration_s,
            payload={"records": int(records)},
        )

    def record_degraded_sync(self, label: str, dead: Any, world: int) -> None:
        """One coalesced sync that completed over a survivor quorum: the
        ranks in ``dead`` presented tombstone metadata rows, the fold covered
        the survivors, and the sync is marked degraded instead of hanging."""
        self.counters.record_degraded_sync()
        self._event(
            "degraded_sync", label, "quorum",
            payload={"dead": [int(r) for r in dead], "world": int(world)},
        )

    def record_rank_rejoin(self, label: str, rank: int, epoch: int) -> None:
        """A previously dead rank presented a live metadata row again — its
        accumulated contribution folds back in on this sync (full-state
        gather: reconciliation without double counting)."""
        self.counters.record_rank_rejoin()
        self._event(
            "rank_rejoin", label, "rejoin",
            payload={"rank": int(rank), "epoch": int(epoch)},
        )

    def record_fleet_heartbeat(self, host: str) -> None:
        """One member-host lease renewal (fleet plane). Counter-only — a
        heartbeat per host per step would swamp the event stream."""
        self.counters.record_fleet_heartbeat()

    def record_lease_expiry(self, host: str) -> None:
        """One host lease past ``dead_after`` — the suspect → dead transition
        the failover path keys off."""
        self.counters.record_lease_expiry()

    def record_migration(
        self, label: str, src: str, dst: str, tenants: int, duration_s: float
    ) -> None:
        """One COMMITTED migration: ``tenants`` drained on ``src``,
        snapshot-sliced, transferred, restored on ``dst`` and cut over."""
        self.counters.record_migration(tenants, int(duration_s * 1e6))
        self._event(
            "migration", label, "commit",
            duration_s=duration_s,
            payload={"src": str(src), "dst": str(dst), "tenants": int(tenants)},
        )

    def record_host_failover(
        self, label: str, host: str, tenants: int, replayed: int, rpo_records: int,
        roster: Optional[List[str]] = None,
    ) -> None:
        """One dead host's roster adopted by survivors: restored from its
        latest snapshot generation plus ``replayed`` journal-tail records,
        with ``rpo_records`` admissions unrecoverable (the fsync window).
        ``roster`` names the adopted tenants (bounded repr list) so a flight-
        recorder dump identifies the dead host's in-flight sessions."""
        self.counters.record_host_failover()
        payload: Dict[str, Any] = {
            "host": str(host), "tenants": int(tenants),
            "replayed": int(replayed), "rpo_records": int(rpo_records),
        }
        if roster:
            payload["roster"] = [str(t)[:80] for t in roster[:32]]
        self._event("failover", label, "adopt", payload=payload)

    def record_d2h(self, site: str, nbytes: int, metric: Any = None) -> None:
        """An instrumented device→host readback (``state_dict``,
        ``compute_on_cpu`` appends, finiteness guards). The hot loop's
        contract is that this counter stays at zero."""
        self.counters.record_d2h(nbytes)
        name = self._metric_name(metric) if metric is not None else ""
        self._event("d2h", name, site, payload={"nbytes": int(nbytes)})

    def record_retry(
        self, describe: str, attempt: int, exc: BaseException, delay_s: float = 0.0
    ) -> None:
        self.counters.record_retry()
        self.histograms.record_duration("retry_backoff", describe, delay_s)
        self._event(
            "retry", describe, "retry",
            payload={"attempt": attempt, "error": repr(exc)[:240], "delay_s": round(delay_s, 6)},
        )

    def record_retry_exhausted(self, describe: str, attempts: int, exc: BaseException) -> None:
        self.counters.record_retry_exhausted()
        self._event(
            "retry_exhausted", describe, "retry",
            payload={"attempts": attempts, "error": repr(exc)[:240]},
        )

    def record_quarantine(self, name: str, stage: str, status: str, exc: BaseException, update_count: int) -> None:
        self.counters.record_quarantine(status)
        self._event(
            "quarantine", name, stage,
            payload={"status": status, "error": repr(exc)[:240], "update_count": update_count},
        )

    # -------------------------------------------------------------- inspection

    def metric_summary(self, metric: Any) -> Dict[str, Any]:
        """Per-tag dispatch accounting for one metric instance."""
        stamp = metric.__dict__.get("_telemetry_id")
        if not (isinstance(stamp, tuple) and stamp[0] == self._epoch):
            return {"dispatches": 0, "tags": {}}
        prefix = f"{type(metric).__name__}#{stamp[1]}."
        tags: Dict[str, Any] = {}
        total = 0
        for key, rec in self.counters.keys_for(prefix).items():
            tag = key[len(prefix):]
            aot_hits = rec.get("aot_hits", 0)
            n = rec["compiles"] + rec["cache_hits"] + aot_hits
            total += n
            tags[tag] = {
                "dispatches": n,
                "compiles": rec["compiles"],
                "cache_hits": rec["cache_hits"],
                "aot_hits": aot_hits,
                "retraces": max(0, rec["compiles"] - 1),
                "signatures": rec["signatures"],
            }
        return {"dispatches": total, "tags": tags}

    def cost_snapshot(self) -> Dict[str, Any]:
        """Per-dispatch-key compiled costs: ``{key: {signature: record}}``.
        Reconciles with the compile counters — every key counted as a compile
        has an entry (placeholders mark unavailable analysis)."""
        return self.costs.snapshot()

    def cost_summary(self) -> Dict[str, Any]:
        """Dispatch-weighted run cost totals (``run_flops`` etc.) — the flat
        block bench configs embed next to the brief counters."""
        return self.counters.snapshot().cost_totals()

    def memory_snapshot(self) -> Dict[str, Any]:
        """Per-metric state-memory report: current and peak bytes, per-state
        breakdown, per-state peaks."""
        return self.memory.snapshot()

    def histogram_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-(kind, key) latency/size distributions as flat report blocks:
        ``{kind: {key: {count, sum, mean, p50, p95, p99, p999, buckets}}}``.
        Latency kinds are microseconds; size kinds
        (:data:`histograms.SIZE_KINDS`) are bytes."""
        return {
            kind: {key: hist.summary() for key, hist in keys.items()}
            for kind, keys in self.histograms.snapshot().items()
        }

    def latency_summary(self) -> Dict[str, Any]:
        """Per-kind percentile headline merged across all keys — the block the
        full ``summary()`` and the bench columns embed (``*_us`` for latency
        kinds, ``*_bytes`` for size kinds)."""
        out: Dict[str, Any] = {}
        for kind, hist in self.histograms.kind_totals().items():
            unit = "bytes" if kind in histograms_module.SIZE_KINDS else "us"
            block: Dict[str, Any] = {"count": hist.count}
            for name, est in hist.percentiles().items():
                block[f"{name}_{unit}"] = round(est, 1) if est is not None else None
            out[kind] = block
        return out

    def metric_latency(self, metric: Any) -> Dict[str, Any]:
        """One metric's per-stage latency percentiles (``update``/``forward``/
        ``compute``/``sync`` — whichever this session recorded), for
        ``MetricCollection.telemetry_summary()``'s per-member attribution."""
        stamp = metric.__dict__.get("_telemetry_id")
        if not (isinstance(stamp, tuple) and stamp[0] == self._epoch):
            return {}
        name = f"{type(metric).__name__}#{stamp[1]}"
        out: Dict[str, Any] = {}
        for kind in ("update", "forward", "compute", "sync", "aot_load", "wupdate",
                     "wdual", "wstack", "dupdate", "vupdate", "vwupdate"):
            hist = self.histograms.get(kind, name)
            if hist is None or not hist.count:
                continue
            pct = hist.percentiles()
            out[kind] = {
                "count": hist.count,
                "p50_us": round(pct["p50"], 1) if pct["p50"] is not None else None,
                "p99_us": round(pct["p99"], 1) if pct["p99"] is not None else None,
            }
        return out

    def observe_history(self, now: float = None) -> int:
        """Feed one counter/histogram snapshot into the session's telescoping
        telemetry history (no-op when ``history_spans`` disabled retention).
        Returns the number of blocks the feed closed; each closure bumps the
        ``history_folds`` counter and emits one ``history`` event so the fold
        cadence itself is observable."""
        if self.history is None:
            return 0
        folds = self.history.observe(
            self.counters.counts_vector(),
            self.histograms.fleet_vector(),
            now=now,
        )
        if folds:
            self.counters.record_history_folds(folds)
            self._event(
                "history",
                "telemetry",
                "fold",
                payload={"folds": folds, "blocks": self.history.block_count()},
            )
        return folds

    def history_block(self, last_n: int = 8) -> Optional[Dict[str, Any]]:
        """The deterministic history export: last ``last_n`` retained block
        boundaries per level, wall-clock-tainted counters dropped — the block
        a flight-recorder dump and a ``SoakReport`` carry contractually
        (byte-identical across same-seed virtual-clock runs)."""
        if self.history is None:
            return None
        return self.history.export_block(
            last_n=last_n, drop=flightrec_module.NONDETERMINISTIC_COUNTERS
        )

    def evaluate_slos(self, now: float = None) -> list:
        """Evaluate the session's SLO rules right now (the health server and
        the export flusher call this on their own cadence; sync boundaries do
        it automatically under ``slo_eval_on_sync``). Returns alerts emitted
        by this evaluation."""
        return self.slo.observe_and_evaluate(self, now=now)

    def slo_snapshot(self) -> Dict[str, Any]:
        return self.slo.snapshot()

    def summary(
        self,
        brief: bool = False,
        fleet: bool = False,
        process_group: Any = None,
        dist_sync_fn: Any = None,
    ) -> Dict[str, Any]:
        """Session summary. ``fleet=True`` gathers every rank's counters
        through the metadata gather plane and returns pod-wide totals plus
        straggler attribution; the local summary rides along under
        ``"local"``. Local-only otherwise."""
        snap = self.counters.snapshot()
        if not fleet:
            out = snap.summary(brief=brief)
            if not brief:
                out["latency"] = self.latency_summary()
            return out
        fleet_snap = gather_counters(snap, process_group=process_group, dist_sync_fn=dist_sync_fn)
        out = fleet_snap.summary(brief=brief)
        out["local"] = snap.summary(brief=True)
        return out

    @property
    def events(self) -> Tuple[TelemetryEvent, ...]:
        """Events from the session's first ring-buffer sink (empty tuple when
        only external sinks are configured)."""
        for sink in self.sinks:
            if isinstance(sink, RingBufferSink):
                return sink.events
        return ()

    def events_of(self, *kinds: str) -> Tuple[TelemetryEvent, ...]:
        return tuple(e for e in self.events if e.kind in kinds)

    def close(self) -> None:
        if self._closed:  # idempotent: a replaced-then-disabled session must
            return        # not flush its histograms into the sinks twice
        self._closed = True
        # fold the session's final counter state into the history so the last
        # partial block is retained before the sinks stop listening
        if self.history is not None:
            self.observe_history()
        # flush the final histogram state into the event stream before the
        # sinks close: one ``hist`` event per (kind, key), so a JSONL trace
        # carries the latency distributions ``tools/trace_report.py`` renders
        # as percentile columns (bucket counts ride sparse — mostly zeros)
        for kind, keys in self.histograms.snapshot().items():
            for key, hist in keys.items():
                if hist.count:
                    self._event("hist", key, kind, payload=hist.summary())
        for sink in self.sinks:
            sink.close()


# Session epochs make metric identity stamps self-invalidating across sessions
# (a stale stamp from a dead session or an unpickled metric never collides).
_SESSION_EPOCHS = itertools.count()

# The one module attribute the hot paths read. ``None`` == disabled: the
# dispatch path takes a single pointer-compare branch and does nothing else.
_ACTIVE: Optional[TelemetryRecorder] = None


def active() -> Optional[TelemetryRecorder]:
    """The currently active recorder, or ``None`` when telemetry is disabled."""
    return _ACTIVE


def enabled() -> bool:
    return _ACTIVE is not None


def enable(config: Optional[TelemetryConfig] = None) -> TelemetryRecorder:
    """Start a process-wide telemetry session (replaces any active one)."""
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.close()
    _ACTIVE = TelemetryRecorder(config)
    return _ACTIVE


def disable() -> Optional[TelemetryRecorder]:
    """End the session; returns the (closed) recorder for post-hoc inspection."""
    global _ACTIVE
    rec, _ACTIVE = _ACTIVE, None
    if rec is not None:
        rec.close()
    return rec


def cost_snapshot() -> Dict[str, Any]:
    """The active session's per-key compiled costs (empty when disabled)."""
    return _ACTIVE.cost_snapshot() if _ACTIVE is not None else {}


def gather_counters(
    snapshot: Optional[CountersSnapshot] = None,
    process_group: Any = None,
    dist_sync_fn: Any = None,
    prefer_sync_rows: bool = True,
) -> FleetSnapshot:
    """Gather this process's counters across all ranks and merge them.

    The payload is metadata-sized — one int64 vector of :data:`COUNTER_FIELDS`
    per rank — and rides the coalesced gather plane. When a coalesced sync
    already ran under the active session, its metadata collective carried
    every rank's counter vector, so this rollup reuses those rows and launches
    **zero extra collectives** (the local row is refreshed from ``snapshot``;
    remote rows are as of each rank's last sync — pass
    ``prefer_sync_rows=False`` to force a fresh collective). Otherwise one
    ``gather_metadata_vector`` collective runs (``dist_sync_fn`` is the usual
    injection seam and always bypasses the cached rows). With one process (or
    no snapshot source) this degrades to a single-rank fleet view. Remote
    ranks contribute counts only; per-key dispatch records stay local (strings
    don't ride the array gather), so the merged ``per_key`` covers this rank
    alone.
    """
    if snapshot is None:
        if _ACTIVE is None:
            raise RuntimeError("gather_counters needs an active telemetry session or an explicit snapshot")
        snapshot = _ACTIVE.counters.snapshot()
    from ..parallel import coalesce as _coalesce  # lazy: parallel imports this module
    from ..parallel import sync as _sync

    rows: Any = None
    my_rank: Optional[int] = None
    # cached rows describe the LAST sync's whole-world metadata collective: an
    # explicit process_group (a different scope) or injected gather always
    # forces a fresh collective
    if prefer_sync_rows and dist_sync_fn is None and process_group is None:
        cached = _coalesce.fleet_counter_rows()
        if cached is not None:
            rows, my_rank = cached
    if rows is None:
        rows = _sync.gather_metadata_vector(
            snapshot.counts_vector(), process_group=process_group, dist_sync_fn=dist_sync_fn
        )
        for i, row in enumerate(rows):  # re-attach local per-key records to our own row
            if row == snapshot.counts_vector() and my_rank is None:
                my_rank = i
    ranks: list = list(rows)
    if my_rank is not None and 0 <= my_rank < len(ranks):
        ranks[my_rank] = snapshot
    return aggregate_counters(ranks)


def gather_histograms(
    vector: Optional[list] = None,
    process_group: Any = None,
    dist_sync_fn: Any = None,
    prefer_sync_rows: bool = True,
) -> Dict[str, Histogram]:
    """Merge every rank's per-kind latency/size histograms into fleet
    distributions (``{kind: Histogram}`` — p99 across the POD, not per host).

    Same transport contract as :func:`gather_counters`: the payload is one int
    vector of :data:`histograms.FLEET_VECTOR_LEN` entries per rank (fieldwise
    sum IS the exact merge), and a coalesced sync under the active session
    already shipped every rank's vector inside its metadata collective — this
    rollup reuses those rows and launches **zero extra collectives** (local
    row refreshed live; remote rows as of each rank's last sync; pass
    ``prefer_sync_rows=False`` to force a fresh ``gather_metadata_vector``
    collective). Per-key histograms stay local, like per-key dispatch records.
    """
    if vector is None:
        if _ACTIVE is None:
            raise RuntimeError("gather_histograms needs an active telemetry session or an explicit vector")
        vector = _ACTIVE.histograms.fleet_vector()
    from ..parallel import coalesce as _coalesce  # lazy: parallel imports this module
    from ..parallel import sync as _sync

    rows: Any = None
    my_rank: Optional[int] = None
    if prefer_sync_rows and dist_sync_fn is None and process_group is None:
        cached = _coalesce.fleet_histogram_rows()
        if cached is not None:
            rows, my_rank = cached
    if rows is None:
        rows = _sync.gather_metadata_vector(
            vector, process_group=process_group, dist_sync_fn=dist_sync_fn
        )
    else:
        rows = list(rows)
        if my_rank is not None and 0 <= my_rank < len(rows):
            rows[my_rank] = vector  # local row refreshed from the live registry
    return aggregate_histograms(rows)


@contextlib.contextmanager
def telemetry_session(config: Optional[TelemetryConfig] = None) -> Iterator[TelemetryRecorder]:
    """``with telemetry_session() as rec: ...`` — enable for the block, always
    disable after (the recorder stays readable)."""
    rec = enable(config)
    try:
        yield rec
    finally:
        if _ACTIVE is rec:
            disable()
        else:  # a nested enable() replaced us — don't kill the newer session
            rec.close()
