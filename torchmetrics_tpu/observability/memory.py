"""State-memory accounting from array metadata — zero device traffic.

Every number here comes from ``shape``/``dtype``/``size`` attributes (jax
arrays, numpy arrays, and anything array-like expose them without a device
read), so ``Metric.state_memory()`` is safe inside a
``jax.transfer_guard_device_to_host("disallow")`` block and inside a hot loop.

Two consumers:

- :func:`state_memory` — a point-in-time per-state byte report, the body of
  ``Metric.state_memory()`` / ``MetricCollection.state_memory()``.
- :class:`StateMemoryTracker` — owned by the telemetry recorder: tracks the
  peak state footprint per metric across updates and fires the
  unbounded-growth sentinel when a list ("cat") state crosses a configurable
  byte threshold. Cat states are host-appended per batch and concatenated only
  at compute, which makes them the #1 silent OOM in long evals — nothing else
  in the runtime grows without bound.

Stdlib-only (no jax import): the bench driver and offline tooling can read
reports without initializing a runtime.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple


def leaf_nbytes(leaf: Any) -> int:
    """Bytes held by one array-like leaf, from metadata only (0 for non-arrays)."""
    size = getattr(leaf, "size", None)
    dtype = getattr(leaf, "dtype", None)
    itemsize = getattr(dtype, "itemsize", None)
    if size is None or itemsize is None:
        return 0
    return int(size) * int(itemsize)


def _tensor_info(value: Any) -> Dict[str, Any]:
    return {
        "kind": "tensor",
        "nbytes": leaf_nbytes(value),
        "shape": tuple(getattr(value, "shape", ()) or ()),
        "dtype": str(getattr(value, "dtype", "")),
    }


def state_memory(state: Mapping[str, Any]) -> Dict[str, Any]:
    """Per-state byte accounting for one metric's state dict.

    Returns ``{"states": {name: info}, "total_bytes": int}`` where tensor
    states carry ``shape``/``dtype`` and list (cat) states carry ``elements``
    — the growth axis the sentinel watches.
    """
    states: Dict[str, Any] = {}
    total = 0
    for name, value in state.items():
        if isinstance(value, list):
            nbytes = sum(leaf_nbytes(x) for x in value)
            info: Dict[str, Any] = {"kind": "list", "nbytes": nbytes, "elements": len(value)}
        else:
            info = _tensor_info(value)
        states[name] = info
        total += info["nbytes"]
    return {"states": states, "total_bytes": total}


class StateMemoryTracker:
    """Peak-footprint tracking + the unbounded-growth sentinel (one per session).

    ``observe(name, state)`` is called by the recorder after every instrumented
    update/forward; it returns the list states that crossed ``warn_bytes`` for
    the FIRST time (the recorder turns those into events + a rank-zero warning
    — this module stays stdlib and side-effect-free).
    """

    def __init__(self, warn_bytes: int) -> None:
        self.warn_bytes = int(warn_bytes)
        self._current: Dict[str, Dict[str, Any]] = {}
        self._peak: Dict[str, int] = {}
        self._peak_per_state: Dict[str, Dict[str, int]] = {}
        self._warned: set = set()
        # name -> state -> (list_id, elements_summed, nbytes): list states are
        # append-only between resets, so re-summing only the tail keeps a
        # per-update observation O(new elements) instead of O(all elements) —
        # a 100k-batch cat-state eval must not go quadratic in its own telemetry
        self._list_cache: Dict[str, Dict[str, Tuple[int, int, int]]] = {}

    def _report(self, name: str, state: Mapping[str, Any]) -> Dict[str, Any]:
        cache = self._list_cache.setdefault(name, {})
        states: Dict[str, Any] = {}
        total = 0
        for sname, value in state.items():
            if isinstance(value, list):
                n = len(value)
                cached = cache.get(sname)
                if cached is not None and cached[0] == id(value) and cached[1] <= n:
                    nbytes = cached[2] + sum(leaf_nbytes(x) for x in value[cached[1]:])
                else:  # fresh/replaced/shrunk list (reset): full re-sum
                    nbytes = sum(leaf_nbytes(x) for x in value)
                cache[sname] = (id(value), n, nbytes)
                info: Dict[str, Any] = {"kind": "list", "nbytes": nbytes, "elements": n}
            else:
                info = _tensor_info(value)
            states[sname] = info
            total += info["nbytes"]
        return {"states": states, "total_bytes": total}

    def observe(self, name: str, state: Mapping[str, Any]) -> Tuple[Tuple[str, Dict[str, Any]], ...]:
        report = self._report(name, state)
        self._current[name] = report
        total = report["total_bytes"]
        if total > self._peak.get(name, -1):
            self._peak[name] = total
        peaks = self._peak_per_state.setdefault(name, {})
        crossings = []
        for sname, info in report["states"].items():
            if info["nbytes"] > peaks.get(sname, -1):
                peaks[sname] = info["nbytes"]
            if info["kind"] != "list" or info["nbytes"] <= self.warn_bytes:
                continue
            wkey = (name, sname)
            if wkey in self._warned:
                continue
            self._warned.add(wkey)
            crossings.append((sname, info))
        return tuple(crossings)

    def snapshot(self) -> Dict[str, Any]:
        """``{metric: {current_bytes, peak_bytes, states, per_state_peak}}``."""
        return {
            name: {
                "current_bytes": report["total_bytes"],
                "peak_bytes": self._peak.get(name, report["total_bytes"]),
                "states": {k: dict(v) for k, v in report["states"].items()},
                "per_state_peak": dict(self._peak_per_state.get(name, {})),
            }
            for name, report in self._current.items()
        }
