"""Mergeable log2-bucketed latency/size histograms.

Counters say *how often*; costs say *what it should cost*; histograms say *how
long it actually took* — as a distribution, because at fleet scale the tail IS
the story (straggler and tail-latency effects dominate pjit/TPUv4-scale runs;
a mean hides the one rank holding the barrier). The design constraints:

- **O(1) record, no allocation growth.** A histogram is a fixed vector of
  :data:`N_BUCKETS` integer counts; bucket ``b`` spans ``[2^b, 2^(b+1))`` in
  the histogram's unit (microseconds for latencies, bytes for sizes). Values
  are host-side metadata (monotonic-clock spans, ``size×itemsize`` bytes) —
  recording never touches device memory, exactly like the counters.
- **Merge == fieldwise integer sum.** Bucket counts, total count, and value
  sum are all plain integers, so a fleet rollup is the exact elementwise sum
  of per-rank vectors — the DrJAX-style integer-vector reduction, and the same
  contract the counter rollup already rides (:func:`merge_vectors`). No
  sketch, no approximation in the merge itself; only the bucket resolution is
  approximate (a quantile estimate is exact to within its bucket, i.e. a
  factor of 2 — tight enough to see a p99 move from 2 ms to 200 ms, which is
  the operational question).
- **Fixed fleet layout.** Per-key histograms stay local (string keys don't
  ride int collectives — same rule as per-key dispatch records); the fleet
  plane ships one int vector of the per-kind totals in
  :data:`FLEET_HISTOGRAM_KINDS` order, small enough to piggyback on the
  coalesced sync's metadata collective (``parallel/coalesce.py``).

Stdlib-only (no jax import). The bucket table and the quantile walk live in
``quantile.py`` (re-exported here): the ONE canonical estimator, which
``tools/trace_report.py`` and the bench driver load by file path instead of
mirroring the math by hand.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .quantile import N_BUCKETS, bucket_bounds, bucket_index, percentile_from_buckets

# The kinds whose per-kind totals ride the fleet plane, in vector order. The
# first nine are latency histograms (microseconds); the last two are size
# histograms (bytes). Fixed across ranks by construction — the fleet vector
# needs no key exchange. (Growing this tuple changes the piggyback layout:
# bump parallel/coalesce._VERSION — the streaming "wupdate" addition rode the
# v5 bump, the tiered-window "wdual"/"wstack" additions the v6 bump, each
# together with the counter-vector growth.)
FLEET_HISTOGRAM_KINDS: Tuple[str, ...] = (
    "update",        # jitted/host update dispatch latency
    "forward",       # forward dispatch latency
    "compute",       # Metric.compute latency
    "sync",          # Metric.sync / MetricCollection.sync wall-clock
    "retry_backoff", # backoff delay accepted before a transient retry
    "aot_load",      # serialized-executable load latency (aot compile cache)
    "wupdate",       # SlidingWindow ring-roll dispatch latency (streaming plane)
    "wdual",         # dual-pair window dispatch latency (tiered windows)
    "wstack",        # two-stack window dispatch latency (tiered windows)
    "sync_payload",  # bytes a process contributed to one sync
    "gather_bytes",  # bytes of one sync-plane collective payload
)

# kinds measured in bytes (everything else is microseconds)
SIZE_KINDS: Tuple[str, ...] = ("sync_payload", "gather_bytes")

# per-kind section: [count, value_sum, bucket_0 .. bucket_{N-1}]
_KIND_VEC_LEN = 2 + N_BUCKETS
# the whole fleet payload: one section per kind in FLEET_HISTOGRAM_KINDS order
FLEET_VECTOR_LEN = len(FLEET_HISTOGRAM_KINDS) * _KIND_VEC_LEN

# estimation quantiles the reports surface, in reporting order
PERCENTILES: Tuple[Tuple[str, float], ...] = (
    ("p50", 0.50),
    ("p95", 0.95),
    ("p99", 0.99),
    ("p999", 0.999),
)


class Histogram:
    """One mergeable log2 histogram (fixed buckets + count + value sum).

    ``lo``/``hi`` track the exact observed extrema locally — they sharpen
    percentile estimates but do NOT ride the fleet vector (min/max cannot
    merge by summation; a merged histogram estimates from buckets alone).
    """

    __slots__ = ("counts", "count", "total", "lo", "hi")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * N_BUCKETS
        self.count = 0
        self.total = 0
        self.lo: Optional[int] = None
        self.hi: Optional[int] = None

    def record(self, value: int) -> None:
        v = int(value)
        if v < 0:
            v = 0
        self.counts[bucket_index(v)] += 1
        self.count += 1
        self.total += v
        if self.lo is None or v < self.lo:
            self.lo = v
        if self.hi is None or v > self.hi:
            self.hi = v

    # ------------------------------------------------------------------ math

    def percentile(self, q: float) -> Optional[float]:
        """Estimate the ``q``-quantile (``0 < q <= 1``) via the shared
        log2-bucket walk (:func:`~torchmetrics_tpu.observability.quantile.
        percentile_from_buckets`). Exact to within the bucket's width;
        clamped to the observed ``[lo, hi]`` when the exact extrema are
        known (local histograms)."""
        return percentile_from_buckets(self.counts, self.count, q, lo=self.lo, hi=self.hi)

    def percentiles(self) -> Dict[str, Optional[float]]:
        return {name: self.percentile(q) for name, q in PERCENTILES}

    def mean(self) -> Optional[float]:
        return (self.total / self.count) if self.count else None

    # ----------------------------------------------------------------- merge

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other`` into ``self`` (fieldwise integer sum) and return
        ``self``. Exact: merged bucket counts are the sum of the inputs'."""
        for b in range(N_BUCKETS):
            self.counts[b] += other.counts[b]
        self.count += other.count
        self.total += other.total
        for attr in ("lo", "hi"):
            mine, theirs = getattr(self, attr), getattr(other, attr)
            if theirs is not None and (mine is None or (theirs < mine) == (attr == "lo")):
                setattr(self, attr, theirs)
        return self

    def copy(self) -> "Histogram":
        out = Histogram()
        out.counts = list(self.counts)
        out.count, out.total, out.lo, out.hi = self.count, self.total, self.lo, self.hi
        return out

    # --------------------------------------------------------------- vectors

    def to_vector(self) -> List[int]:
        """``[count, value_sum, buckets...]`` — the mergeable int section."""
        return [self.count, self.total, *self.counts]

    @classmethod
    def from_vector(cls, vec: Sequence[int]) -> "Histogram":
        vals = [int(v) for v in vec]
        if len(vals) != _KIND_VEC_LEN:
            raise ValueError(f"histogram vector has {len(vals)} entries, expected {_KIND_VEC_LEN}")
        out = cls()
        out.count, out.total = vals[0], vals[1]
        out.counts = vals[2:]
        return out

    def summary(self) -> Dict[str, Any]:
        """Flat report block: count, sum, mean, the estimation quantiles, and
        the non-empty buckets (sparse — most of the table is zero)."""
        out: Dict[str, Any] = {"count": self.count, "sum": self.total}
        mean = self.mean()
        out["mean"] = round(mean, 3) if mean is not None else None
        for name, est in self.percentiles().items():
            out[name] = round(est, 3) if est is not None else None
        out["buckets"] = {str(b): c for b, c in enumerate(self.counts) if c}
        return out


class HistogramRegistry:
    """Per-session store of histograms keyed by ``(kind, key)`` (thread-safe).

    ``kind`` is the event kind / dispatch stage (``update``/``sync``/...);
    ``key`` is the metric identity (``ClassName#n``) or a site label. Recording
    happens only behind the ``_ACTIVE`` guard — a disabled process never calls
    into this module from a dispatch path (guarded by the zero-overhead test).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hists: Dict[Tuple[str, str], Histogram] = {}

    def record(self, kind: str, key: str, value: int) -> None:
        with self._lock:
            hist = self._hists.get((kind, key))
            if hist is None:
                hist = self._hists[(kind, key)] = Histogram()
            hist.record(value)

    def record_duration(self, kind: str, key: str, duration_s: float) -> None:
        """Record a span in microseconds (the latency unit everywhere here)."""
        self.record(kind, key, max(0, int(duration_s * 1e6)))

    # -------------------------------------------------------------- querying

    def get(self, kind: str, key: str) -> Optional[Histogram]:
        with self._lock:
            hist = self._hists.get((kind, key))
            return hist.copy() if hist is not None else None

    def snapshot(self) -> Dict[str, Dict[str, Histogram]]:
        """``{kind: {key: histogram-copy}}`` as of now."""
        with self._lock:
            out: Dict[str, Dict[str, Histogram]] = {}
            for (kind, key), hist in self._hists.items():
                out.setdefault(kind, {})[key] = hist.copy()
            return out

    def kind_totals(self) -> Dict[str, Histogram]:
        """Per-kind merge across all keys — what the fleet vector ships."""
        with self._lock:
            out: Dict[str, Histogram] = {}
            for (kind, _), hist in self._hists.items():
                out.setdefault(kind, Histogram()).merge(hist)
            return out

    def keys_for(self, kind: str, prefix: str = "") -> Dict[str, Histogram]:
        with self._lock:
            return {
                key: hist.copy()
                for (k, key), hist in self._hists.items()
                if k == kind and key.startswith(prefix)
            }

    def fleet_vector(self) -> List[int]:
        """The per-kind totals as one flat int vector in
        :data:`FLEET_HISTOGRAM_KINDS` order — the payload the fleet gather
        plane (and the coalesced sync's metadata piggyback) ships per rank."""
        totals = self.kind_totals()
        vec: List[int] = []
        for kind in FLEET_HISTOGRAM_KINDS:
            hist = totals.get(kind)
            vec.extend(hist.to_vector() if hist is not None else [0] * _KIND_VEC_LEN)
        return vec

    def reset(self) -> None:
        with self._lock:
            self._hists = {}


# ---------------------------------------------------------------------------
# fleet merge (pure; the gather plane lives in parallel/sync.py)
# ---------------------------------------------------------------------------


def empty_fleet_vector() -> List[int]:
    return [0] * FLEET_VECTOR_LEN


def merge_vectors(rows: Iterable[Sequence[int]]) -> List[int]:
    """Exact fieldwise sum of per-rank fleet vectors — the merge IS integer
    addition, which is why histogram rollups ride the same int-vector plane
    as the counters."""
    out = empty_fleet_vector()
    n = 0
    for row in rows:
        vals = [int(v) for v in row]
        if len(vals) != FLEET_VECTOR_LEN:
            raise ValueError(
                f"fleet histogram vector has {len(vals)} entries, expected {FLEET_VECTOR_LEN}"
            )
        for i, v in enumerate(vals):
            out[i] += v
        n += 1
    if n == 0:
        raise ValueError("merge_vectors needs at least one rank vector")
    return out


def decode_fleet_vector(vec: Sequence[int]) -> Dict[str, Histogram]:
    """Split one (possibly merged) fleet vector back into per-kind histograms."""
    vals = [int(v) for v in vec]
    if len(vals) != FLEET_VECTOR_LEN:
        raise ValueError(
            f"fleet histogram vector has {len(vals)} entries, expected {FLEET_VECTOR_LEN}"
        )
    out: Dict[str, Histogram] = {}
    for i, kind in enumerate(FLEET_HISTOGRAM_KINDS):
        out[kind] = Histogram.from_vector(vals[i * _KIND_VEC_LEN : (i + 1) * _KIND_VEC_LEN])
    return out


def aggregate_histograms(
    rows: Sequence[Sequence[int]],
) -> Dict[str, Histogram]:
    """Merge per-rank fleet vectors into per-kind fleet histograms. The merged
    bucket counts equal the exact fieldwise sum over ranks — the invariant the
    acceptance test pins."""
    return decode_fleet_vector(merge_vectors(rows))
