"""Declarative SLO rules and the alert engine.

An operable metric service needs its "is it healthy" question answered by the
runtime, not by a human reading counters. A :class:`SloRule` is one declarative
statement of health — a boolean expression over **windowed counter deltas and
histogram percentiles** — plus the operational envelope around it: how much
history the expression sees (``window``), how loud a breach is (``severity``),
and how often it may page (``cooldown``). The engine evaluates rules against a
rolling ring of samples the recorder feeds it; a breach emits an ``alert``
:class:`~torchmetrics_tpu.observability.events.TelemetryEvent`, a rank-zero
warning, and — optionally — a degradation callback (the seam for "quarantine
the collection when the retry rate stays breached").

Expression namespace (everything is computed over the rule's window):

- every counter field by name (``retries``, ``dispatches``, ``sync_calls``,
  ``retraces``, ``state_growths``, ...) — the **delta** over the window;
- ``total(name)`` — the absolute counter value at evaluation time;
- ``p50(kind)`` / ``p95(kind)`` / ``p99(kind)`` / ``p999(kind)`` — percentile
  estimate of the window's histogram delta for a
  :data:`~torchmetrics_tpu.observability.histograms.FLEET_HISTOGRAM_KINDS`
  kind, in the kind's unit (microseconds for latencies, bytes for sizes);
  ``0.0`` when the window recorded nothing of that kind (a no-data window
  never breaches a ``>`` threshold);
- ``collectives_per_sync`` — the derived coalescing headline over the window;
- ``drift(name)`` — the latest score a
  :class:`~torchmetrics_tpu.streaming.DriftMonitor` recorded under ``name``
  (``0.0`` when none ran) — lets an SLO rule page on sustained drift, e.g.
  ``drift('accuracy') > 0.1 and drift_evals > 3``;
- ``window`` — the seconds of history actually covered (shorter than the
  configured window early in a session);
- ``max`` / ``min`` / ``abs`` — the only builtins exposed.

Expressions are evaluated with ``eval`` under an empty ``__builtins__`` — they
are operator-authored configuration, not untrusted input (the same trust level
as a ``dist_sync_fn``). A rule whose expression raises is reported once as a
``rule_error`` alert and then disabled for the session — a typo must not
silently disable monitoring OR crash the loop being monitored.

Evaluation is **pull-based and off the hot path**: the recorder feeds a sample
and evaluates at sync boundaries (low-frequency, already collective-shaped),
and the export layer's background flusher / health server evaluate on their own
cadence. With telemetry disabled nothing here runs at all (guarded by the
zero-overhead test).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional, Sequence, Tuple

from ..utilities.prints import rank_zero_warn
from . import histograms as _histograms
from .counters import COUNTER_FIELDS

SEVERITIES = ("info", "warning", "critical")


@dataclasses.dataclass(frozen=True)
class SloRule:
    """One declarative health rule.

    Args:
        name: stable identifier (alert events and ``/sloz`` key on it).
        expr: boolean expression over the windowed namespace (module docs).
            ``True`` == breached.
        window: seconds of history the expression's deltas/percentiles cover.
        severity: ``"info"`` / ``"warning"`` / ``"critical"`` — ``critical``
            breaches flip the health endpoint to 503.
        cooldown: seconds after an alert during which the rule stays silent
            (it keeps *evaluating* — ``breached`` state stays live — but emits
            no new alert/callback; alert storms page nobody usefully).
        description: human text carried on the alert.
        on_breach: optional degradation callback ``fn(alert_dict)`` — e.g.
            quarantine a collection on a sustained retry-rate breach. Runs
            after the alert event/warning; exceptions are caught and attached
            to the alert (a broken remediation must not take down the sync
            path that triggered evaluation).
    """

    name: str
    expr: str
    window: float = 60.0
    severity: str = "warning"
    cooldown: float = 300.0
    description: str = ""
    on_breach: Optional[Callable[[Dict[str, Any]], None]] = None

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, got {self.severity!r}")
        if self.window <= 0:
            raise ValueError(f"window must be > 0, got {self.window}")
        if self.cooldown < 0:
            raise ValueError(f"cooldown must be >= 0, got {self.cooldown}")
        compile(self.expr, f"<SloRule {self.name}>", "eval")  # syntax errors fail at construction


def default_rules(
    collectives_per_sync_max: float = 8.0,
    retrace_window_max: int = 8,
    update_p99_us_max: float = 200_000.0,
    retry_rate_max: float = 0.10,
) -> Tuple[SloRule, ...]:
    """The shipped rule pack — the five failure modes this runtime has actually
    hit, thresholded loosely enough to stay quiet on a healthy run:

    - ``collectives_per_sync``: the coalesced plane regressing toward per-leaf
      collectives (the `collection_sync_16metrics` bench gates the same drift);
    - ``retrace_storm``: shape-unstable inputs recompiling per batch;
    - ``update_p99_latency``: dispatch tail blowing past the envelope;
    - ``retry_rate``: sustained transient-failure churn (the degradation-
      callback candidate: quarantine before the budget exhausts mid-eval);
    - ``state_growth``: a cat state crossing the unbounded-growth sentinel.
    """
    return (
        SloRule(
            name="collectives_per_sync",
            expr=f"sync_calls > 0 and sync_collectives / sync_calls > {collectives_per_sync_max}",
            window=120.0,
            severity="warning",
            description="sync plane drifting from coalesced buckets back toward per-leaf collectives",
        ),
        SloRule(
            name="retrace_storm",
            expr=f"retraces > {retrace_window_max}",
            window=120.0,
            severity="warning",
            description="recompile churn: many new input shape/dtype signatures in the window",
        ),
        SloRule(
            name="update_p99_latency",
            expr=f"p99('update') > {update_p99_us_max}",
            window=60.0,
            severity="warning",
            description="update dispatch p99 latency over budget (us)",
        ),
        SloRule(
            name="retry_rate",
            expr=(
                "retries >= 3 and "
                f"retries / max(dispatches + host_dispatches + sync_calls, 1) > {retry_rate_max}"
            ),
            window=120.0,
            severity="critical",
            description="sustained transient-failure retry churn",
        ),
        SloRule(
            name="state_growth",
            expr="state_growths > 0",
            window=3600.0,
            severity="critical",
            description="a list/cat state crossed the unbounded-growth threshold",
        ),
    )


@dataclasses.dataclass
class _RuleState:
    breached: bool = False
    breaches: int = 0  # evaluations that found the expression true
    alerts: int = 0  # alerts actually emitted (cooldown-gated)
    last_alert_at: Optional[float] = None
    last_value_at: Optional[float] = None
    error: Optional[str] = None  # expression error — rule disabled for the session


# sample-ring bounds: enough resolution for any window, never unbounded growth
# on a high-frequency sync loop (each sample is a counters dict + fleet vector)
_MAX_SAMPLES = 512


class SloEngine:
    """Rolling-window evaluator over (counter snapshot, histogram vector)
    samples. One engine per telemetry session; the recorder owns it.

    Thread-safe: the training thread (sync boundaries), the export flusher,
    and health-server request threads all evaluate concurrently — one reentrant
    lock covers the sample ring, the cooldown bookkeeping (so an alert and its
    degradation callback fire exactly once per cooldown window), and the
    snapshots the endpoints render."""

    def __init__(self, rules: Sequence[SloRule] = ()) -> None:
        self.rules: Tuple[SloRule, ...] = tuple(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SloRule names: {sorted(names)}")
        # reentrant: an on_breach callback may legitimately read snapshot()
        self._lock = threading.RLock()
        self._states: Dict[str, _RuleState] = {r.name: _RuleState() for r in self.rules}
        # ring of (monotonic_t, counts_dict, fleet_hist_vector); pruned to the
        # longest rule window on every append
        self._samples: Deque[Tuple[float, Dict[str, int], List[int]]] = collections.deque()
        self._max_window = max((r.window for r in self.rules), default=0.0)
        self._alerts: Deque[Dict[str, Any]] = collections.deque(maxlen=256)
        # the implicit session-start sample: all-zero counters/histograms. A
        # young session (or one that never observes) deltas against THIS, so
        # the first evaluation after a breach already sees it instead of
        # comparing the current state against itself.
        self._genesis: Optional[Tuple[float, Dict[str, int], List[int]]] = None
        # burn(expr, ...) sub-expressions, compiled once per distinct string
        self._burn_codes: Dict[str, Any] = {}

    # ------------------------------------------------------------- sampling

    def _sample(self, recorder: Any, now: float) -> Tuple[float, Dict[str, int], List[int]]:
        counts = dict(zip(COUNTER_FIELDS, recorder.counters.counts_vector()))
        return (now, counts, recorder.histograms.fleet_vector())

    def _ensure_genesis(self, t: float) -> None:
        if self._genesis is None:
            self._genesis = (
                t,
                {f: 0 for f in COUNTER_FIELDS},
                [0] * _histograms.FLEET_VECTOR_LEN,
            )

    def observe(
        self,
        recorder: Any,
        now: Optional[float] = None,
        sample: Optional[Tuple[float, Dict[str, int], List[int]]] = None,
    ) -> None:
        """Append one sample (and prune history past the longest window)."""
        if not self.rules:
            return
        from . import tracing

        t = tracing.monotonic() if now is None else now
        if sample is None:
            sample = self._sample(recorder, t)
        with self._lock:
            self._ensure_genesis(t)
            # thin by spacing so a per-batch sync loop cannot grow the ring
            # unboundedly: ~_MAX_SAMPLES samples cover the longest window with
            # plenty of baseline resolution (genesis covers young sessions)
            spacing = self._max_window / (_MAX_SAMPLES / 2)
            if self._samples and t - self._samples[-1][0] < spacing:
                return
            self._samples.append(sample)
            # keep one sample OLDER than the window so a full window always has
            # a baseline (delta against the sample just before the window edge)
            while len(self._samples) > 2 and self._samples[1][0] <= t - self._max_window:
                self._samples.popleft()
            while len(self._samples) > _MAX_SAMPLES:  # hard backstop
                self._samples.popleft()

    # ------------------------------------------------------------ evaluation

    @staticmethod
    def _namespace(
        current: Tuple[float, Dict[str, int], List[int]],
        baseline: Tuple[float, Dict[str, int], List[int]],
    ) -> Dict[str, Any]:
        t1, counts1, hist1 = current
        t0, counts0, hist0 = baseline
        delta = {f: counts1[f] - counts0.get(f, 0) for f in COUNTER_FIELDS}
        hist_delta = [a - b for a, b in zip(hist1, hist0)]
        kinds = _histograms.decode_fleet_vector(hist_delta)

        def pct(q: float) -> Callable[[str], float]:
            def f(kind: str) -> float:
                hist = kinds.get(kind)
                if hist is None:
                    raise NameError(
                        f"unknown histogram kind {kind!r}; known: {_histograms.FLEET_HISTOGRAM_KINDS}"
                    )
                est = hist.percentile(q)
                return 0.0 if est is None else est

            return f

        syncs = delta.get("sync_calls", 0)
        ns: Dict[str, Any] = dict(delta)
        ns.update(
            total=lambda name: counts1[name],
            p50=pct(0.50), p95=pct(0.95), p99=pct(0.99), p999=pct(0.999),
            collectives_per_sync=(delta.get("sync_collectives", 0) / syncs) if syncs else 0.0,
            # floored at 1s: a session's first evaluation shares the genesis
            # timestamp, and a rate rule dividing by `window` must neither
            # ZeroDivisionError (killing the rule for the session) nor see a
            # microscopic window that inflates any delta into a breach
            window=max(t1 - t0, 1.0),
            max=max, min=min, abs=abs,
        )
        return ns

    def _baseline_at(self, now: float, window: float) -> Tuple[float, Dict[str, int], List[int]]:
        """Newest sample at or older than ``now - window`` (so the delta
        covers at least the window); a session younger than the window deltas
        against the zero genesis sample (= everything since session start)."""
        edge = now - window
        chosen = self._genesis
        for sample in self._samples:
            if sample[0] <= edge:
                chosen = sample
            else:
                break
        return chosen

    def _baseline_for(self, rule: SloRule, now: float) -> Tuple[float, Dict[str, int], List[int]]:
        return self._baseline_at(now, rule.window)

    def observe_and_evaluate(self, recorder: Any, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed the window and evaluate in one step, building the (counters +
        histograms) sample ONCE — the per-sync heartbeat path, where walking
        both registries twice back-to-back would be pure waste."""
        if not self.rules:
            return []
        from . import tracing

        t = tracing.monotonic() if now is None else now
        sample = self._sample(recorder, t)
        self.observe(recorder, now=t, sample=sample)
        return self.evaluate(recorder, now=t, sample=sample)

    def evaluate(
        self,
        recorder: Any,
        now: Optional[float] = None,
        sample: Optional[Tuple[float, Dict[str, int], List[int]]] = None,
    ) -> List[Dict[str, Any]]:
        """Evaluate every rule against the current state (or an explicit
        ``sample``); returns the alerts emitted by THIS evaluation (already
        recorded/emitted via the recorder). Safe to call from any thread;
        cheap when no rules are configured."""
        if not self.rules:
            return []
        from . import tracing

        t = tracing.monotonic() if now is None else now
        current = sample if sample is not None else self._sample(recorder, t)
        fired: List[Dict[str, Any]] = []
        callbacks: List[Tuple[SloRule, Dict[str, Any]]] = []
        with self._lock:
            self._ensure_genesis(t)
            for rule in self.rules:
                state = self._states[rule.name]
                if state.error is not None:
                    continue
                ns = self._namespace(current, self._baseline_for(rule, t))
                self._inject_gauges(ns, recorder)
                burn_state = self._inject_timetravel(ns, recorder, current, t, rule)
                try:
                    breached = bool(eval(rule.expr, {"__builtins__": {}}, ns))  # noqa: S307 — operator config
                except Exception as err:
                    state.error = f"{type(err).__name__}: {err}"[:240]
                    state.breached = False
                    alert = self._emit(recorder, rule, t, kind="rule_error", error=state.error)
                    fired.append(alert)
                    continue
                state.breached = breached
                state.last_value_at = t
                if not breached:
                    continue
                state.breaches += 1
                if state.last_alert_at is not None and t - state.last_alert_at < rule.cooldown:
                    continue  # cooling down: stay breached, page nobody
                state.last_alert_at = t
                state.alerts += 1
                alert = self._emit(recorder, rule, t, kind="breach", window=ns["window"])
                if burn_state["burned"]:
                    # a multi-window burn page rides the SAME cooldown as the
                    # alert above — exactly once per cooldown, never flapping
                    alert["burn"] = {"short": burn_state["short"], "long": burn_state["long"]}
                    self._emit_burn(recorder, rule, t, burn_state)
                if rule.on_breach is not None:
                    callbacks.append((rule, alert))
                fired.append(alert)
        # degradation callbacks run OUTSIDE the lock: a slow remediation (a
        # pager call, a quarantine sweep) fired from a server/flusher thread
        # must not block the training thread's sync-boundary evaluation
        for rule, alert in callbacks:
            try:
                rule.on_breach(alert)
            except Exception as err:  # noqa: BLE001 — remediation must not kill the sync path
                alert["callback_error"] = f"{type(err).__name__}: {err}"[:240]
        return fired

    @staticmethod
    def _inject_gauges(ns: Dict[str, Any], recorder: Any) -> None:
        # drift scores are recorder-local gauges (not window deltas): the
        # namespace exposes the latest value a DriftMonitor recorded under
        # each name
        drift_fn = getattr(recorder, "drift_score", None)
        if drift_fn is not None:
            ns["drift"] = drift_fn
        # the quantized sync plane's error-feedback residual norm is a
        # SCALAR gauge (unlike drift's per-name lookup), so expose the
        # value itself — rules write `quant_feedback_norm > 1e-3`
        quant_fn = getattr(recorder, "quant_feedback_norm", None)
        if quant_fn is not None:
            ns["quant_feedback_norm"] = quant_fn()

    def _inject_timetravel(
        self,
        ns: Dict[str, Any],
        recorder: Any,
        current: Tuple[float, Dict[str, int], List[int]],
        t: float,
        rule: SloRule,
    ) -> Dict[str, Any]:
        """``rate()``/``delta()``/``burn()`` — the telemetry-history plane's
        SLO face: windowed counter lookups at ARBITRARY windows over the
        sample ring (a plain name is always the delta over the rule's own
        window; these reach past it). Returns the burn bookkeeping cell the
        breach path reads to decide whether this alert is also a burn page."""
        _, counts1, _ = current
        burn_state: Dict[str, Any] = {"burned": False, "short": None, "long": None}

        def _delta(name: str, window: float) -> int:
            if name not in counts1:
                raise NameError(f"unknown counter {name!r}; known: {COUNTER_FIELDS}")
            _, counts0, _ = self._baseline_at(t, window)
            return counts1[name] - counts0.get(name, 0)

        def _rate(name: str, window: Optional[float] = None) -> float:
            """Per-second rate of a counter over ``window`` (default: the
            rule's own window), with the same 1s elapsed floor as ``window``."""
            if name not in counts1:
                raise NameError(f"unknown counter {name!r}; known: {COUNTER_FIELDS}")
            t0, counts0, _ = self._baseline_at(t, rule.window if window is None else window)
            return (counts1[name] - counts0.get(name, 0)) / max(t - t0, 1.0)

        def _burn(expr: str, short: float, long: float) -> bool:
            """Google-SRE multi-window burn rate: ``expr`` must hold over BOTH
            the short and the long window — a short spike alone never pages
            (the long window is clean), a slow burn alone never pages at the
            tail (the short window has recovered); both burning is the page."""
            code = self._burn_codes.get(expr)
            if code is None:
                code = self._burn_codes[expr] = compile(expr, f"<burn:{expr}>", "eval")
            burned = True
            for w in (short, long):
                wns = self._namespace(current, self._baseline_at(t, w))
                self._inject_gauges(wns, recorder)
                wns["rate"], wns["delta"] = _rate, _delta
                burned = bool(eval(code, {"__builtins__": {}}, wns)) and burned  # noqa: S307 — operator config
            if burned:
                burn_state.update(burned=True, short=short, long=long)
            return burned

        ns["rate"], ns["delta"], ns["burn"] = _rate, _delta, _burn
        return burn_state

    def _emit_burn(self, recorder: Any, rule: SloRule, t: float, burn_state: Dict[str, Any]) -> None:
        """The burn page itself, alongside the regular alert: its own event
        kind + counter so pager routing can treat a multi-window burn as the
        high-confidence page it is."""
        recorder.counters.record_burn_alert()
        recorder._event(
            "burn_alert", rule.name, rule.severity,
            payload={
                "kind": "burn",
                "short_window": burn_state["short"],
                "long_window": burn_state["long"],
                "at": t,
            },
        )

    def _emit(self, recorder: Any, rule: SloRule, t: float, kind: str, **extra: Any) -> Dict[str, Any]:
        alert: Dict[str, Any] = {
            "rule": rule.name,
            "severity": rule.severity,
            "kind": kind,
            "expr": rule.expr,
            "description": rule.description,
            "at": t,
            **extra,
        }
        self._alerts.append(alert)
        recorder.counters.record_alert()
        recorder._event(
            "alert", rule.name, rule.severity,
            payload={k: v for k, v in alert.items() if k not in ("rule",)},
        )
        if kind == "rule_error":
            rank_zero_warn(
                f"SLO rule {rule.name!r} raised while evaluating ({extra.get('error')}); "
                f"the rule is disabled for this session. Expression: {rule.expr!r}.",
                UserWarning,
            )
        else:
            rank_zero_warn(
                f"SLO breach [{rule.severity}] {rule.name}: {rule.description or rule.expr} "
                f"(window {rule.window:.0f}s, cooldown {rule.cooldown:.0f}s).",
                UserWarning,
            )
        return alert

    # -------------------------------------------------------------- reports

    def snapshot(self) -> Dict[str, Any]:
        """``/sloz``'s document: per-rule config + live state + recent alerts."""
        with self._lock:
            rules_out: Dict[str, Any] = {}
            for rule in self.rules:
                state = self._states[rule.name]
                rules_out[rule.name] = {
                    "expr": rule.expr,
                    "window": rule.window,
                    "severity": rule.severity,
                    "cooldown": rule.cooldown,
                    "description": rule.description,
                    "breached": state.breached,
                    "breaches": state.breaches,
                    "alerts": state.alerts,
                    "error": state.error,
                }
            return {
                "rules": rules_out,
                "recent_alerts": [dict(a) for a in self._alerts],
                "samples": len(self._samples),
            }

    def breached(self, min_severity: str = "info") -> List[str]:
        """Names of currently-breached rules at or above ``min_severity``."""
        floor = SEVERITIES.index(min_severity)
        with self._lock:
            return [
                r.name
                for r in self.rules
                if self._states[r.name].breached and SEVERITIES.index(r.severity) >= floor
            ]
