"""Process-wide telemetry counters.

Everything the runtime can observe without a device→host readback is counted
here: jitted dispatches split into compiles vs cache hits per ``_jit_cache`` key
(first-seen input shape/dtype signature == a trace/compile; a repeat == a cache
hit, mirroring ``jax.jit``'s own cache discipline), retraces (every compile
beyond a key's first), device→host readbacks at the runtime's instrumented
sites (``state_dict``, ``compute_on_cpu`` appends, finiteness guards),
``process_sync`` invocations with payload bytes (computed from array metadata —
``shape``/``dtype`` never touch the device), and the reliability layer's
retry/quarantine totals.

The registry is pure stdlib (no jax import): the bench driver and
``tools/trace_report.py`` consume snapshots without initializing a runtime.
Counting happens only while a telemetry session is active — a disabled process
never calls into this module from a dispatch path.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

# every scalar the registry tracks, in reporting order
COUNTER_FIELDS: Tuple[str, ...] = (
    "dispatches",  # jitted donated dispatches (update/forward tensor path)
    "jit_compiles",  # first-seen (key, signature) pairs — one XLA trace each
    "jit_cache_hits",  # repeat signatures — served from an in-memory program
    "retraces",  # compiles beyond a key's first actual compile (shape/dtype churn)
    "aot_cache_hits",  # first-seen signatures served by a LOADED executable (aot/)
    "aot_cache_misses",  # aot-plane disk probes that found nothing usable
    "aot_deserialize_us",  # wall-clock spent loading serialized executables
    "host_dispatches",  # HostMetric update/forward (eager, never jitted)
    "computes",  # Metric.compute invocations
    "d2h_readbacks",  # device→host transfers at instrumented runtime sites
    "d2h_bytes",
    "sync_calls",  # process_sync invocations
    "sync_payload_bytes",  # bytes entering the cross-process gather
    "sync_time_us",  # wall-clock spent inside Metric.sync (straggler signal)
    "gather_calls",  # per-leaf gather_all_arrays collectives (fallback plane)
    "gathers_coalesced",  # state leaves served by a coalesced bucket (no own collective)
    "sync_collectives",  # collectives actually launched by the sync planes
    "sync_bytes_saved",  # wire bytes the quantized codecs shaved off sync payloads
    "quantized_buckets",  # dtype buckets shipped as compressed byte streams
    "retries",  # transient failures accepted for retry
    "retries_exhausted",  # retry budgets that ran out on a transient failure
    "quarantines",  # metrics frozen by MetricCollection(on_error="quarantine")
    "skips",  # per-batch skips under on_error="skip"
    "state_growths",  # list/cat states that crossed the unbounded-growth sentinel
    "alerts",  # SLO engine alerts emitted (breaches + rule errors)
    "serve_dispatches",  # megabatched stacked-state dispatches (serving engine)
    "serve_tenant_rows",  # real tenant rows those dispatches served
    "serve_padded_rows",  # scratch pad rows burned to keep megabatch signatures fixed
    "tenant_spills",  # cold tenant states spilled from the stack to host memory
    "tenant_readmits",  # spilled tenant states uploaded back into a stack slot
    "tenant_spill_us",  # wall-clock spent spilling/readmitting tenant state
    "window_rolls",  # SlidingWindow updates (streaming plane, wupdate/wdual/wstack dispatches)
    "window_rotations",  # dual block rotations / two-stack pane completions (window hop progress)
    "async_syncs",  # double-buffered background syncs committed (AsyncSyncHandle)
    "async_sync_wait_us",  # wall-clock commit() actually blocked — the UNHIDDEN sync latency
    "drift_evals",  # DriftMonitor window-vs-reference evaluations
    "drift_breaches",  # evaluations whose drift score crossed the monitor's threshold
    "serve_rejected",  # tenant batches shed by the serving admission rate limit
    "snapshots",  # crash-consistent engine snapshot generations written (durability plane)
    "snapshot_restores",  # engine restores from a snapshot generation
    "journal_records",  # batches appended to the write-ahead traffic journal
    "journal_fsyncs",  # journal appends that reached stable storage (fsync batches)
    "replayed_records",  # journal records rolled forward into a restored engine
    "degraded_syncs",  # coalesced syncs completed over a survivor quorum (dead rank seen)
    "rank_rejoins",  # previously dead ranks whose contribution reconciled on rejoin
    "fleet_heartbeats",  # member-host lease renewals seen by the fleet controller
    "lease_expiries",  # host leases that ran past dead_after (suspect -> dead transitions)
    "host_failovers",  # dead hosts whose tenants survivors adopted (snapshot + journal tail)
    "tenant_migrations",  # tenants moved host-to-host by the committed migrate protocol
    "migration_us",  # wall-clock spent inside committed migrations (drain -> cutover)
    "flightrec_dumps",  # postmortem artifacts the flight recorder dumped (observability plane)
    "history_folds",  # telemetry-history blocks closed/telescoped (timeseries plane)
    "burn_alerts",  # multi-window burn-rate pages (both short AND long window burned)
)


def _tenants_per_dispatch(counts: Mapping[str, int]) -> float:
    """Derived headline of the serving engine: real tenant rows served per
    megabatched dispatch. One python dispatch per tenant reads 1.0; the
    stacked/vmapped plane reads close to the megabatch size — the direct
    observable of one-compile-many-tenants amortization (0.0 before any
    serving dispatch ran)."""
    dispatches = int(counts.get("serve_dispatches", 0))
    if not dispatches:
        return 0.0
    return round(int(counts.get("serve_tenant_rows", 0)) / dispatches, 3)


def _collectives_per_sync(counts: Mapping[str, int]) -> float:
    """Derived headline of the coalesced sync plane: collectives launched per
    ``process_sync``/collection sync. K·L per-leaf collectives collapse to
    1 metadata gather + one per dtype bucket — this ratio is the direct
    observable of that reduction (0.0 before any sync ran)."""
    syncs = int(counts.get("sync_calls", 0))
    if not syncs:
        return 0.0
    return round(int(counts.get("sync_collectives", 0)) / syncs, 3)


@dataclasses.dataclass(frozen=True)
class CountersSnapshot:
    """Immutable point-in-time view of a :class:`Counters` registry.

    ``costs`` is the per-key compiled-cost map (``observability/costs.py``) as
    of the snapshot — empty when no cost registry is attached (standalone
    counters, cost accounting disabled).
    """

    counts: Dict[str, int]
    per_key: Dict[str, Dict[str, Any]]
    costs: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)

    def __getitem__(self, name: str) -> int:
        return self.counts[name]

    def diff(self, earlier: "CountersSnapshot") -> "CountersSnapshot":
        """This snapshot minus an ``earlier`` one (per-key signatures and cost
        entries: only the ones that appeared in between)."""
        counts = {k: v - earlier.counts.get(k, 0) for k, v in self.counts.items()}
        per_key: Dict[str, Dict[str, Any]] = {}
        for key, rec in self.per_key.items():
            old = earlier.per_key.get(key, {})
            old_sigs = set(old.get("signatures", ()))
            old_counts = old.get("sig_counts", {})
            delta = {
                "compiles": rec["compiles"] - old.get("compiles", 0),
                "cache_hits": rec["cache_hits"] - old.get("cache_hits", 0),
                "aot_hits": rec.get("aot_hits", 0) - old.get("aot_hits", 0),
                "signatures": [s for s in rec["signatures"] if s not in old_sigs],
                "sig_counts": {
                    s: n - old_counts.get(s, 0)
                    for s, n in rec.get("sig_counts", {}).items()
                    if n - old_counts.get(s, 0)
                },
            }
            if delta["compiles"] or delta["cache_hits"] or delta["aot_hits"] or delta["signatures"]:
                per_key[key] = delta
        costs = {}
        for key, sigs in self.costs.items():
            old_sigs = set(earlier.costs.get(key, {}))
            fresh = {s: rec for s, rec in sigs.items() if s not in old_sigs}
            if fresh:
                costs[key] = fresh
        return CountersSnapshot(counts=counts, per_key=per_key, costs=costs)

    def summary(self, brief: bool = False) -> Dict[str, Any]:
        """Flat JSON-friendly dict. ``brief`` keeps only the headline counters
        (the shape bench configs embed next to ``attempts``/``recovered_from``)."""
        if brief:
            keys = (
                "dispatches", "jit_compiles", "jit_cache_hits", "retraces",
                "host_dispatches", "d2h_readbacks", "sync_calls",
                "gathers_coalesced", "serve_dispatches",
            )
            out = {k: self.counts[k] for k in keys}
            out["collectives_per_sync"] = _collectives_per_sync(self.counts)
            out["tenants_per_dispatch"] = _tenants_per_dispatch(self.counts)
            return out
        out: Dict[str, Any] = dict(self.counts)
        out["collectives_per_sync"] = _collectives_per_sync(self.counts)
        out["tenants_per_dispatch"] = _tenants_per_dispatch(self.counts)
        out["per_key"] = {
            k: {"compiles": v["compiles"], "cache_hits": v["cache_hits"],
                "aot_hits": v.get("aot_hits", 0),
                "signatures": list(v["signatures"]),
                "sig_counts": dict(v.get("sig_counts", {}))}
            for k, v in self.per_key.items()
        }
        if self.costs:
            out["costs"] = {k: {s: dict(r) for s, r in v.items()} for k, v in self.costs.items()}
            out["cost_totals"] = self.cost_totals()
        return out

    def cost_totals(self) -> Dict[str, Any]:
        """Dispatch-weighted run totals: each program's per-call cost times how
        often its exact ``(key, signature)`` dispatched — the per-program cost
        attribution the compile counters alone cannot give."""
        totals: Dict[str, Any] = {
            "run_flops": 0.0, "run_bytes_accessed": 0.0, "run_transcendentals": 0.0,
            "compiled_programs": 0, "unavailable": 0,
            "peak_argument_bytes": 0, "peak_output_bytes": 0, "peak_temp_bytes": 0,
        }
        for key, sigs in self.costs.items():
            sig_counts = self.per_key.get(key, {}).get("sig_counts", {})
            for sig, rec in sigs.items():
                totals["compiled_programs"] += 1
                if not rec.get("available"):
                    totals["unavailable"] += 1
                    continue
                n = int(sig_counts.get(sig, 0))
                totals["run_flops"] += rec.get("flops", 0.0) * n
                totals["run_bytes_accessed"] += rec.get("bytes_accessed", 0.0) * n
                totals["run_transcendentals"] += rec.get("transcendentals", 0.0) * n
                for peak, field in (
                    ("peak_argument_bytes", "argument_bytes"),
                    ("peak_output_bytes", "output_bytes"),
                    ("peak_temp_bytes", "temp_bytes"),
                ):
                    totals[peak] = max(totals[peak], int(rec.get(field, 0)))
        return totals

    def counts_vector(self) -> List[int]:
        """Counts as an int vector in :data:`COUNTER_FIELDS` order — the
        metadata-only payload the fleet gather plane ships per rank."""
        return [int(self.counts.get(f, 0)) for f in COUNTER_FIELDS]


class Counters:
    """Mutable counters registry (one per telemetry session; thread-safe)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {f: 0 for f in COUNTER_FIELDS}
        # "ClassName#id.tag" -> {"compiles", "cache_hits", "signatures": [..],
        #                        "sig_counts": {sig: dispatches}}
        self._per_key: Dict[str, Dict[str, Any]] = {}
        # optional costs.CostRegistry — its snapshot rides along in snapshot()
        self._costs: Optional[Any] = None

    def attach_costs(self, registry: Any) -> None:
        """Fold a ``costs.CostRegistry``'s snapshots into this registry's
        (the recorder attaches its per-session registry here)."""
        self._costs = registry

    # -------------------------------------------------------------- recording

    def record_dispatch(self, key: str, signature: str, aot_loaded: bool = False) -> Tuple[bool, int]:
        """One successful jitted dispatch under ``key`` with the given input
        ``signature``. Returns ``(is_new_signature, n_compiles_for_key)`` —
        the second element counts the key's actual COMPILES (not distinct
        signatures), which is what the retrace event/sentinel key off.

        ``aot_loaded`` marks a dispatch served by a deserialized executable
        from the AOT cache: a FIRST-seen signature then counts as an
        ``aot_cache_hit`` instead of a compile (and never as a retrace —
        nothing recompiled), keeping ``jit_compiles + jit_cache_hits +
        aot_cache_hits == dispatches`` an exact identity. Repeat signatures
        count as ``jit_cache_hits`` either way: they are served by an
        in-memory program, whichever plane first materialized it. With no AOT
        activity, compiles == distinct signatures, so the return is exactly
        what it always was.
        """
        with self._lock:
            rec = self._per_key.setdefault(
                # "signatures" keeps first-seen order for reports; "_sig_set" is
                # the O(1) membership twin — a retrace storm (the pathology this
                # counter diagnoses) must not make its own bookkeeping O(n)
                key, {"compiles": 0, "cache_hits": 0, "aot_hits": 0, "signatures": [],
                      "_sig_set": set(), "sig_counts": {}}
            )
            self._counts["dispatches"] += 1
            rec["sig_counts"][signature] = rec["sig_counts"].get(signature, 0) + 1
            if signature in rec["_sig_set"]:
                rec["cache_hits"] += 1
                self._counts["jit_cache_hits"] += 1
                return False, rec["compiles"]
            rec["signatures"].append(signature)
            rec["_sig_set"].add(signature)
            if aot_loaded:
                rec["aot_hits"] += 1
                self._counts["aot_cache_hits"] += 1
            else:
                rec["compiles"] += 1
                self._counts["jit_compiles"] += 1
                # a retrace is a recompile beyond the key's first COMPILE —
                # signatures served by the AOT cache never recompiled anything
                if rec["compiles"] > 1:
                    self._counts["retraces"] += 1
            return True, rec["compiles"]

    def record_aot_miss(self) -> None:
        """The AOT plane probed the disk cache for a first-seen signature and
        found nothing usable (absent, stale-keyed, or corrupt — all misses)."""
        with self._lock:
            self._counts["aot_cache_misses"] += 1

    def record_aot_deserialize(self, duration_s: float) -> None:
        """Wall-clock of one executable load (microseconds, accumulated like
        ``sync_time_us``)."""
        with self._lock:
            self._counts["aot_deserialize_us"] += max(0, int(duration_s * 1e6))

    def has_signature(self, key: str, signature: str) -> bool:
        """Whether ``(key, signature)`` has already been counted (the recorder
        peeks this to harvest a fresh program's cost BEFORE the compile counter
        ticks — see :meth:`snapshot` for why the ordering matters)."""
        with self._lock:
            rec = self._per_key.get(key)
            return rec is not None and signature in rec["_sig_set"]

    def record_host_dispatch(self) -> None:
        with self._lock:
            self._counts["host_dispatches"] += 1

    def record_compute(self) -> None:
        with self._lock:
            self._counts["computes"] += 1

    def record_d2h(self, nbytes: int) -> None:
        with self._lock:
            self._counts["d2h_readbacks"] += 1
            self._counts["d2h_bytes"] += int(nbytes)

    def record_sync(self, payload_bytes: int) -> None:
        with self._lock:
            self._counts["sync_calls"] += 1
            self._counts["sync_payload_bytes"] += int(payload_bytes)

    def record_sync_time(self, duration_s: float) -> None:
        """Wall-clock of one ``Metric.sync`` (microseconds; the fleet rollup
        turns per-rank totals into straggler min/max skew)."""
        with self._lock:
            self._counts["sync_time_us"] += max(0, int(duration_s * 1e6))

    def record_gather(self) -> None:
        with self._lock:
            self._counts["gather_calls"] += 1

    def record_coalesced(self, n_leaves: int) -> None:
        """``n_leaves`` state leaves rode a coalesced bucket (no per-leaf
        collective of their own)."""
        with self._lock:
            self._counts["gathers_coalesced"] += int(n_leaves)

    def record_sync_collectives(self, n: int) -> None:
        """``n`` collectives launched by a sync plane (coalesced: metadata +
        one per bucket; per-leaf fallback: one per leaf)."""
        with self._lock:
            self._counts["sync_collectives"] += int(n)

    def record_quant(self, buckets: int, bytes_saved: int) -> None:
        """One quantized coalesced sync: ``buckets`` dtype buckets shipped as
        compressed byte streams, saving ``bytes_saved`` wire bytes vs the
        exact plane (payload minus the scale metadata that rode the metadata
        collective; clamped at zero — a pathological all-tiny-leaf sync could
        cost more in scales than it saves, which the eligibility floor
        normally prevents)."""
        with self._lock:
            self._counts["quantized_buckets"] += int(buckets)
            self._counts["sync_bytes_saved"] += max(0, int(bytes_saved))

    def record_retry(self) -> None:
        with self._lock:
            self._counts["retries"] += 1

    def record_retry_exhausted(self) -> None:
        with self._lock:
            self._counts["retries_exhausted"] += 1

    def record_quarantine(self, status: str) -> None:
        with self._lock:
            self._counts["quarantines" if status == "quarantined" else "skips"] += 1

    def record_state_growth(self) -> None:
        with self._lock:
            self._counts["state_growths"] += 1

    def record_serve_dispatch(self, rows: int, padded: int = 0) -> None:
        """One megabatched serving dispatch that updated ``rows`` real tenant
        rows (plus ``padded`` scratch rows keeping the signature fixed)."""
        with self._lock:
            self._counts["serve_dispatches"] += 1
            self._counts["serve_tenant_rows"] += int(rows)
            self._counts["serve_padded_rows"] += int(padded)

    def record_tenant_spill(self, duration_s: float, readmit: bool = False) -> None:
        """One tenant-state spill to host (or, ``readmit=True``, an upload
        back into a stack slot); wall-clock accumulates like ``sync_time_us``."""
        with self._lock:
            self._counts["tenant_readmits" if readmit else "tenant_spills"] += 1
            self._counts["tenant_spill_us"] += max(0, int(duration_s * 1e6))

    def record_alert(self) -> None:
        with self._lock:
            self._counts["alerts"] += 1

    def record_window_roll(self, rotated: bool = False) -> None:
        """One SlidingWindow update (a windowed ``wupdate``/``wdual``/
        ``wstack`` dispatch); ``rotated`` marks a dual block rotation or a
        two-stack pane completion — the hop cadence of the constant-memory
        window tiers."""
        self.record_window_rolls(1, 1 if rotated else 0)

    def record_window_rolls(self, n: int, rotations: int = 0) -> None:
        """Bulk form: ``n`` windowed per-tenant row updates (one vmapped
        ``vwupdate`` megabatch advances many tenant windows at once), of
        which ``rotations`` completed a block/pane."""
        with self._lock:
            self._counts["window_rolls"] += int(n)
            self._counts["window_rotations"] += int(rotations)

    def record_async_sync(self, wait_s: float) -> None:
        """One committed double-buffered background sync; ``wait_s`` is how
        long ``commit()`` actually blocked — the part of the sync latency the
        overlap did NOT hide (the gather's full wall-clock still lands in
        ``sync_time_us`` like a blocking sync)."""
        with self._lock:
            self._counts["async_syncs"] += 1
            self._counts["async_sync_wait_us"] += max(0, int(wait_s * 1e6))

    def record_drift(self, breached: bool) -> None:
        """One DriftMonitor evaluation (``breached``: score over threshold)."""
        with self._lock:
            self._counts["drift_evals"] += 1
            if breached:
                self._counts["drift_breaches"] += 1

    def record_serve_rejected(self) -> None:
        """One tenant batch shed by the serving admission rate limit."""
        with self._lock:
            self._counts["serve_rejected"] += 1

    def record_snapshot(self, restore: bool = False) -> None:
        """One crash-consistent engine snapshot written (``restore=True``:
        one engine restored from a generation instead)."""
        with self._lock:
            self._counts["snapshot_restores" if restore else "snapshots"] += 1

    def record_journal_append(self, fsynced: bool) -> None:
        """One batch appended to the write-ahead journal; ``fsynced`` marks
        the appends that closed an fsync batch (stable-storage boundary)."""
        with self._lock:
            self._counts["journal_records"] += 1
            if fsynced:
                self._counts["journal_fsyncs"] += 1

    def record_journal_replay(self, records: int) -> None:
        """``records`` journal entries rolled forward into a restored engine."""
        with self._lock:
            self._counts["replayed_records"] += int(records)

    def record_degraded_sync(self) -> None:
        """One coalesced sync that completed over a survivor quorum because a
        rank presented a dead (all-zero) metadata row."""
        with self._lock:
            self._counts["degraded_syncs"] += 1

    def record_rank_rejoin(self) -> None:
        """One previously dead rank seen alive again — its accumulated state
        folds back in on this very sync (full-state gather, no double count)."""
        with self._lock:
            self._counts["rank_rejoins"] += 1

    def record_fleet_heartbeat(self) -> None:
        """One member-host lease renewal accepted by the fleet controller."""
        with self._lock:
            self._counts["fleet_heartbeats"] += 1

    def record_lease_expiry(self) -> None:
        """One host lease that ran past its expiry — the suspect → dead
        transition that triggers tenant adoption by the survivors."""
        with self._lock:
            self._counts["lease_expiries"] += 1

    def record_host_failover(self) -> None:
        """One dead host whose tenant roster was adopted by survivors
        (latest snapshot generation + journal-tail replay)."""
        with self._lock:
            self._counts["host_failovers"] += 1

    def record_migration(self, tenants: int, duration_us: int) -> None:
        """One committed host-to-host migration: ``tenants`` moved, with the
        wall-clock the drain → cutover protocol took."""
        with self._lock:
            self._counts["tenant_migrations"] += int(tenants)
            self._counts["migration_us"] += int(duration_us)

    def record_flightrec_dump(self) -> None:
        """One postmortem artifact dumped by the flight recorder (auto-trigger
        or explicit ``dump()``)."""
        with self._lock:
            self._counts["flightrec_dumps"] += 1

    def record_history_folds(self, folds: int = 1) -> None:
        """``folds`` telemetry-history blocks closed (each fold telescopes a
        fine block into the coarser level above it)."""
        with self._lock:
            self._counts["history_folds"] += int(folds)

    def record_burn_alert(self) -> None:
        """One multi-window burn-rate page: a ``burn(expr, short, long)`` rule
        breached with BOTH windows burning (cooldown-gated, like alerts)."""
        with self._lock:
            self._counts["burn_alerts"] += 1

    # --------------------------------------------------------------- querying

    def value(self, name: str) -> int:
        with self._lock:
            return self._counts[name]

    def counts_vector(self) -> List[int]:
        """Counts in :data:`COUNTER_FIELDS` order without the full snapshot
        copy — the sync-latency path ships this on every coalesced sync, so it
        must not pay the per-key/costs deep copies ``snapshot()`` does."""
        with self._lock:
            return [int(self._counts.get(f, 0)) for f in COUNTER_FIELDS]

    def signatures(self, key: str) -> List[str]:
        with self._lock:
            rec = self._per_key.get(key)
            return list(rec["signatures"]) if rec else []

    def keys_for(self, prefix: str) -> Dict[str, Dict[str, Any]]:
        """Per-key records whose key starts with ``prefix`` (instance lookup:
        keys are ``ClassName#id.tag``, so ``ClassName#id.`` selects one metric)."""
        with self._lock:
            return {
                k: {"compiles": v["compiles"], "cache_hits": v["cache_hits"],
                    "aot_hits": v.get("aot_hits", 0),
                    "signatures": list(v["signatures"]),
                    "sig_counts": dict(v["sig_counts"])}
                for k, v in self._per_key.items()
                if k.startswith(prefix)
            }

    def snapshot(self) -> CountersSnapshot:
        with self._lock:
            counts = dict(self._counts)
            per_key = {
                k: {"compiles": v["compiles"], "cache_hits": v["cache_hits"],
                    "aot_hits": v.get("aot_hits", 0),
                    "signatures": list(v["signatures"]),
                    "sig_counts": dict(v["sig_counts"])}
                for k, v in self._per_key.items()
            }
        # Cost registry read AFTER the counts, then trimmed to the counted
        # signatures. The recorder harvests a fresh program's cost BEFORE
        # ticking its compile counter, so every signature visible in per_key
        # already has its cost entry by the time the counts were copied —
        # a concurrent snapshot can never catch a compile without its cost
        # (the 1:1 reconciliation invariant); entries harvested after the
        # counts copy are dropped from THIS snapshot, not lost.
        costs: Dict[str, Dict[str, Any]] = {}
        if self._costs is not None:
            for key, sigs in self._costs.snapshot().items():
                counted = set(per_key.get(key, {}).get("signatures", ()))
                kept = {s: r for s, r in sigs.items() if s in counted}
                if kept:
                    costs[key] = kept
        return CountersSnapshot(counts=counts, per_key=per_key, costs=costs)

    def reset(self) -> None:
        with self._lock:
            self._counts = {f: 0 for f in COUNTER_FIELDS}
            self._per_key = {}
        if self._costs is not None:
            self._costs.reset()


# ---------------------------------------------------------------------------
# fleet aggregation (pure merge; the gather plane lives in parallel/sync.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FleetSnapshot:
    """Pod-wide counter rollup: N per-rank snapshots merged into one view.

    ``totals`` is the exact fieldwise sum of the per-rank counts; ``per_key``
    is the union of per-rank dispatch records (available only when full
    snapshots were aggregated — the cross-host gather ships counts vectors
    only, metadata-sized); ``stragglers`` attributes sync-time skew to ranks.
    """

    per_rank: Tuple[Dict[str, int], ...]
    totals: Dict[str, int]
    per_key: Dict[str, Dict[str, Any]]
    stragglers: Dict[str, Any]

    @property
    def ranks(self) -> int:
        return len(self.per_rank)

    def __getitem__(self, name: str) -> int:
        return self.totals[name]

    def summary(self, brief: bool = False) -> Dict[str, Any]:
        if brief:
            keys = (
                "dispatches", "jit_compiles", "jit_cache_hits", "retraces",
                "host_dispatches", "d2h_readbacks", "sync_calls",
                "gathers_coalesced", "serve_dispatches",
            )
            return {
                "fleet": True, "ranks": self.ranks,
                **{k: self.totals[k] for k in keys},
                "collectives_per_sync": _collectives_per_sync(self.totals),
                "tenants_per_dispatch": _tenants_per_dispatch(self.totals),
                "stragglers": dict(self.stragglers),
            }
        return {
            "fleet": True,
            "ranks": self.ranks,
            "totals": dict(self.totals),
            "per_rank": [dict(r) for r in self.per_rank],
            "per_key": {k: dict(v) for k, v in self.per_key.items()},
            "stragglers": dict(self.stragglers),
        }


def _rank_counts(snap: Union["CountersSnapshot", Mapping[str, int], Sequence[int]]) -> Dict[str, int]:
    """Normalize one rank's contribution: a full snapshot, a counts mapping, or
    the bare counts vector the gather plane ships."""
    if isinstance(snap, CountersSnapshot):
        return {f: int(snap.counts.get(f, 0)) for f in COUNTER_FIELDS}
    if isinstance(snap, Mapping):
        return {f: int(snap.get(f, 0)) for f in COUNTER_FIELDS}
    values = list(snap)
    if len(values) != len(COUNTER_FIELDS):
        raise ValueError(
            f"counts vector has {len(values)} entries, expected {len(COUNTER_FIELDS)} "
            f"({', '.join(COUNTER_FIELDS)})"
        )
    return {f: int(v) for f, v in zip(COUNTER_FIELDS, values)}


def _skew(per_rank: Sequence[Dict[str, int]], field: str) -> Dict[str, int]:
    values = [r[field] for r in per_rank]
    lo, hi = min(values), max(values)
    return {
        "min": lo, "max": hi, "skew": hi - lo,
        "min_rank": values.index(lo), "max_rank": values.index(hi),
    }


def aggregate_counters(
    snapshots: Sequence[Union["CountersSnapshot", Mapping[str, int], Sequence[int]]],
) -> FleetSnapshot:
    """Merge per-rank counter snapshots into one fleet view (pure, stdlib).

    ``totals`` equals the exact fieldwise sum of the inputs — the invariant the
    acceptance test pins — and ``stragglers`` carries per-rank min/max skew for
    the sync-time and sync-call fields (the rank holding the max sync time is
    the pod's straggler candidate). Accepts full :class:`CountersSnapshot`
    objects (simulated ranks, tests), plain counts mappings, or the raw counts
    vectors the gather plane returns.
    """
    if not snapshots:
        raise ValueError("aggregate_counters needs at least one rank snapshot")
    per_rank = tuple(_rank_counts(s) for s in snapshots)
    totals = {f: sum(r[f] for r in per_rank) for f in COUNTER_FIELDS}
    per_key: Dict[str, Dict[str, Any]] = {}
    for snap in snapshots:
        if not isinstance(snap, CountersSnapshot):
            continue
        for key, rec in snap.per_key.items():
            merged = per_key.setdefault(
                key, {"compiles": 0, "cache_hits": 0, "aot_hits": 0, "signatures": [], "sig_counts": {}}
            )
            merged["compiles"] += rec["compiles"]
            merged["cache_hits"] += rec["cache_hits"]
            merged["aot_hits"] += rec.get("aot_hits", 0)
            for sig in rec["signatures"]:
                if sig not in merged["signatures"]:
                    merged["signatures"].append(sig)
            for sig, n in rec.get("sig_counts", {}).items():
                merged["sig_counts"][sig] = merged["sig_counts"].get(sig, 0) + n
    stragglers = {
        "sync_time_us": _skew(per_rank, "sync_time_us"),
        "sync_calls": _skew(per_rank, "sync_calls"),
    }
    return FleetSnapshot(per_rank=per_rank, totals=totals, per_key=per_key, stragglers=stragglers)
