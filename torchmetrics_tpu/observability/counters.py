"""Process-wide telemetry counters.

Everything the runtime can observe without a device→host readback is counted
here: jitted dispatches split into compiles vs cache hits per ``_jit_cache`` key
(first-seen input shape/dtype signature == a trace/compile; a repeat == a cache
hit, mirroring ``jax.jit``'s own cache discipline), retraces (every compile
beyond a key's first), device→host readbacks at the runtime's instrumented
sites (``state_dict``, ``compute_on_cpu`` appends, finiteness guards),
``process_sync`` invocations with payload bytes (computed from array metadata —
``shape``/``dtype`` never touch the device), and the reliability layer's
retry/quarantine totals.

The registry is pure stdlib (no jax import): the bench driver and
``tools/trace_report.py`` consume snapshots without initializing a runtime.
Counting happens only while a telemetry session is active — a disabled process
never calls into this module from a dispatch path.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Tuple

# every scalar the registry tracks, in reporting order
COUNTER_FIELDS: Tuple[str, ...] = (
    "dispatches",  # jitted donated dispatches (update/forward tensor path)
    "jit_compiles",  # first-seen (key, signature) pairs — one XLA trace each
    "jit_cache_hits",  # repeat signatures — served from jit's cache
    "retraces",  # compiles beyond a key's first (shape/dtype churn)
    "host_dispatches",  # HostMetric update/forward (eager, never jitted)
    "computes",  # Metric.compute invocations
    "d2h_readbacks",  # device→host transfers at instrumented runtime sites
    "d2h_bytes",
    "sync_calls",  # process_sync invocations
    "sync_payload_bytes",  # bytes entering the cross-process gather
    "gather_calls",  # gather_all_arrays collectives (one per state leaf)
    "retries",  # transient failures accepted for retry
    "retries_exhausted",  # retry budgets that ran out on a transient failure
    "quarantines",  # metrics frozen by MetricCollection(on_error="quarantine")
    "skips",  # per-batch skips under on_error="skip"
)


@dataclasses.dataclass(frozen=True)
class CountersSnapshot:
    """Immutable point-in-time view of a :class:`Counters` registry."""

    counts: Dict[str, int]
    per_key: Dict[str, Dict[str, Any]]

    def __getitem__(self, name: str) -> int:
        return self.counts[name]

    def diff(self, earlier: "CountersSnapshot") -> "CountersSnapshot":
        """This snapshot minus an ``earlier`` one (per-key signatures: only the
        ones that appeared in between)."""
        counts = {k: v - earlier.counts.get(k, 0) for k, v in self.counts.items()}
        per_key: Dict[str, Dict[str, Any]] = {}
        for key, rec in self.per_key.items():
            old = earlier.per_key.get(key, {})
            old_sigs = set(old.get("signatures", ()))
            delta = {
                "compiles": rec["compiles"] - old.get("compiles", 0),
                "cache_hits": rec["cache_hits"] - old.get("cache_hits", 0),
                "signatures": [s for s in rec["signatures"] if s not in old_sigs],
            }
            if delta["compiles"] or delta["cache_hits"] or delta["signatures"]:
                per_key[key] = delta
        return CountersSnapshot(counts=counts, per_key=per_key)

    def summary(self, brief: bool = False) -> Dict[str, Any]:
        """Flat JSON-friendly dict. ``brief`` keeps only the headline counters
        (the shape bench configs embed next to ``attempts``/``recovered_from``)."""
        if brief:
            keys = (
                "dispatches", "jit_compiles", "jit_cache_hits", "retraces",
                "host_dispatches", "d2h_readbacks", "sync_calls",
            )
            return {k: self.counts[k] for k in keys}
        out: Dict[str, Any] = dict(self.counts)
        out["per_key"] = {
            k: {"compiles": v["compiles"], "cache_hits": v["cache_hits"],
                "signatures": list(v["signatures"])}
            for k, v in self.per_key.items()
        }
        return out


class Counters:
    """Mutable counters registry (one per telemetry session; thread-safe)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {f: 0 for f in COUNTER_FIELDS}
        # "ClassName#id.tag" -> {"compiles", "cache_hits", "signatures": [..]}
        self._per_key: Dict[str, Dict[str, Any]] = {}

    # -------------------------------------------------------------- recording

    def record_dispatch(self, key: str, signature: str) -> Tuple[bool, int]:
        """One successful jitted dispatch under ``key`` with the given input
        ``signature``. Returns ``(is_new_signature, n_signatures_for_key)``."""
        with self._lock:
            rec = self._per_key.setdefault(
                # "signatures" keeps first-seen order for reports; "_sig_set" is
                # the O(1) membership twin — a retrace storm (the pathology this
                # counter diagnoses) must not make its own bookkeeping O(n)
                key, {"compiles": 0, "cache_hits": 0, "signatures": [], "_sig_set": set()}
            )
            self._counts["dispatches"] += 1
            if signature in rec["_sig_set"]:
                rec["cache_hits"] += 1
                self._counts["jit_cache_hits"] += 1
                return False, len(rec["signatures"])
            rec["signatures"].append(signature)
            rec["_sig_set"].add(signature)
            rec["compiles"] += 1
            self._counts["jit_compiles"] += 1
            if len(rec["signatures"]) > 1:
                self._counts["retraces"] += 1
            return True, len(rec["signatures"])

    def record_host_dispatch(self) -> None:
        with self._lock:
            self._counts["host_dispatches"] += 1

    def record_compute(self) -> None:
        with self._lock:
            self._counts["computes"] += 1

    def record_d2h(self, nbytes: int) -> None:
        with self._lock:
            self._counts["d2h_readbacks"] += 1
            self._counts["d2h_bytes"] += int(nbytes)

    def record_sync(self, payload_bytes: int) -> None:
        with self._lock:
            self._counts["sync_calls"] += 1
            self._counts["sync_payload_bytes"] += int(payload_bytes)

    def record_gather(self) -> None:
        with self._lock:
            self._counts["gather_calls"] += 1

    def record_retry(self) -> None:
        with self._lock:
            self._counts["retries"] += 1

    def record_retry_exhausted(self) -> None:
        with self._lock:
            self._counts["retries_exhausted"] += 1

    def record_quarantine(self, status: str) -> None:
        with self._lock:
            self._counts["quarantines" if status == "quarantined" else "skips"] += 1

    # --------------------------------------------------------------- querying

    def value(self, name: str) -> int:
        with self._lock:
            return self._counts[name]

    def signatures(self, key: str) -> List[str]:
        with self._lock:
            rec = self._per_key.get(key)
            return list(rec["signatures"]) if rec else []

    def keys_for(self, prefix: str) -> Dict[str, Dict[str, Any]]:
        """Per-key records whose key starts with ``prefix`` (instance lookup:
        keys are ``ClassName#id.tag``, so ``ClassName#id.`` selects one metric)."""
        with self._lock:
            return {
                k: {"compiles": v["compiles"], "cache_hits": v["cache_hits"],
                    "signatures": list(v["signatures"])}
                for k, v in self._per_key.items()
                if k.startswith(prefix)
            }

    def snapshot(self) -> CountersSnapshot:
        with self._lock:
            return CountersSnapshot(
                counts=dict(self._counts),
                per_key={
                    k: {"compiles": v["compiles"], "cache_hits": v["cache_hits"],
                        "signatures": list(v["signatures"])}
                    for k, v in self._per_key.items()
                },
            )

    def reset(self) -> None:
        with self._lock:
            self._counts = {f: 0 for f in COUNTER_FIELDS}
            self._per_key = {}
