"""Prometheus-style export: text rendering, background flusher, health server.

Three pieces, all fed from **snapshots** so the hot path is never touched:

- :func:`render_prometheus` — the active session's counters, cost totals,
  state-memory footprints, histograms, and SLO states as Prometheus text
  exposition format (``# HELP``/``# TYPE`` + samples; histograms as the
  standard cumulative ``_bucket{le=…}`` / ``_sum`` / ``_count`` triplet, with
  latency buckets converted to seconds per Prometheus convention).
- :class:`MetricsFlusher` — a daemon thread that periodically snapshots the
  recorder, renders, and atomically replaces a file on disk (write-new +
  ``os.replace``, so a scraping sidecar never reads a torn file). The flusher
  also feeds/evaluates the SLO engine on its own cadence, which keeps rules
  live even in loops that never sync.
- :class:`HealthServer` — a stdlib ``ThreadingHTTPServer`` serving
  ``/healthz`` (liveness + SLO verdict; 503 while a *critical* rule is
  breached), ``/metricsz`` (the Prometheus text), ``/costz`` (compiled-cost
  accounting as JSON), ``/sloz`` (rule states + recent alerts as JSON),
  ``/fleetz`` (the live fleet controller's rollup), and ``/historyz`` (the
  telemetry history's retained levels; ``?at=``/``?level=`` time-travel
  queries) — each request takes fresh snapshots, so what a scraper sees is
  live. The full endpoint table lives in ``docs/observability.md``.

Everything degrades gracefully with no active session: the renderer emits the
``telemetry_enabled 0`` gauge and whatever a passed-in recorder holds; the
server answers 200/ok with ``"telemetry": false``.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from . import histograms as _histograms

# every exported sample name carries this prefix (Prometheus namespacing)
PREFIX = "tpu_metrics"

_COUNTER_HELP = {
    "dispatches": "jitted donated dispatches (update/forward tensor path)",
    "jit_compiles": "first-seen (key, signature) pairs — one XLA trace each",
    "jit_cache_hits": "repeat signatures served from jit's cache",
    "retraces": "compiles beyond a key's first (shape/dtype churn)",
    "d2h_readbacks": "instrumented device-to-host transfers",
    "sync_calls": "process_sync invocations",
    "sync_collectives": "collectives launched by the sync planes",
    "retries": "transient failures accepted for retry",
    "retries_exhausted": "retry budgets exhausted on a transient failure",
    "quarantines": "metrics frozen by on_error='quarantine'",
    "state_growths": "cat states past the unbounded-growth sentinel",
    "alerts": "SLO alerts emitted",
    "flightrec_dumps": "postmortem artifacts dumped by the flight recorder",
}


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _sanitize_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _fmt(value: Any) -> str:
    if value is None:
        return "NaN"
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Lines:
    """Accumulates exposition lines with one HELP/TYPE header per family."""

    def __init__(self) -> None:
        self.lines: List[str] = []
        self._seen: set = set()

    def header(self, name: str, kind: str, help_text: str) -> None:
        if name in self._seen:
            return
        self._seen.add(name)
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {kind}")

    def sample(self, name: str, labels: Dict[str, str], value: Any) -> None:
        if labels:
            inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in labels.items())
            self.lines.append(f"{name}{{{inner}}} {_fmt(value)}")
        else:
            self.lines.append(f"{name} {_fmt(value)}")

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"


def _render_histogram(out: _Lines, family: str, help_text: str, unit_div: float,
                      labels: Dict[str, str], hist: "_histograms.Histogram") -> None:
    """One histogram in the standard cumulative form. ``unit_div`` converts
    the bucket bounds out of the recording unit (1e6 for us→seconds, 1 for
    bytes)."""
    out.header(family, "histogram", help_text)
    cum = 0
    # the top bucket is open-ended (bucket_index clamps overflows into it), so
    # it gets NO finite le line — claiming its observations are <= 2^32 units
    # would break cumulative semantics; +Inf is its honest upper bound
    for b, count in enumerate(hist.counts[: _histograms.N_BUCKETS - 1]):
        cum += count
        if count == 0 and b > 0:
            continue  # sparse: always emit the first bound, skip empty middles
        le = _histograms.bucket_bounds(b)[1] / unit_div
        out.sample(f"{family}_bucket", {**labels, "le": repr(float(le))}, cum)
    out.sample(f"{family}_bucket", {**labels, "le": "+Inf"}, hist.count)
    out.sample(f"{family}_sum", labels, hist.total / unit_div)
    out.sample(f"{family}_count", labels, hist.count)


def render_prometheus(recorder: Any = None) -> str:
    """Render a recorder's full state (counters, costs, memory, histograms,
    SLOs) as Prometheus text exposition format. ``recorder=None`` uses the
    active session (and renders a minimal liveness document when telemetry is
    disabled)."""
    from . import active as _active

    rec = recorder if recorder is not None else _active()
    out = _Lines()
    out.header(f"{PREFIX}_telemetry_enabled", "gauge", "1 while a telemetry session is active")
    out.sample(f"{PREFIX}_telemetry_enabled", {}, 0 if rec is None else 1)
    if rec is None:
        return out.render()

    snap = rec.counters.snapshot()
    for field, value in snap.counts.items():
        name = f"{PREFIX}_{_sanitize_name(field)}_total"
        out.header(name, "counter", _COUNTER_HELP.get(field, f"session counter {field}"))
        out.sample(name, {}, value)
    syncs = snap.counts.get("sync_calls", 0)
    name = f"{PREFIX}_collectives_per_sync"
    out.header(name, "gauge", "collectives launched per sync (coalescing headline)")
    out.sample(name, {}, (snap.counts.get("sync_collectives", 0) / syncs) if syncs else 0.0)

    totals = snap.cost_totals() if snap.costs else {}
    for field, value in totals.items():
        name = f"{PREFIX}_cost_{_sanitize_name(field)}"
        out.header(name, "gauge", f"dispatch-weighted compiled-cost total: {field}")
        out.sample(name, {}, value)

    mem = rec.memory_snapshot()
    if mem:
        cur = f"{PREFIX}_state_bytes"
        peak = f"{PREFIX}_state_peak_bytes"
        out.header(cur, "gauge", "current metric state footprint (metadata-derived bytes)")
        out.header(peak, "gauge", "peak metric state footprint this session")
        for metric_name, report in mem.items():
            out.sample(cur, {"metric": metric_name}, report.get("current_bytes", 0))
            out.sample(peak, {"metric": metric_name}, report.get("peak_bytes", 0))

    lat_family = f"{PREFIX}_latency_seconds"
    size_family = f"{PREFIX}_size_bytes"
    for kind, keys in sorted(rec.histograms.snapshot().items()):
        is_size = kind in _histograms.SIZE_KINDS
        for key, hist in sorted(keys.items()):
            _render_histogram(
                out,
                size_family if is_size else lat_family,
                "sync-plane payload size distribution" if is_size
                else "dispatch-boundary latency distribution (log2 buckets)",
                1.0 if is_size else 1e6,
                {"kind": kind, "key": key},
                hist,
            )

    slo = rec.slo.snapshot()
    if slo["rules"]:
        breached = f"{PREFIX}_slo_breached"
        trips = f"{PREFIX}_slo_breaches_total"
        alerts = f"{PREFIX}_slo_alerts_total"
        out.header(breached, "gauge", "1 while the rule's expression currently evaluates true")
        out.header(trips, "counter", "evaluations that found the rule breached")
        out.header(alerts, "counter", "alerts actually emitted (cooldown-gated)")
        for rule_name, state in slo["rules"].items():
            labels = {"rule": rule_name, "severity": state["severity"]}
            out.sample(breached, labels, 1 if state["breached"] else 0)
            out.sample(trips, labels, state["breaches"])
            out.sample(alerts, labels, state["alerts"])
    return out.render()


# ---------------------------------------------------------------------------
# background flusher
# ---------------------------------------------------------------------------


class MetricsFlusher:
    """Periodically render the active session to ``path`` from a daemon
    thread — the scrape file a node-exporter-style sidecar tails, produced
    without ever touching the dispatch hot path.

    Each tick: snapshot → render → write ``path + ".tmp"`` → ``os.replace``
    (atomic on POSIX, so readers never see a torn document), then feed and
    evaluate the SLO engine (keeping rules live for loops that never sync).
    ``interval_s`` is wall-clock between ticks; ``flush_now()`` forces one
    synchronously (also what ``stop()`` does on the way out, so the file's
    final state covers the whole session).
    """

    def __init__(self, path: str, interval_s: float = 5.0) -> None:
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.path = str(path)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._flush_lock = threading.Lock()  # worker tick vs stop()'s final flush
        self.flushes = 0

    def flush_now(self) -> str:
        """One synchronous snapshot→render→atomic-replace; returns the text.
        Serialized against the worker thread, and each write uses its own tmp
        name — two flushes can never interleave bytes into one tmp file, so
        ``os.replace`` always publishes a complete document."""
        from . import active as _active

        rec = _active()
        if rec is not None and rec.slo.rules:
            rec.evaluate_slos()
        text = render_prometheus(rec)
        with self._flush_lock:
            tmp = f"{self.path}.{os.getpid()}.{threading.get_ident()}.tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as fh:
                    fh.write(text)
                os.replace(tmp, self.path)
            finally:
                if os.path.exists(tmp):  # a failed replace must not leave droppings
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
            self.flushes += 1
        return text

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.flush_now()
            except Exception:  # noqa: BLE001 — a flush hiccup must not kill the thread
                continue

    def start(self) -> "MetricsFlusher":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="tpu-metrics-flusher", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1.0)
            self._thread = None
        try:
            self.flush_now()  # final state on disk covers the whole session
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self) -> "MetricsFlusher":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# health endpoint (stdlib http.server)
# ---------------------------------------------------------------------------


def _healthz_doc() -> Tuple[int, Dict[str, Any]]:
    from . import active as _active

    rec = _active()
    if rec is None:
        return 200, {"status": "ok", "telemetry": False}
    rec.evaluate_slos()  # the liveness answer reflects the rules RIGHT NOW
    critical = rec.slo.breached(min_severity="critical")
    breached = rec.slo.breached()
    doc = {
        "status": "critical" if critical else ("degraded" if breached else "ok"),
        "telemetry": True,
        "breached_rules": breached,
        "counters": rec.counters.snapshot().summary(brief=True),
    }
    return (503 if critical else 200), doc


def _costz_doc() -> Tuple[int, Dict[str, Any]]:
    from . import active as _active

    rec = _active()
    if rec is None:
        return 200, {"telemetry": False}
    return 200, {
        "telemetry": True,
        "cost_totals": rec.cost_summary(),
        "per_key": rec.cost_snapshot(),
        "state_memory": rec.memory_snapshot(),
    }


def _sloz_doc() -> Tuple[int, Dict[str, Any]]:
    from . import active as _active

    rec = _active()
    if rec is None:
        return 200, {"telemetry": False}
    rec.evaluate_slos()
    return 200, {"telemetry": True, **rec.slo_snapshot()}


def _fleetz_doc() -> Tuple[int, Dict[str, Any]]:
    """The fleet control tower: the live controller's rollup, if one exists.

    The controller registers itself weakly at construction (cleared on
    ``close()``); the lazy import keeps the health plane importable without
    the fleet/serving stack."""
    try:
        from ..fleet import controller as _fleet_controller
    except Exception:  # noqa: BLE001 — health must answer even if fleet can't import
        return 200, {"fleet": False}
    fc = _fleet_controller.active_controller()
    if fc is None:
        return 200, {"fleet": False}
    return 200, {"fleet": True, **fc.telemetry()}


def _historyz_doc(query: str) -> Tuple[int, Dict[str, Any]]:
    """The telemetry-history time machine over HTTP.

    No params: every retained level with its block boundaries
    (``history.levels()``). ``?at=T``: the finest retained block covering
    instant ``T`` — byte-for-byte what ``history.at(T)`` answers in-process.
    ``?level=i``: that level's blocks only. Degrades to
    ``{"telemetry": false}`` with no active session (or history disabled)."""
    from . import active as _active

    rec = _active()
    if rec is None or rec.history is None:
        return 200, {"telemetry": False}
    params = urllib.parse.parse_qs(query)
    if "at" in params:
        try:
            t = float(params["at"][0])
        except (ValueError, IndexError):
            return 400, {"telemetry": True, "error": "?at= expects a float timestamp"}
        return 200, {"telemetry": True, "at": t, "block": rec.history.at(t)}
    if "level" in params:
        try:
            level = int(params["level"][0])
            blocks = rec.history.range(float("-inf"), float("inf"), level=level)
        except (ValueError, IndexError):
            return 400, {"telemetry": True, "error": "?level= expects a valid level index"}
        return 200, {"telemetry": True, "level": level, "blocks": blocks}
    return 200, {"telemetry": True, "history": rec.history.levels()}


class _HealthHandler(BaseHTTPRequestHandler):
    server_version = "tpu-metrics-health/1"

    def do_GET(self) -> None:  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0].rstrip("/") or "/healthz"
        try:
            if path == "/healthz":
                status, doc = _healthz_doc()
                self._reply(status, json.dumps(doc, default=str), "application/json")
            elif path == "/metricsz":
                self._reply(200, render_prometheus(), "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/costz":
                status, doc = _costz_doc()
                self._reply(status, json.dumps(doc, default=str), "application/json")
            elif path == "/sloz":
                status, doc = _sloz_doc()
                self._reply(status, json.dumps(doc, default=str), "application/json")
            elif path == "/fleetz":
                status, doc = _fleetz_doc()
                self._reply(status, json.dumps(doc, default=str), "application/json")
            elif path == "/historyz":
                query = self.path.split("?", 1)[1] if "?" in self.path else ""
                status, doc = _historyz_doc(query)
                self._reply(status, json.dumps(doc, default=str), "application/json")
            else:
                self._reply(
                    404,
                    json.dumps({"error": f"unknown path {path}",
                                "endpoints": ["/healthz", "/metricsz", "/costz",
                                              "/sloz", "/fleetz", "/historyz"]}),
                    "application/json",
                )
        except Exception as err:  # noqa: BLE001 — a render bug must answer 500, not hang
            self._reply(500, json.dumps({"error": f"{type(err).__name__}: {err}"[:500]}),
                        "application/json")

    def _reply(self, status: int, body: str, content_type: str) -> None:
        data = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt: str, *args: Any) -> None:  # silence per-request stderr
        pass


class HealthServer:
    """The live health endpoint: ``ThreadingHTTPServer`` on a daemon thread,
    answering from fresh snapshots of whatever telemetry session is active at
    request time (it holds no recorder reference — sessions can come and go
    under a long-lived server).

    ``port=0`` binds an ephemeral port (tests); :attr:`port` is the bound one.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 8000) -> None:
        self._httpd = ThreadingHTTPServer((host, port), _HealthHandler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "HealthServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="tpu-metrics-health", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "HealthServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
