"""Structured telemetry events and pluggable sinks.

One event per observable runtime moment — a jitted dispatch, a compute, a
cross-process sync, a retry, a quarantine, a retrace, an instrumented
device→host readback. Timestamps are **monotonic-clock** (``time.monotonic``):
telemetry orders and measures, it does not tell wall-clock time (a trace
consumer that needs an epoch anchor records one itself at session start).

Sinks are deliberately tiny: ``emit(event)`` plus optional ``close()``. The
runtime never constructs an event unless a telemetry session is active, so a
slow sink can only ever tax an opted-in process.

Everything here is stdlib-only; ``tools/trace_report.py`` re-reads the JSONL
output without importing jax.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import socket
import threading
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

# the closed set of event kinds the runtime emits
EVENT_KINDS: Tuple[str, ...] = (
    "dispatch",  # a jitted (or HostMetric eager) update/forward dispatch
    "compute",  # Metric.compute
    "sync",  # Metric.sync through process_sync
    "retry",  # a transient failure accepted for retry
    "retry_exhausted",  # retry budget ran out on a transient failure
    "quarantine",  # MetricCollection froze/skipped a failing member
    "retrace",  # a dispatch key saw a NEW shape/dtype signature (recompile)
    "aot_load",  # a serialized executable was loaded from the AOT cache (aot/)
    "d2h",  # an instrumented device→host readback
    "state_growth",  # a list/cat state crossed the unbounded-growth threshold
    "alert",  # an SLO rule breached (or errored) — observability/slo.py
    "hist",  # a latency/size histogram snapshot (flushed at session close)
    "serve",  # a megabatched stacked-state dispatch (serving engine)
    "tenant_spill",  # tenant state spilled to host / readmitted into a stack
    "window_roll",  # a SlidingWindow completed a full window wrap (streaming plane)
    "async_sync",  # a double-buffered background sync committed (overlap accounting)
    "serve_rejected",  # a tenant batch shed by the serving admission rate limit
    "quant",  # a coalesced sync shipped quantized buckets (compression accounting)
    "snapshot",  # a crash-consistent engine snapshot written or restored (durability plane)
    "journal",  # write-ahead journal records replayed into a restored engine
    "degraded_sync",  # a coalesced sync completed over a survivor quorum (dead rank)
    "rank_rejoin",  # a previously dead rank reconciled back into the coalesced sync
    "migration",  # a committed host-to-host tenant migration (fleet plane)
    "failover",  # a dead host's tenants adopted by survivors (fleet plane)
    "flightrec",  # the flight recorder dumped a postmortem artifact
    "history",  # the telemetry history telescoped retained blocks (timeseries plane)
    "burn_alert",  # a multi-window burn-rate rule paged (short AND long window burned)
)


@dataclasses.dataclass(frozen=True)
class TelemetryEvent:
    """One structured telemetry record.

    Args:
        kind: one of :data:`EVENT_KINDS`.
        metric: metric identity — ``ClassName#instance_id`` for runtime events,
            a collection key for quarantine events, a ``describe`` string for
            retry events.
        tag: dispatch tag / stage (``update``/``forward``/``compute``/``sync``,
            or a site name for ``d2h``).
        timestamp: ``time.monotonic()`` at emission.
        duration_s: measured span for dispatch/compute/sync events (honest
            wall-clock only under the blocking-timing mode — async dispatch
            returns before the device finishes).
        signature: the input shape/dtype key for dispatch/retrace events.
        cache_hit: for dispatch events — False on the signature's first sight.
        trace_id / span_id / parent_id: causal trace linkage (deterministic
            sha256-derived ids from ``observability/spans.py``) — stamped by
            the recorder when a span is active, ``None`` otherwise.
        payload: kind-specific extras (attempt numbers, error reprs, byte
            counts, ...).
    """

    kind: str
    metric: str
    tag: str
    timestamp: float
    duration_s: Optional[float] = None
    signature: Optional[str] = None
    cache_hit: Optional[bool] = None
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_id: Optional[str] = None
    payload: Mapping[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "kind": self.kind,
            "metric": self.metric,
            "tag": self.tag,
            "timestamp": round(self.timestamp, 9),
        }
        if self.duration_s is not None:
            out["duration_s"] = round(self.duration_s, 9)
        if self.signature is not None:
            out["signature"] = self.signature
        if self.cache_hit is not None:
            out["cache_hit"] = self.cache_hit
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.span_id is not None:
            out["span_id"] = self.span_id
        if self.parent_id is not None:
            out["parent_id"] = self.parent_id
        if self.payload:
            out["payload"] = dict(self.payload)
        return out


class Sink:
    """Sink protocol: receives every event of a session."""

    def emit(self, event: TelemetryEvent) -> None:  # pragma: no cover - protocol
        raise NotImplementedError

    def close(self) -> None:
        """Flush/release resources at session end. Default: nothing."""


class RingBufferSink(Sink):
    """Bounded in-memory event buffer (oldest events evicted first; O(1) emit —
    this sink sits on the instrumented dispatch path)."""

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._events: "collections.deque[TelemetryEvent]" = collections.deque(maxlen=capacity)
        self.evicted = 0  # how many events fell off the front
        # server/flusher threads emit alerts while the training thread emits
        # dispatches and readers snapshot — iterating a deque mid-append raises
        self._emit_lock = threading.Lock()

    def emit(self, event: TelemetryEvent) -> None:
        with self._emit_lock:
            if len(self._events) == self.capacity:
                self.evicted += 1  # deque(maxlen) drops the oldest on append
            self._events.append(event)

    @property
    def events(self) -> Tuple[TelemetryEvent, ...]:
        with self._emit_lock:
            return tuple(self._events)

    def of_kind(self, *kinds: str) -> Tuple[TelemetryEvent, ...]:
        return tuple(e for e in self.events if e.kind in kinds)

    def drain(self) -> Tuple[TelemetryEvent, ...]:
        with self._emit_lock:
            out = tuple(self._events)
            self._events.clear()
            return out


class JSONLSink(Sink):
    """Appends one JSON line per event to ``path`` (opened lazily). The format
    is what ``tools/trace_report.py`` renders.

    ``flush_every=1`` (the default) flushes per event so a crashed process
    still leaves a readable trace; raising it batches flushes for hot sessions.
    Either way ``close()`` — and context-manager exit, which routes through it —
    flushes AND fsyncs, so a trace ``scp``'d off a preempted host ends on a
    complete line. A line truncated by a hard kill mid-write is still possible;
    ``trace_report.py``'s skip-bad-line tolerance covers that tail case.

    Every line carries a ``host`` field (``host=`` override, defaulting to
    ``socket.gethostname()``) so JSONL files merged across a fleet attribute
    each event to its emitter — ``trace_report.py`` uses it as the rank label
    when no explicit ``--rank`` mapping is given.
    """

    def __init__(self, path: str, flush_every: int = 1, host: Optional[str] = None) -> None:
        if flush_every < 1:
            raise ValueError(f"flush_every must be >= 1, got {flush_every}")
        self.path = str(path)
        self.flush_every = flush_every
        if host is None:
            host = socket.gethostname()
        self.host = str(host)
        self._fh = None
        self._unflushed = 0
        self.written = 0
        # the health plane emits from server/flusher threads too — the lazy
        # open, the write, and the flush counter must not interleave with the
        # training thread's events (a merged line is a silently dropped event)
        self._emit_lock = threading.Lock()

    def emit(self, event: TelemetryEvent) -> None:
        record = event.to_dict()
        record["host"] = self.host
        line = json.dumps(record) + "\n"
        with self._emit_lock:
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write(line)
            self.written += 1
            self._unflushed += 1
            if self._unflushed >= self.flush_every:
                self._fh.flush()
                self._unflushed = 0

    def close(self) -> None:
        with self._emit_lock:
            if self._fh is not None:
                self._fh.flush()
                try:
                    os.fsync(self._fh.fileno())
                except OSError:  # non-seekable/pseudo files: flushed is the best we get
                    pass
                self._fh.close()
                self._fh = None
                self._unflushed = 0

    def __enter__(self) -> "JSONLSink":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class CallbackSink(Sink):
    """Routes events to user hooks by kind.

    ``on_update`` fires for dispatch events (tags ``update``/``forward``),
    ``on_compute`` for compute, ``on_sync`` for sync, ``on_retry`` for
    retry/retry_exhausted, ``on_quarantine`` for quarantine. ``on_event``
    fires for *every* event (including retrace/d2h). Hook exceptions propagate
    — a monitoring callback that raises is a bug worth surfacing, not
    swallowing.
    """

    def __init__(
        self,
        on_update: Optional[Callable[[TelemetryEvent], None]] = None,
        on_compute: Optional[Callable[[TelemetryEvent], None]] = None,
        on_sync: Optional[Callable[[TelemetryEvent], None]] = None,
        on_retry: Optional[Callable[[TelemetryEvent], None]] = None,
        on_quarantine: Optional[Callable[[TelemetryEvent], None]] = None,
        on_event: Optional[Callable[[TelemetryEvent], None]] = None,
    ) -> None:
        self.on_update = on_update
        self.on_compute = on_compute
        self.on_sync = on_sync
        self.on_retry = on_retry
        self.on_quarantine = on_quarantine
        self.on_event = on_event

    def emit(self, event: TelemetryEvent) -> None:
        if self.on_event is not None:
            self.on_event(event)
        if event.kind == "dispatch":
            if self.on_update is not None and event.tag in ("update", "forward"):
                self.on_update(event)
        elif event.kind == "compute":
            if self.on_compute is not None:
                self.on_compute(event)
        elif event.kind == "sync":
            if self.on_sync is not None:
                self.on_sync(event)
        elif event.kind in ("retry", "retry_exhausted"):
            if self.on_retry is not None:
                self.on_retry(event)
        elif event.kind == "quarantine":
            if self.on_quarantine is not None:
                self.on_quarantine(event)
