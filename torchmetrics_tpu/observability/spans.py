"""Request-scoped causal trace contexts (the span plane).

A *span* is a lightweight ``(trace_id, span_id, parent_id)`` triple carried
on a thread-local stack and stamped onto every :class:`TelemetryEvent`
emitted while it is active (``TelemetryRecorder._event`` reads
:func:`current`).  Ids are **deterministic**: sha256 digests of the caller's
identifying parts, truncated to 16 hex chars — no wall clock, no PRNG — so
a seeded soak produces byte-identical trace trees across runs and a
postmortem artifact can be diffed against a replay.

Zero-overhead contract (the PR 2 guard): spans are only *created* inside a
``rec is not None`` branch at the call site.  With telemetry disabled no
:class:`SpanContext` is constructed and no digest is computed — the guard
test in ``tests/test_observability.py`` monkeypatches both with poison to
prove it.  :func:`current` itself is a bare thread-local read and is only
invoked from the recorder (which implies telemetry is on).

Typical shapes::

    with spans.scope("serve", tenant, seq):          # root: derives a trace
        engine.update(tenant, preds, target)         # events inherit the span

    ctx = spans.enter("failover", host, parent=kill_ctx)   # cross-stack link
    try: ...adopt...
    finally: spans.exit(ctx)

Callers are responsible for making the ``parts`` unique where uniqueness
matters (e.g. include a sequence number when the same logical operation
repeats inside one trace).
"""

from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager
from typing import Iterator, Optional

__all__ = [
    "SpanContext",
    "current",
    "derive_span_id",
    "derive_trace_id",
    "enter",
    "exit",
    "scope",
]

_TLS = threading.local()


def _digest(*parts: object) -> str:
    """Deterministic 16-hex-char id from the stringified parts."""
    joined = "|".join(str(p) for p in parts)
    return hashlib.sha256(joined.encode("utf-8")).hexdigest()[:16]


def derive_trace_id(*parts: object) -> str:
    """A trace id from stable identifying parts (seed, step, tenant, ...)."""
    return _digest("trace", *parts)


def derive_span_id(trace_id: str, parent_id: Optional[str], *parts: object) -> str:
    """A span id scoped under ``trace_id``/``parent_id`` from stable parts."""
    return _digest("span", trace_id, parent_id or "", *parts)


class SpanContext:
    """One active span: immutable id triple linking an event into a trace."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str] = None) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"SpanContext(trace_id={self.trace_id!r}, span_id={self.span_id!r}, "
                f"parent_id={self.parent_id!r})")


def current() -> Optional[SpanContext]:
    """The innermost active span on this thread, or ``None``."""
    stack = getattr(_TLS, "stack", None)
    if not stack:
        return None
    return stack[-1]


def enter(*parts: object, trace: Optional[str] = None,
          parent: Optional[SpanContext] = None) -> SpanContext:
    """Push a new span and return it (pair with :func:`exit` in a finally).

    Parent resolution, in order: an explicit ``parent`` context (cross-stack
    linking, e.g. a failover chaining off the kill site), else the current
    thread-local span, else none (a fresh root).  ``trace`` pins the trace
    id explicitly (e.g. a fault-ledger trace); otherwise the parent's trace
    is inherited or a new one derived from ``parts``.
    """
    if parent is None:
        parent = current()
    if trace is not None:
        trace_id = trace
    elif parent is not None:
        trace_id = parent.trace_id
    else:
        trace_id = derive_trace_id(*parts)
    parent_id = parent.span_id if parent is not None else None
    ctx = SpanContext(trace_id, derive_span_id(trace_id, parent_id, *parts), parent_id)
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = []
        _TLS.stack = stack
    stack.append(ctx)
    return ctx


def exit(ctx: SpanContext) -> None:  # noqa: A001 - deliberate pairing with enter()
    """Pop ``ctx`` (and anything leaked above it) off this thread's stack."""
    stack = getattr(_TLS, "stack", None)
    if not stack:
        return
    while stack:
        top = stack.pop()
        if top is ctx:
            break


@contextmanager
def scope(*parts: object, trace: Optional[str] = None,
          parent: Optional[SpanContext] = None) -> Iterator[SpanContext]:
    """Context-manager form of :func:`enter`/:func:`exit`."""
    ctx = enter(*parts, trace=trace, parent=parent)
    try:
        yield ctx
    finally:
        exit(ctx)
