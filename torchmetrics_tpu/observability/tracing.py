"""Profiler integration: xprof annotations and honest dispatch timing.

Two complementary mechanisms, both free when nobody is looking:

- :func:`trace_span` — a host-side ``jax.profiler.TraceAnnotation``. Shows up
  as a named span on the xprof host timeline, so metric work is attributable
  next to the model's steps. Constructed unconditionally at the dispatch
  boundaries (it was already there for ``update``/``compute``; the telemetry
  layer extends it to ``forward``/``sync``) — its cost without an active
  profiler is a counter bump inside jax.
- :func:`graph_scope` — ``jax.named_scope``: a *trace-time* HLO name prefix.
  Zero runtime cost (it only exists while jit is tracing) and it is what makes
  a metric's ops findable in the xprof device view: the fused collection's HLO
  otherwise CSEs four metrics into an anonymous soup.

Timing: async dispatch returns when XLA has *enqueued* the work, so a bare
``monotonic()`` pair measures dispatch latency, not device time. The
blocking-timing mode (``TelemetryConfig(block_until_ready=True)``) inserts
:func:`block_for_timing` after each dispatch for honest per-call wall-clock —
at the price of serializing the pipeline, which is exactly why it is opt-in
per session and never the default.
"""

from __future__ import annotations

import time
from typing import Any

import jax


def trace_span(label: str):
    """Host-side profiler span (xprof host timeline / TraceMe)."""
    return jax.profiler.TraceAnnotation(label)


def graph_scope(label: str):
    """Trace-time HLO name scope — wrap jitted metric bodies so their ops carry
    the metric's name in the xprof device view. No runtime cost."""
    return jax.named_scope(label)


def monotonic() -> float:
    """The telemetry clock (monotonic; never wall time)."""
    return time.monotonic()


def block_for_timing(value: Any) -> Any:
    """Wait for the dispatched work to complete so the surrounding monotonic
    pair measures device wall-clock, not enqueue latency. ``block_until_ready``
    waits on futures without transferring — no device→host readback."""
    return jax.block_until_ready(value)
