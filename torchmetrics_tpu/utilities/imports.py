"""Optional-dependency registry.

Parity: reference ``src/torchmetrics/utilities/imports.py:22-68`` (~45 RequirementCache
flags). Here flags are plain lazy booleans; anything unavailable in the zero-install TPU
image is gated off and the dependent metric raises a clear ModuleNotFoundError.
"""

from __future__ import annotations

import importlib.util
import sys


def _module_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except (ModuleNotFoundError, ValueError):
        return False


_PYTHON_GREATER_EQUAL_3_10 = sys.version_info >= (3, 10)

_JAX_AVAILABLE = _module_available("jax")
_FLAX_AVAILABLE = _module_available("flax")
_TORCH_AVAILABLE = _module_available("torch")  # CPU torch: weight conversion only
_TRANSFORMERS_AVAILABLE = _module_available("transformers")
_SKLEARN_AVAILABLE = _module_available("sklearn")
_SCIPY_AVAILABLE = _module_available("scipy")
_MATPLOTLIB_AVAILABLE = _module_available("matplotlib")
_NLTK_AVAILABLE = _module_available("nltk")
_PESQ_AVAILABLE = _module_available("pesq")
_PYSTOI_AVAILABLE = _module_available("pystoi")
_LIBROSA_AVAILABLE = _module_available("librosa")
_ONNXRUNTIME_AVAILABLE = _module_available("onnxruntime")
_GAMMATONE_AVAILABLE = _module_available("gammatone")
_TORCHAUDIO_AVAILABLE = _module_available("torchaudio")
_TORCHVISION_AVAILABLE = _module_available("torchvision")
_PYCOCOTOOLS_AVAILABLE = _module_available("pycocotools")
_FASTER_COCO_EVAL_AVAILABLE = _module_available("faster_coco_eval")
_MECAB_AVAILABLE = _module_available("MeCab")
_IPADIC_AVAILABLE = _module_available("ipadic")
_SENTENCEPIECE_AVAILABLE = _module_available("sentencepiece")
_REGEX_AVAILABLE = _module_available("regex")
_VMAF_AVAILABLE = False  # vmaf_torch: CUDA-only upstream; no TPU equivalent shipped
_PANDAS_AVAILABLE = _module_available("pandas")
