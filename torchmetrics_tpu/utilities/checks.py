"""Host-side input validation (NOT jit-traceable — gate with ``validate_args``).

Parity: reference ``src/torchmetrics/utilities/checks.py`` (_check_same_shape:36,
retrieval checks:44-120). Shape/dtype checks are trace-safe (static metadata); any check
that must look at *values* pulls to host and therefore only runs when ``validate_args``
is True outside of jit — mirroring the reference's ``validate_args`` speed knob.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _is_traced(*arrays) -> bool:
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def _check_same_shape(preds, target) -> None:
    """Raise if shapes differ (static — safe under jit)."""
    if tuple(preds.shape) != tuple(target.shape):
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, "
            f"but got {tuple(preds.shape)} and {tuple(target.shape)}."
        )


def _check_value_range(x, low: float, high: float, name: str) -> None:
    """Value check — skipped when traced (cannot sync inside jit)."""
    if _is_traced(x):
        return
    xv = np.asarray(x)
    if xv.size and (xv.min() < low or xv.max() > high):
        raise ValueError(f"Expected `{name}` values in [{low}, {high}] but got range [{xv.min()}, {xv.max()}].")


def _check_int_dtype(x, name: str) -> None:
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer) and not jnp.issubdtype(jnp.asarray(x).dtype, jnp.bool_):
        raise ValueError(f"Expected `{name}` to be an int tensor, but got {jnp.asarray(x).dtype}.")


def _check_label_values(x, num_classes: int, name: str, ignore_index: Optional[int] = None) -> None:
    if _is_traced(x):
        return
    xv = np.asarray(x)
    if ignore_index is not None:
        xv = xv[xv != ignore_index]
    if xv.size and (xv.min() < 0 or xv.max() >= num_classes):
        raise RuntimeError(
            f"Detected more unique values in `{name}` than expected. Expected only {num_classes} but found "
            f"values in range [{xv.min()}, {xv.max()}]."
        )


def _check_for_empty_tensors(preds, target) -> bool:
    return preds.size == 0 or target.size == 0


def _check_retrieval_inputs(
    indexes, preds, target, allow_non_binary_target: bool = False, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """Validate and flatten retrieval (indexes, preds, target) triples.

    Reference: utilities/checks.py:44-120.
    """
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(jnp.asarray(indexes).dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    tgt = jnp.asarray(target)
    if not (jnp.issubdtype(tgt.dtype, jnp.integer) or jnp.issubdtype(tgt.dtype, jnp.bool_) or jnp.issubdtype(tgt.dtype, jnp.floating)):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")
    if not allow_non_binary_target and not _is_traced(target):
        tv = np.asarray(target)
        if tv.size and (tv.max() > 1 or tv.min() < 0):
            raise ValueError("`target` must contain `binary` values")
    indexes = jnp.asarray(indexes).reshape(-1)
    preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
    target = tgt.reshape(-1)
    if ignore_index is not None:
        keep = target != ignore_index
        # host-side compaction (compute-time path, not jitted)
        keep_np = np.asarray(keep)
        indexes = indexes[keep_np]
        preds = preds[keep_np]
        target = target[keep_np]
    return indexes, preds, target


def check_forward_full_state_property(
    metric_class,
    init_args: Optional[dict] = None,
    input_args: Optional[dict] = None,
    num_update_to_compare=(10, 100, 1000),
    reps: int = 5,
) -> None:
    """Check whether ``full_state_update=False`` is safe for ``metric_class``
    (public API parity: reference ``utilities/checks.py:171``).

    The reference compares ``forward`` under its two update strategies. This
    framework's pure ``init/_batch_state/_merge`` core computes the batch value
    from the batch state alone (never from mutated global state), so the partial
    strategy is structurally exact; the check still runs the comparison — batch
    ``forward`` value vs a fresh single-batch metric — and the timing sweep, and
    prints the same recommendation format as the reference.
    """
    import time as _time

    init_args = init_args or {}
    input_args = input_args or {}
    metric = metric_class(**init_args)
    for _ in range(3):
        batch_val = metric(**input_args)
        fresh = metric_class(**init_args)
        fresh.update(**input_args)
        single = fresh.compute()
        equal = jax.tree.all(
            jax.tree.map(lambda a, b: bool(np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)), batch_val, single)
        )
        if not equal:
            # stdout contract mirrors the reference's doctested output
            print("Recommended setting `full_state_update=True`")
            return
    for steps in num_update_to_compare:
        # there is only ONE update strategy in this framework (the batch value
        # never derives from mutated global state), so a single timing serves
        # both of the reference's labels — printed under both for the stdout
        # format drop-in scripts parse
        best = float("inf")
        for _ in range(reps):
            m = metric_class(**init_args)
            start = _time.perf_counter()
            for _ in range(steps):
                m(**input_args)
            jax.block_until_ready(m._state) if hasattr(m, "_state") else None
            best = min(best, _time.perf_counter() - start)
        print(f"Full state for {steps} steps took: {best}")
        print(f"Partial state for {steps} steps took: {best}")
    print("Recommended setting `full_state_update=False`")
