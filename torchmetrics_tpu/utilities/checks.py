"""Host-side input validation (NOT jit-traceable — gate with ``validate_args``).

Parity: reference ``src/torchmetrics/utilities/checks.py`` (_check_same_shape:36,
retrieval checks:44-120). Shape/dtype checks are trace-safe (static metadata); any check
that must look at *values* pulls to host and therefore only runs when ``validate_args``
is True outside of jit — mirroring the reference's ``validate_args`` speed knob.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _is_traced(*arrays) -> bool:
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def _check_same_shape(preds, target) -> None:
    """Raise if shapes differ (static — safe under jit)."""
    if tuple(preds.shape) != tuple(target.shape):
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, "
            f"but got {tuple(preds.shape)} and {tuple(target.shape)}."
        )


def _check_value_range(x, low: float, high: float, name: str) -> None:
    """Value check — skipped when traced (cannot sync inside jit)."""
    if _is_traced(x):
        return
    xv = np.asarray(x)
    if xv.size and (xv.min() < low or xv.max() > high):
        raise ValueError(f"Expected `{name}` values in [{low}, {high}] but got range [{xv.min()}, {xv.max()}].")


def _check_int_dtype(x, name: str) -> None:
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer) and not jnp.issubdtype(jnp.asarray(x).dtype, jnp.bool_):
        raise ValueError(f"Expected `{name}` to be an int tensor, but got {jnp.asarray(x).dtype}.")


def _check_label_values(x, num_classes: int, name: str, ignore_index: Optional[int] = None) -> None:
    if _is_traced(x):
        return
    xv = np.asarray(x)
    if ignore_index is not None:
        xv = xv[xv != ignore_index]
    if xv.size and (xv.min() < 0 or xv.max() >= num_classes):
        raise RuntimeError(
            f"Detected more unique values in `{name}` than expected. Expected only {num_classes} but found "
            f"values in range [{xv.min()}, {xv.max()}]."
        )


def _check_for_empty_tensors(preds, target) -> bool:
    return preds.size == 0 or target.size == 0


def _check_retrieval_inputs(
    indexes, preds, target, allow_non_binary_target: bool = False, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """Validate and flatten retrieval (indexes, preds, target) triples.

    Reference: utilities/checks.py:44-120.
    """
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(jnp.asarray(indexes).dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    tgt = jnp.asarray(target)
    if not (jnp.issubdtype(tgt.dtype, jnp.integer) or jnp.issubdtype(tgt.dtype, jnp.bool_) or jnp.issubdtype(tgt.dtype, jnp.floating)):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")
    if not allow_non_binary_target and not _is_traced(target):
        tv = np.asarray(target)
        if tv.size and (tv.max() > 1 or tv.min() < 0):
            raise ValueError("`target` must contain `binary` values")
    indexes = jnp.asarray(indexes).reshape(-1)
    preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
    target = tgt.reshape(-1)
    if ignore_index is not None:
        keep = target != ignore_index
        # host-side compaction (compute-time path, not jitted)
        keep_np = np.asarray(keep)
        indexes = indexes[keep_np]
        preds = preds[keep_np]
        target = target[keep_np]
    return indexes, preds, target
