"""Numerics helpers — jit-safe, static-shape.

Parity: reference ``src/torchmetrics/utilities/compute.py`` (_safe_divide:49,
_safe_xlogy, _auc_compute, interp, normalize_logits_if_needed:240-246). All functions are
pure jnp and safe to call inside ``jax.jit`` / ``shard_map``; data-dependent branches use
``jnp.where`` (both sides computed — cheap elementwise, fuses into one XLA kernel) so
nothing forces a device→host sync.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def _safe_divide(num: Array, denom: Array, zero_division: Union[float, Array] = 0.0) -> Array:
    """``num / denom`` with 0-denominator positions replaced by ``zero_division``.

    Both operands are promoted to float. Reference: utilities/compute.py:49.
    """
    num = jnp.asarray(num)
    denom = jnp.asarray(denom)
    dtype = jnp.result_type(num.dtype, denom.dtype, jnp.float32)
    if not jnp.issubdtype(dtype, jnp.floating):
        dtype = jnp.float32
    num = num.astype(dtype)
    denom = denom.astype(dtype)
    zero = denom == 0
    safe_denom = jnp.where(zero, jnp.ones_like(denom), denom)
    return jnp.where(zero, jnp.asarray(zero_division, dtype=dtype), num / safe_denom)


def _safe_xlogy(x: Array, y: Array) -> Array:
    """``x * log(y)`` that is 0 where ``x == 0`` (even if y is 0/inf)."""
    x, y = jnp.asarray(x), jnp.asarray(y)
    dtype = jnp.result_type(x.dtype, y.dtype, jnp.float32)
    x, y = x.astype(dtype), y.astype(dtype)
    safe_y = jnp.where(x == 0, jnp.ones_like(y), y)
    return jnp.where(x == 0, jnp.zeros_like(x), x * jnp.log(safe_y))


def _safe_log(x: Array, eps: float = 1e-20) -> Array:
    return jnp.log(jnp.clip(x, min=eps))


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul with fp16/bf16 inputs accumulated in fp32 (MXU-native on TPU)."""
    if x.dtype in (jnp.float16, jnp.bfloat16) or y.dtype in (jnp.float16, jnp.bfloat16):
        return jnp.matmul(x, y, preferred_element_type=jnp.float32).astype(x.dtype)
    return jnp.matmul(x, y)


def _adjust_weights_safe_divide(
    score: Array, average: Optional[str], multilabel: bool, tp: Array, fp: Array, fn: Array, top_k: int = 1
) -> Array:
    """Weighted/macro reduction of per-class scores, ignoring absent classes.

    Reference: utilities/compute.py (same name).
    """
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = (tp + fn).astype(jnp.float32)
    else:
        weights = jnp.ones_like(score, dtype=jnp.float32)
        if not multilabel:
            # drop classes that never appear (neither predicted nor present); with
            # top_k > 1 only true absence (no support) drops a class
            absent = (tp + fp + fn) == 0 if top_k == 1 else (tp + fn) == 0
            weights = weights * (~absent)
    norm = weights.sum(-1, keepdims=True)
    return (_safe_divide(weights, norm) * score).sum(-1)


def _auc_compute(x: Array, y: Array, direction: Optional[float] = None, reorder: bool = False) -> Array:
    """Trapezoidal area under the (x, y) curve.

    ``direction`` handles monotonically decreasing x (e.g. PR curves built from
    descending thresholds) without a host round-trip: when None, the sign of the first
    finite dx decides, computed in-graph. Reference: utilities/compute.py (_auc_compute).
    """
    x, y = jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32)
    if reorder:
        order = jnp.argsort(x)
        x, y = x[order], y[order]
    dx = jnp.diff(x)
    trapz = ((y[1:] + y[:-1]) / 2 * dx).sum()
    if direction is None:
        sign = jnp.where(jnp.all(dx <= 0), -1.0, 1.0)
        sign = jnp.where(jnp.all(dx >= 0), 1.0, sign)
        return trapz * sign
    return trapz * direction


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """1-D linear interpolation (jnp.interp wrapper, static-shape)."""
    return jnp.interp(x, xp, fp)


def normalize_logits_if_needed(preds: Array, normalization: str = "sigmoid") -> Array:
    """Apply sigmoid/softmax only when values fall outside [0, 1].

    In-graph branchless formulation (reference uses the same torch.where trick at
    utilities/compute.py:240-246 to avoid a device→host sync).
    """
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        preds = jnp.asarray(preds, jnp.float32)
    outside = (preds.min() < 0) | (preds.max() > 1)
    if normalization == "sigmoid":
        return jnp.where(outside, jax.nn.sigmoid(preds), preds)
    if normalization == "softmax":
        return jnp.where(outside, jax.nn.softmax(preds, axis=1), preds)
    return preds


def _auc_reorder_and_compute(x: Array, y: Array) -> Array:
    return _auc_compute(x, y, reorder=True)


def reduce(x: Array, reduction: Optional[str]) -> Array:
    """Reduce a tensor by ``'elementwise_mean'``, ``'sum'``, or ``'none'``/None
    (public API parity: reference ``utilities/distributed.py:22-42``)."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "none" or reduction is None:
        return jnp.asarray(x)
    if reduction == "sum":
        return jnp.sum(x)
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: Optional[str] = "none") -> Array:
    """Reduce per-class ``num / denom * weights`` metrics by micro/macro/weighted/none
    (public API parity: reference ``utilities/distributed.py:45-88``); NaN cells
    (0-support classes) count as 0, matching the reference's in-place fixup."""
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    num = jnp.asarray(num)
    denom = jnp.asarray(denom)
    weights = jnp.asarray(weights)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights.astype(jnp.float32) / jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")
