"""Data/layout helpers — static-shape, TPU-first.

Parity: reference ``src/torchmetrics/utilities/data.py`` (dim_zero_*:29-56, to_onehot,
select_topk:116, to_categorical, _bincount:178-206, _cumsum:209, _flexible_bincount).

Design notes (TPU):
- ``_bincount`` uses ``jax.ops.segment_sum`` (scatter-add) with a masked weight vector —
  the formulation the reference reserves for its XLA fallback (data.py:202-206) is the
  *primary* path here since dynamic-shape boolean indexing cannot be jitted.
- A one-hot-matmul variant (``_bincount_matmul``) rides the MXU for large batches.
- All helpers accept an optional ``weights`` argument so ``ignore_index`` filtering is
  expressed as zero weights instead of dynamic-shape gathers (SURVEY §7 hard parts).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenate a (possibly nested) list of arrays along dim 0."""
    if isinstance(x, (jax.Array, jnp.ndarray)) or hasattr(x, "ndim"):
        return jnp.asarray(x)
    if not isinstance(x, (list, tuple)):
        return jnp.asarray(x)
    x = [jnp.atleast_1d(jnp.asarray(el)) for el in _flatten(x)]
    if not x:
        raise ValueError("No samples to concatenate")
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten one level of nesting."""
    out = []
    for item in x:
        if isinstance(item, (list, tuple)):
            out.extend(item)
        else:
            out.append(item)
    return out


def _flatten_dict(x: dict) -> tuple:
    """Flatten one level of nested dicts; returns (flat_dict, duplicates_found)."""
    new_dict = {}
    duplicates = False
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                if k in new_dict:
                    duplicates = True
                new_dict[k] = v
        else:
            if key in new_dict:
                duplicates = True
            new_dict[key] = value
    return new_dict, duplicates


def to_onehot(label_tensor: Array, num_classes: int) -> Array:
    """Integer labels ``(N, ...)`` → one-hot ``(N, C, ...)``."""
    oh = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32)  # (N, ..., C)
    return jnp.moveaxis(oh, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the top-k entries along ``dim``. Reference: data.py:116."""
    if topk == 1:  # cheap argmax path
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    _, idx = jax.lax.top_k(jnp.moveaxis(prob_tensor, dim, -1), topk)
    mask = jnp.zeros(jnp.moveaxis(prob_tensor, dim, -1).shape, dtype=jnp.int32)
    mask = jnp.put_along_axis(mask, idx, 1, axis=-1, inplace=False)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Probabilities → class labels via argmax."""
    return jnp.argmax(x, axis=argmax_dim)


def _bincount(x: Array, minlength: int, weights: Optional[Array] = None) -> Array:
    """Histogram of integer values with static output shape ``(minlength,)``.

    Out-of-range / negative entries (e.g. an ``ignore_index`` remapped to -1) drop out
    via zero weights. scatter-add lowers efficiently on TPU; reference keeps this
    formulation as its deterministic/XLA fallback (data.py:202-206).
    """
    x = jnp.asarray(x).reshape(-1)
    valid = (x >= 0) & (x < minlength)
    w = jnp.where(valid, jnp.ones(x.shape, jnp.float32) if weights is None else jnp.asarray(weights).reshape(-1).astype(jnp.float32), 0.0)
    idx = jnp.where(valid, x, 0)
    counts = jax.ops.segment_sum(w, idx, num_segments=minlength)
    if weights is None:
        return counts.astype(jnp.int32)
    return counts


def _bincount_matmul(x: Array, minlength: int, weights: Optional[Array] = None) -> Array:
    """One-hot × weights bincount — rides the MXU; better for huge fused batches."""
    x = jnp.asarray(x).reshape(-1)
    oh = jax.nn.one_hot(x, minlength, dtype=jnp.float32)  # out-of-range rows are all-zero
    w = jnp.ones(x.shape, jnp.float32) if weights is None else jnp.asarray(weights).reshape(-1).astype(jnp.float32)
    counts = w @ oh
    if weights is None:
        return counts.astype(jnp.int32)
    return counts


def _bincount_2d(x: Array, y: Array, nx: int, ny: int, weights: Optional[Array] = None) -> Array:
    """Joint histogram (confusion-matrix kernel): returns ``(nx, ny)`` counts.

    Implemented as a single 1-D bincount over fused index ``x * ny + y`` — one
    scatter-add instead of a Python loop over classes.
    """
    x = jnp.asarray(x).reshape(-1)
    y = jnp.asarray(y).reshape(-1)
    valid = (x >= 0) & (x < nx) & (y >= 0) & (y < ny)
    w = jnp.where(valid, jnp.ones(x.shape, jnp.float32) if weights is None else jnp.asarray(weights).reshape(-1).astype(jnp.float32), 0.0)
    fused = jnp.where(valid, x * ny + y, 0)
    counts = jax.ops.segment_sum(w, fused, num_segments=nx * ny).reshape(nx, ny)
    if weights is None:
        return counts.astype(jnp.int32)
    return counts


def _cumsum(x: Array, axis: int = 0) -> Array:
    """Deterministic cumulative sum (XLA cumsum is deterministic on TPU)."""
    return jnp.cumsum(x, axis=axis)


def _flexible_bincount(x: Array) -> Array:
    """Count occurrences of each *unique* value (dynamic output — host-side only).

    Used by retrieval metrics at compute time; under jit prefer ``_bincount`` with a
    static upper bound. Reference: data.py (_flexible_bincount).
    """
    import numpy as np

    xs = np.asarray(x)
    _, counts = np.unique(xs, return_counts=True)
    return jnp.asarray(counts)


def _squeeze_if_scalar(data):
    """Squeeze 0-d arrays inside (possibly nested) containers to python-friendly scalars."""
    if isinstance(data, dict):
        return {k: _squeeze_if_scalar(v) for k, v in data.items()}
    if isinstance(data, (list, tuple)):
        return type(data)(_squeeze_if_scalar(d) for d in data)
    if hasattr(data, "ndim") and data.ndim == 0:
        return data
    return data


def allclose(a, b, rtol: float = 1e-5, atol: float = 1e-8) -> bool:
    import numpy as np

    return bool(np.allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol))
