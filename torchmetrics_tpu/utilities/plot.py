"""Plotting backend (matplotlib optional).

Parity: reference ``utilities/plot.py`` (plot_single_or_multi_val:65,
plot_confusion_matrix:221, plot_curve:297).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

from .imports import _MATPLOTLIB_AVAILABLE

_error_msg = "matplotlib is required to plot metrics, install it to use the `.plot` method"


def _get_ax(ax=None):
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots()
    else:
        fig = ax.get_figure()
    return fig, ax


def _to_np(val):
    if isinstance(val, dict):
        return {k: _to_np(v) for k, v in val.items()}
    if isinstance(val, (list, tuple)):
        return [np.asarray(v) for v in val]
    return np.asarray(val)


def plot_single_or_multi_val(
    val,
    ax=None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Scalar → point; vector/dict/list-over-steps → lines (reference plot.py:65)."""
    fig, ax = _get_ax(ax)
    val = _to_np(val)
    if isinstance(val, dict):
        for i, (k, v) in enumerate(val.items()):
            v = np.atleast_1d(v)
            if v.size == 1:
                ax.plot([i], v, "o", label=str(k))
            else:
                ax.plot(v, label=str(k))
        ax.legend()
    elif isinstance(val, list):
        arr = np.stack([np.atleast_1d(v) for v in val])
        if arr.ndim == 2 and arr.shape[1] > 1:
            for c in range(arr.shape[1]):
                ax.plot(arr[:, c], label=f"{legend_name or 'dim'} {c}")
            ax.legend()
        else:
            ax.plot(arr.reshape(arr.shape[0], -1))
        ax.set_xlabel("Step")
    else:
        arr = np.atleast_1d(val)
        if arr.size == 1:
            ax.plot([0], arr, "o")
        else:
            labels = [f"{legend_name or 'dim'} {i}" for i in range(arr.size)]
            ax.bar(np.arange(arr.size), arr.reshape(-1), tick_label=labels)
    if lower_bound is not None and upper_bound is not None:
        ax.set_ylim(lower_bound, upper_bound)
    if name:
        ax.set_title(name)
    return fig, ax


def plot_confusion_matrix(
    confmat,
    ax=None,
    add_text: bool = True,
    labels: Optional[Sequence] = None,
    cmap: Optional[str] = None,
):
    """Heatmap(s) for (C,C) or (N,2,2) confusion matrices (reference plot.py:221)."""
    fig, ax = _get_ax(ax)
    cm = np.asarray(confmat)
    if cm.ndim == 3:  # multilabel — plot the first, reference creates a grid; keep simple
        cm = cm[0]
    im = ax.imshow(cm, cmap=cmap or "Blues")
    fig.colorbar(im, ax=ax)
    n = cm.shape[0]
    ticks = labels if labels is not None else list(range(n))
    ax.set_xticks(range(n), ticks)
    ax.set_yticks(range(n), ticks)
    ax.set_xlabel("Predicted class")
    ax.set_ylabel("True class")
    if add_text:
        for i in range(n):
            for j in range(cm.shape[1]):
                ax.text(j, i, f"{cm[i, j]:.2g}", ha="center", va="center")
    return fig, ax


def plot_curve(
    curve: Tuple,
    score=None,
    ax=None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """ROC/PR-style curve plot (reference plot.py:297)."""
    fig, ax = _get_ax(ax)
    x, y = np.asarray(curve[0]), np.asarray(curve[1])
    if x.ndim == 1:
        ax.plot(x, y)
    else:
        for c in range(x.shape[0]):
            ax.plot(x[c], y[c], label=f"{legend_name or 'class'} {c}")
        ax.legend()
    if label_names:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if score is not None:
        ax.set_title(f"{name or 'curve'} (score={np.asarray(score):.3f})")
    elif name:
        ax.set_title(name)
    return fig, ax
