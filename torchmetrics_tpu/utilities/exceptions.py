"""Exception types.

Parity: reference ``src/torchmetrics/utilities/exceptions.py``, extended with the
reliability-layer taxonomy (``reliability/``): infrastructure faults that are safe to
retry vs state corruption that must never be (retrying corrupted state would launder
garbage into a "successful" eval).
"""


class TorchMetricsUserError(Exception):
    """Error raised on wrong usage of the metric API."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on questionable usage of the metric API."""


class TransientRuntimeError(RuntimeError):
    """A transient infrastructure fault (remote compile service, RPC transport, host
    dropout) that is safe to retry with the same inputs.

    Raised by the fault-injection harness and used by :mod:`..reliability.retry` as
    the always-retryable exception type; real runtime faults (``JaxRuntimeError``
    with an ``INTERNAL:``/``UNAVAILABLE:`` status) are classified by message.
    """


class StateCorruptionError(RuntimeError):
    """A metric state violated its ``init_state()`` spec — missing leaf, wrong
    shape/dtype, or non-finite values — at a sync/merge/checkpoint-restore boundary.

    Never retryable: the state itself is damaged, so re-running the same operation
    can only propagate the damage.
    """
