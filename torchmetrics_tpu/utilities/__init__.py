from .checks import _check_same_shape, check_forward_full_state_property
from .compute import class_reduce, reduce
from .data import dim_zero_cat, dim_zero_max, dim_zero_mean, dim_zero_min, dim_zero_sum
from .exceptions import TorchMetricsUserError, TorchMetricsUserWarning
from .prints import rank_zero_debug, rank_zero_info, rank_zero_warn

__all__ = [
    "TorchMetricsUserError",
    "TorchMetricsUserWarning",
    "check_forward_full_state_property",
    "class_reduce",
    "reduce",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
]
