from .checks import _check_same_shape
from .data import dim_zero_cat, dim_zero_max, dim_zero_mean, dim_zero_min, dim_zero_sum
from .exceptions import TorchMetricsUserError, TorchMetricsUserWarning
from .prints import rank_zero_debug, rank_zero_info, rank_zero_warn

__all__ = [
    "TorchMetricsUserError",
    "TorchMetricsUserWarning",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
]
