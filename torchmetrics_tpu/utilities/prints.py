"""Rank-zero-gated logging / warnings.

Parity: reference ``src/torchmetrics/utilities/prints.py:23-73``. On TPU the rank is the
JAX process index (single-controller SPMD: one Python process may drive many chips, so
"rank zero" means process 0 of the distributed runtime, not device 0).
"""

from __future__ import annotations

import logging
import warnings
from functools import partial, wraps
from typing import Any, Callable

_logger = logging.getLogger("torchmetrics_tpu")


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process 0 of the JAX distributed runtime."""

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        if _process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


@rank_zero_only
def rank_zero_print(*args: Any, **kwargs: Any) -> None:
    print(*args, **kwargs)


@rank_zero_only
def rank_zero_debug(*args: Any, **kwargs: Any) -> None:
    _logger.debug(*args, **kwargs)


@rank_zero_only
def rank_zero_info(*args: Any, **kwargs: Any) -> None:
    _logger.info(*args, **kwargs)


def _warn(message: str, kind: type = UserWarning, **kwargs: Any) -> None:
    warnings.warn(message, kind, stacklevel=kwargs.pop("stacklevel", 5), **kwargs)


@rank_zero_only
def rank_zero_warn(message: str, kind: type = UserWarning, **kwargs: Any) -> None:
    _warn(message, kind, **kwargs)


rank_zero_warn_deprecated = partial(rank_zero_warn, kind=DeprecationWarning)
