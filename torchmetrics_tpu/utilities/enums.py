"""Task / averaging enums.

Parity: reference ``src/torchmetrics/utilities/enums.py:19-153``.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """String enum with case/sep-insensitive ``from_str`` lookup."""

    @staticmethod
    def _name() -> str:
        return "Task"

    @classmethod
    def from_str(cls, value: str, source: str = "Key") -> "EnumStr":
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError as err:
            valid = [m.lower() for m in cls.__members__]
            raise ValueError(
                f"Invalid {cls._name()}: expected one of {valid}, but got {value}."
            ) from err

    def __str__(self) -> str:
        return self.value.lower()


class DataType(EnumStr):
    """Input data type classification."""

    @staticmethod
    def _name() -> str:
        return "Data type"

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Averaging strategy for multi-class reductions."""

    @staticmethod
    def _name() -> str:
        return "Average method"

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = None  # type: ignore[assignment]
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Multi-dim multi-class averaging."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """binary / multiclass / multilabel task switch."""

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoBinary(EnumStr):
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoMultilabel(EnumStr):
    BINARY = "binary"
    MULTICLASS = "multiclass"


def _resolve_average(average: Optional[str], allowed=("micro", "macro", "weighted", "none", None)) -> Optional[str]:
    if average not in allowed:
        raise ValueError(f"Argument `average` has to be one of {allowed}, got {average}.")
    return None if average == "none" else average
