"""VMAF — Video Multi-Method Assessment Fusion.

Reference surface: ``functional/video/vmaf.py`` + ``video/vmaf.py:27`` (a thin
wrapper over the ``vmaf_torch`` package). Three paths, in resolution order:

1. ``vmaf_torch`` installed → host callback through it (bit parity with the
   reference, including the bundled ``vmaf_v0.6.1`` SVM model).
2. ``model_path=`` given → in-tree pipeline: elementary features below + NuSVR
   fusion loaded from a libvmaf-format model JSON.
3. Neither → ``vmaf_features`` still computes the elementary features (the SVM
   weights are a trained artifact that cannot be conjured offline); the fused
   score raises with instructions.

The in-tree elementary features are jnp conv pipelines over ``(B*F, H, W)`` luma
frames (separable gaussian convs — MXU-friendly batched 2-D convolutions):

- **motion / motion2**: mean |Δ| of 5-tap-gaussian-blurred consecutive luma
  frames; ``motion2[i] = min(motion[i-1,i], motion[i,i+1])`` (libvmaf motion
  feature, FILTER_5 taps).
- **vif_scale0..3**: Visual Information Fidelity (Sheikh & Bovik) per scale,
  gaussian windows N=17/9/5/3 (sd N/5), ``sigma_nsq=2``, dyadic downsampling
  between scales — the ``vifp_mscale`` float formulation libvmaf's float VIF
  follows.
- **adm2, adm_scale0..3**: Detail Loss Metric (Li et al.): 4-level db2 DWT,
  decoupling with the 1-degree angle rule, Watson-CSF subband weighting, 1/30
  contrast masking of the additive component, cube-root spatial pooling over
  the center region (10% border crop).

Float pipelines: parity with libvmaf's fixed-point "integer_*" features is
approximate by construction; bit-level validation requires libvmaf golden runs,
which this offline environment cannot produce. Properties (identity → vif=1,
adm=1, motion=0; monotone degradation) are tested instead, and the NuSVR fusion
engine is tested against hand-computed kernels on a synthetic model file.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...utilities.imports import _module_available

_VMAF_TORCH_AVAILABLE = _module_available("vmaf_torch")

# libvmaf motion_tools FILTER_5 (gaussian, sd ~1.08)
_MOTION_FILTER = np.array(
    [0.054488685, 0.244201342, 0.402619947, 0.244201342, 0.054488685], np.float32
)

# Daubechies-2 (db2) analysis filters (orthonormal)
_DB2_LO = np.array(
    [0.482962913144690, 0.836516303737469, 0.224143868041857, -0.129409522550921],
    np.float32,
)
_DB2_HI = np.array(
    [-0.129409522550921, -0.224143868041857, 0.836516303737469, -0.482962913144690],
    np.float32,
)

# Watson et al. DWT noise sensitivity CSF amplitudes for db2, scales 1..4,
# orientations (A, H, V, D) — the weighting the DLM paper prescribes
_CSF_AMPLITUDES = np.array(
    [
        [0.01714, 0.02521, 0.02521, 0.04452],
        [0.01334, 0.01729, 0.01729, 0.02616],
        [0.01143, 0.01329, 0.01329, 0.01784],
        [0.01081, 0.01169, 0.01169, 0.01441],
    ],
    np.float32,
)


def calculate_luma(video: jnp.ndarray) -> jnp.ndarray:
    """(B, 3, F, H, W) RGB in [0,1] -> (B, F, H, W) luma in [0,255]
    (reference ``functional/video/vmaf.py:31-37``)."""
    r, g, b = video[:, 0], video[:, 1], video[:, 2]
    return (0.299 * r + 0.587 * g + 0.114 * b) * 255.0


def _conv2d_sep(x: jnp.ndarray, taps: jnp.ndarray, mode: str = "reflect") -> jnp.ndarray:
    """Separable 2-D convolution of (N, H, W) frames with a symmetric 1-D tap
    vector, edge-replicated like libvmaf's convolution boundary handling."""
    k = taps.shape[0]
    pad = k // 2
    t = jnp.asarray(taps)
    xp = jnp.pad(x, ((0, 0), (pad, pad), (0, 0)), mode="edge")
    x = lax.conv_general_dilated(
        xp[:, None], t.reshape(1, 1, k, 1), (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )[:, 0]
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad, pad)), mode="edge")
    return lax.conv_general_dilated(
        xp[:, None], t.reshape(1, 1, 1, k), (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )[:, 0]


def _gaussian_taps(n: int, sd: float) -> np.ndarray:
    x = np.arange(n) - (n - 1) / 2.0
    w = np.exp(-(x**2) / (2 * sd * sd))
    return (w / w.sum()).astype(np.float32)


# ---------------------------------------------------------------- motion -----

def motion_features(ref_luma: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(B, F, H, W) -> (motion, motion2), each (B, F). Frame 0 scores 0."""
    b, f, h, w = ref_luma.shape
    blurred = _conv2d_sep(ref_luma.reshape(b * f, h, w), jnp.asarray(_MOTION_FILTER)).reshape(b, f, h, w)
    sad = jnp.abs(blurred[:, 1:] - blurred[:, :-1]).mean((-1, -2))  # (B, F-1)
    zero = jnp.zeros((b, 1), sad.dtype)
    motion = jnp.concatenate([zero, sad], axis=1)  # motion[i] = sad(i-1, i)
    nxt = jnp.concatenate([sad, jnp.full((b, 1), jnp.inf, sad.dtype)], axis=1)
    motion2 = jnp.minimum(motion, nxt)
    motion2 = motion2.at[:, 0].set(0.0)
    return motion, motion2


# ------------------------------------------------------------------- VIF -----

def vif_features(ref_luma: jnp.ndarray, dist_luma: jnp.ndarray, sigma_nsq: float = 2.0) -> Dict[str, jnp.ndarray]:
    """Per-scale VIF (B, F) for scales 0..3 (vifp_mscale float formulation)."""
    b, f, h, w = ref_luma.shape
    ref = ref_luma.reshape(b * f, h, w).astype(jnp.float32)
    dist = dist_luma.reshape(b * f, h, w).astype(jnp.float32)
    out = {}
    for scale in range(4):
        n = 2 ** (4 - scale) + 1  # 17, 9, 5, 3
        taps = jnp.asarray(_gaussian_taps(n, n / 5.0))
        if scale > 0:
            ref = _conv2d_sep(ref, taps)[:, ::2, ::2]
            dist = _conv2d_sep(dist, taps)[:, ::2, ::2]
        mu1 = _conv2d_sep(ref, taps)
        mu2 = _conv2d_sep(dist, taps)
        mu1_sq, mu2_sq, mu1_mu2 = mu1 * mu1, mu2 * mu2, mu1 * mu2
        sigma1_sq = jnp.clip(_conv2d_sep(ref * ref, taps) - mu1_sq, 0)
        sigma2_sq = jnp.clip(_conv2d_sep(dist * dist, taps) - mu2_sq, 0)
        sigma12 = _conv2d_sep(ref * dist, taps) - mu1_mu2
        g = sigma12 / (sigma1_sq + 1e-10)
        sv_sq = sigma2_sq - g * sigma12
        g = jnp.where(sigma1_sq < 1e-10, 0.0, g)
        sv_sq = jnp.where(sigma1_sq < 1e-10, sigma2_sq, sv_sq)
        sv_sq = jnp.where(sigma2_sq < 1e-10, 0.0, sv_sq)
        g = jnp.where(sigma2_sq < 1e-10, 0.0, g)
        sv_sq = jnp.where(g < 0, sigma2_sq, sv_sq)
        g = jnp.clip(g, 0)
        sv_sq = jnp.clip(sv_sq, 1e-10)
        num = jnp.log2(1 + g * g * sigma1_sq / (sv_sq + sigma_nsq)).sum((-1, -2))
        den = jnp.log2(1 + sigma1_sq / sigma_nsq).sum((-1, -2))
        out[f"vif_scale{scale}"] = (num / jnp.maximum(den, 1e-10)).reshape(b, f)
    return out


# ------------------------------------------------------------------- ADM -----

def _dwt2_db2(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One db2 DWT level of (N, H, W) -> (A, H, V, D), symmetric padding."""

    def _filt(x, taps, axis):
        k = taps.shape[0]
        pad = [(0, 0), (0, 0), (0, 0)]
        pad[axis] = (k - 1, k - 1)
        xp = jnp.pad(x, pad, mode="symmetric")
        shape = [1, 1, 1, 1]
        shape[2 + (axis - 1)] = k  # axis 1 -> H (kernel dim 2), axis 2 -> W (dim 3)
        kern = jnp.asarray(taps)[::-1].reshape(shape)
        y = lax.conv_general_dilated(
            xp[:, None], kern, (1, 1), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW")
        )[:, 0]
        # downsample by 2 starting at offset 1 (pywt-style even-length output)
        return y[:, 1::2, :] if axis == 1 else y[:, :, 1::2]

    lo_r = _filt(x, jnp.asarray(_DB2_LO), 1)
    hi_r = _filt(x, jnp.asarray(_DB2_HI), 1)
    return (
        _filt(lo_r, jnp.asarray(_DB2_LO), 2),  # A
        _filt(hi_r, jnp.asarray(_DB2_LO), 2),  # H (detail along rows)
        _filt(lo_r, jnp.asarray(_DB2_HI), 2),  # V
        _filt(hi_r, jnp.asarray(_DB2_HI), 2),  # D
    )


def adm_features(ref_luma: jnp.ndarray, dist_luma: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """DLM per scale + combined adm2 (B, F). Border-cropped cube-root pooling."""
    b, f, h, w = ref_luma.shape
    o = ref_luma.reshape(b * f, h, w).astype(jnp.float32)
    t = dist_luma.reshape(b * f, h, w).astype(jnp.float32)
    num_scales, eps = 4, 1e-30
    nums, dens = [], []
    for scale in range(num_scales):
        o_a, o_h, o_v, o_d = _dwt2_db2(o)
        t_a, t_h, t_v, t_d = _dwt2_db2(t)
        o = o_a
        t = t_a
        # decoupling: restored R = clip(T/O, 0, 1) * O, except within 1 degree of
        # equal orientation where the distortion is treated as purely additive
        ot_dp = o_h * t_h + o_v * t_v
        o_mag_sq = o_h * o_h + o_v * o_v + eps
        t_mag_sq = t_h * t_h + t_v * t_v + eps
        cos_1deg_sq = np.cos(np.deg2rad(1.0)) ** 2
        angle_ok = (ot_dp >= 0) & (ot_dp * ot_dp >= cos_1deg_sq * o_mag_sq * t_mag_sq)
        rests = []
        for o_s, t_s in ((o_h, t_h), (o_v, t_v), (o_d, t_d)):
            k = jnp.clip(t_s / (o_s + jnp.where(o_s >= 0, eps, -eps)), 0.0, 1.0)
            rests.append(jnp.where(angle_ok, t_s, k * o_s))
        # CSF weighting
        csf = _CSF_AMPLITUDES[scale]
        o_c = [o_h / csf[1], o_v / csf[2], o_d / csf[3]]
        r_c = [rests[0] / csf[1], rests[1] / csf[2], rests[2] / csf[3]]
        # contrast masking: the restored detail is thresholded by the local energy
        # of the ADDITIVE impairment A = T - R (DLM paper) — zero when T == O, so
        # identity scores exactly 1
        a_c = [
            (t_h - rests[0]) / csf[1],
            (t_v - rests[1]) / csf[2],
            (t_d - rests[2]) / csf[3],
        ]
        mask = sum(jnp.abs(x) for x in a_c) / 30.0
        kern = jnp.ones((1, 1, 3, 3), jnp.float32)
        mask = lax.conv_general_dilated(
            jnp.pad(mask, ((0, 0), (1, 1), (1, 1)), mode="edge")[:, None], kern, (1, 1),
            "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )[:, 0] / 9.0
        # center crop (10% borders, >= 1 px)
        hh, ww = o_h.shape[-2:]
        ch, cw = max(int(hh * 0.1), 1), max(int(ww * 0.1), 1)
        sl = (slice(None), slice(ch, hh - ch), slice(cw, ww - cw))
        num_s = sum(
            (jnp.clip(jnp.abs(r) - mask, 0)[sl] ** 3).sum((-1, -2)) for r in r_c
        ) ** (1 / 3)
        den_s = sum((jnp.abs(x)[sl] ** 3).sum((-1, -2)) for x in o_c) ** (1 / 3)
        nums.append(num_s + 1e-4)
        dens.append(den_s + 1e-4)
    out = {}
    for scale in range(num_scales):
        out[f"adm_scale{scale}"] = (nums[scale] / dens[scale]).reshape(b, f)
    out["adm2"] = (sum(nums) / sum(dens)).reshape(b, f)
    return out


# ------------------------------------------------------------ SVR fusion -----

class VmafModel:
    """NuSVR fusion model in the libvmaf JSON layout.

    Expected schema (the ``model_dict`` of a libvmaf ``.json`` model, e.g.
    ``vmaf_v0.6.1.json``): ``feature_names`` (6 entries), ``norm_type``
    'linear_rescale' with ``slopes``/``intercepts`` (first entry = score, rest
    per-feature), RBF ``gamma``, ``rho``, ``sv_coef`` (n_sv,), ``support_vectors``
    (n_sv, n_features), optional ``score_clip`` and polynomial
    ``score_transform``.
    """

    def __init__(self, blob: Dict) -> None:
        d = blob.get("model_dict", blob)
        self.feature_names = list(d["feature_names"])
        self.slopes = np.asarray(d["slopes"], np.float64)
        self.intercepts = np.asarray(d["intercepts"], np.float64)
        model = d.get("model", d)
        self.gamma = float(model["gamma"])
        self.rho = float(model["rho"])
        self.sv_coef = np.asarray(model["sv_coef"], np.float64).reshape(-1)
        self.support_vectors = np.asarray(model["support_vectors"], np.float64)
        self.score_clip = d.get("score_clip")
        self.score_transform = d.get("score_transform")

    @classmethod
    def from_file(cls, path: str) -> "VmafModel":
        with open(os.path.expanduser(path)) as fh:
            return cls(json.load(fh))

    def predict(self, features: Dict[str, np.ndarray]) -> np.ndarray:
        """features: name -> (...,) arrays. Returns fused score, same shape."""
        x = np.stack([np.asarray(features[name], np.float64) for name in self.feature_names], axis=-1)
        shape = x.shape[:-1]
        x = x.reshape(-1, x.shape[-1])
        x = self.slopes[1:] * x + self.intercepts[1:]  # linear_rescale normalization
        d2 = ((x[:, None, :] - self.support_vectors[None]) ** 2).sum(-1)
        y = (self.sv_coef[None, :] * np.exp(-self.gamma * d2)).sum(-1) - self.rho
        y = (y - self.intercepts[0]) / self.slopes[0]  # denormalize score
        if self.score_transform:
            p = self.score_transform
            y2 = p.get("p0", 0.0) + p.get("p1", 0.0) * y + p.get("p2", 0.0) * y**2
            if p.get("out_gte_in", False):
                y2 = np.maximum(y2, y)
            y = y2
        if self.score_clip:
            y = np.clip(y, self.score_clip[0], self.score_clip[1])
        return y.reshape(shape)


def _canonical_feature_key(name: str) -> str:
    """Map a model-file feature name to the in-tree feature-dict key.

    libvmaf models name features ``VMAF_feature_<name>_score`` (e.g.
    ``'VMAF_feature_adm2_score'`` in vmaf_v0.6.1.json, sometimes quoted);
    vmaf-torch CSV tables use ``integer_<name>``. Both resolve to
    ``integer_<name>``.
    """
    key = name.strip().strip("'\"")
    if key.startswith("VMAF_feature_") and key.endswith("_score"):
        key = key[len("VMAF_feature_") : -len("_score")]
    if not key.startswith("integer_"):
        key = f"integer_{key}"
    return key


_VMAF_FEATURE_ORDER = (
    "integer_motion2", "integer_motion",
    "integer_adm2",
    "integer_adm_scale0", "integer_adm_scale1", "integer_adm_scale2", "integer_adm_scale3",
    "integer_vif_scale0", "integer_vif_scale1", "integer_vif_scale2", "integer_vif_scale3",
)


def vmaf_features(preds: jnp.ndarray, target: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """All elementary features, (B, F) each, under the reference's key names
    (float pipelines; the ``integer_`` prefix is kept for API parity)."""
    if preds.ndim != 5 or target.ndim != 5 or preds.shape[1] != 3:
        raise ValueError(
            f"Expected (batch, 3, frames, height, width) videos, got {preds.shape} and {target.shape}"
        )
    ref = calculate_luma(target)
    dist = calculate_luma(preds)
    motion, motion2 = motion_features(ref)
    out = {"integer_motion": motion, "integer_motion2": motion2}
    for key, val in vif_features(ref, dist).items():
        out[f"integer_{key}"] = val
    for key, val in adm_features(ref, dist).items():
        out[f"integer_{key}"] = val
    return out


def video_multi_method_assessment_fusion(
    preds: jnp.ndarray,
    target: jnp.ndarray,
    features: bool = False,
    model_path: Optional[str] = None,
) -> Union[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """VMAF score (B, F), optionally with the elementary feature dict
    (reference ``functional/video/vmaf.py:40-121``).

    ``model_path`` extends the reference surface: a libvmaf-format model JSON
    drives the in-tree feature + NuSVR pipeline when ``vmaf_torch`` is absent.
    """
    if _VMAF_TORCH_AVAILABLE and model_path is None:
        return _vmaf_torch_callback(preds, target, features)
    if model_path is None:
        raise ModuleNotFoundError(
            "vmaf-torch is not installed and no `model_path` was given. Install "
            "vmaf-torch (`pip install torchmetrics[video]`) for the reference path, or "
            "pass `model_path=` pointing at a libvmaf model JSON (e.g. vmaf_v0.6.1.json) "
            "to fuse the in-tree elementary features. `vmaf_features(preds, target)` "
            "computes the features without any model."
        )
    feats = vmaf_features(preds, target)
    model = VmafModel.from_file(model_path)
    lookup = {
        name: np.asarray(feats[_canonical_feature_key(name)]) for name in model.feature_names
    }
    score = jnp.asarray(model.predict(lookup))
    if features:
        return {"vmaf": score, **feats}
    return score


def _vmaf_torch_callback(preds, target, features: bool):
    """Host callback through vmaf_torch (the reference's only path)."""
    import torch
    from vmaf_torch import VMAF

    vmaf = VMAF()
    ref = torch.as_tensor(np.asarray(calculate_luma(target))).unsqueeze(1)
    dist = torch.as_tensor(np.asarray(calculate_luma(preds))).unsqueeze(1)
    b = ref.shape[0]
    scores, tables = [], []
    for i in range(b):
        r, d = ref[i].transpose(0, 1), dist[i].transpose(0, 1)  # (F,1,H,W)
        scores.append(vmaf.compute_vmaf_score(r, d).flatten())
        if features:
            tables.append(vmaf.table(r, d))
    out_score = jnp.asarray(torch.stack(scores).numpy())
    if not features:
        return out_score
    out = {"vmaf": out_score}
    for key in _VMAF_FEATURE_ORDER:
        out[key] = jnp.asarray(
            np.stack([t[key].to_numpy() if hasattr(t[key], "to_numpy") else np.asarray(t[key]) for t in tables])
        )
    return out
