"""VMAF — Video Multi-Method Assessment Fusion.

Reference surface: ``functional/video/vmaf.py`` + ``video/vmaf.py:27`` (a thin
wrapper over the ``vmaf_torch`` package). Three paths, in resolution order:

1. ``vmaf_torch`` installed → host callback through it (bit parity with the
   reference, including the bundled ``vmaf_v0.6.1`` SVM model).
2. ``model_path=`` given → in-tree pipeline: elementary features below + NuSVR
   fusion loaded from a libvmaf-format model JSON.
3. Neither → ``vmaf_features`` still computes the elementary features (the SVM
   weights are a trained artifact that cannot be conjured offline); the fused
   score raises with instructions.

The in-tree elementary features are jnp conv pipelines over ``(B*F, H, W)`` luma
frames (separable gaussian convs — MXU-friendly batched 2-D convolutions):

- **motion / motion2**: mean |Δ| of 5-tap-gaussian-blurred consecutive luma
  frames; ``motion2[i] = min(motion[i-1,i], motion[i,i+1])`` (libvmaf motion
  feature, FILTER_5 taps).
- **vif_scale0..3**: Visual Information Fidelity (Sheikh & Bovik) per scale,
  gaussian windows N=17/9/5/3 (sd N/5), ``sigma_nsq=2``, dyadic downsampling
  between scales — the ``vifp_mscale`` float formulation libvmaf's float VIF
  follows.
- **adm2, adm_scale0..3**: Detail Loss Metric (Li et al.) in libvmaf's float-ADM
  formulation: 4-level db2 DWT with libvmaf's ``(h+1)/2`` band sizes and boundary
  reflection, decoupling with the 1-degree angle rule, Watson-JPEG2000 quantizer
  -step CSF weighting (``dwt_quant_step``: a=0.495, k=0.466, f0=0.401 at 3H/1080
  viewing), 3x3/30 contrast masking of the additive component, cube-root spatial
  pooling over the center region (10% border crop) plus libvmaf's
  ``(area/32)^(1/3)`` stabilizer, ``adm2 = Σ_s num_s / Σ_s den_s``.

Float pipelines: parity with libvmaf's fixed-point "integer_*" features is
approximate by construction. The ADM pipeline is additionally anchored to the
reference doctest golden (vmaf-torch-computed ``integer_adm2`` on seeded 32x32
noise, ``/root/reference/src/torchmetrics/functional/video/vmaf.py:107-109``)
with measured max deviation 0.045 (float-vs-fixed-point + deep-scale boundary
residual at 2x2 bands; ``tests/test_reference_doctest_goldens.py``). Properties
(identity → vif=1, adm=1, motion=0; monotone degradation) are tested on top, and
the NuSVR fusion engine is tested against hand-computed kernels on a synthetic
model file.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache
from typing import Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...utilities.imports import _module_available

_VMAF_TORCH_AVAILABLE = _module_available("vmaf_torch")

# libvmaf motion_tools FILTER_5 (gaussian, sd ~1.08)
_MOTION_FILTER = np.array(
    [0.054488685, 0.244201342, 0.402619947, 0.244201342, 0.054488685], np.float32
)

# Daubechies-2 (db2) analysis filters (orthonormal)
_DB2_LO = np.array(
    [0.482962913144690, 0.836516303737469, 0.224143868041857, -0.129409522550921],
    np.float32,
)
_DB2_HI = np.array(
    [-0.129409522550921, -0.224143868041857, 0.836516303737469, -0.482962913144690],
    np.float32,
)

def calculate_luma(video: jnp.ndarray) -> jnp.ndarray:
    """(B, 3, F, H, W) RGB in [0,1] -> (B, F, H, W) luma in [0,255]
    (reference ``functional/video/vmaf.py:31-37``)."""
    r, g, b = video[:, 0], video[:, 1], video[:, 2]
    return (0.299 * r + 0.587 * g + 0.114 * b) * 255.0


def _conv2d_sep(x: jnp.ndarray, taps: jnp.ndarray, mode: str = "reflect") -> jnp.ndarray:
    """Separable 2-D convolution of (N, H, W) frames with a symmetric 1-D tap
    vector, edge-replicated like libvmaf's convolution boundary handling."""
    k = taps.shape[0]
    pad = k // 2
    t = jnp.asarray(taps)
    xp = jnp.pad(x, ((0, 0), (pad, pad), (0, 0)), mode="edge")
    x = lax.conv_general_dilated(
        xp[:, None], t.reshape(1, 1, k, 1), (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )[:, 0]
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad, pad)), mode="edge")
    return lax.conv_general_dilated(
        xp[:, None], t.reshape(1, 1, 1, k), (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )[:, 0]


def _gaussian_taps(n: int, sd: float) -> np.ndarray:
    x = np.arange(n) - (n - 1) / 2.0
    w = np.exp(-(x**2) / (2 * sd * sd))
    return (w / w.sum()).astype(np.float32)


# ---------------------------------------------------------------- motion -----

def motion_features(ref_luma: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(B, F, H, W) -> (motion, motion2), each (B, F). Frame 0 scores 0."""
    b, f, h, w = ref_luma.shape
    blurred = _conv2d_sep(ref_luma.reshape(b * f, h, w), jnp.asarray(_MOTION_FILTER)).reshape(b, f, h, w)
    sad = jnp.abs(blurred[:, 1:] - blurred[:, :-1]).mean((-1, -2))  # (B, F-1)
    zero = jnp.zeros((b, 1), sad.dtype)
    motion = jnp.concatenate([zero, sad], axis=1)  # motion[i] = sad(i-1, i)
    nxt = jnp.concatenate([sad, jnp.full((b, 1), jnp.inf, sad.dtype)], axis=1)
    motion2 = jnp.minimum(motion, nxt)
    motion2 = motion2.at[:, 0].set(0.0)
    return motion, motion2


# ------------------------------------------------------------------- VIF -----

def vif_features(ref_luma: jnp.ndarray, dist_luma: jnp.ndarray, sigma_nsq: float = 2.0) -> Dict[str, jnp.ndarray]:
    """Per-scale VIF (B, F) for scales 0..3 (vifp_mscale float formulation)."""
    b, f, h, w = ref_luma.shape
    ref = ref_luma.reshape(b * f, h, w).astype(jnp.float32)
    dist = dist_luma.reshape(b * f, h, w).astype(jnp.float32)
    out = {}
    for scale in range(4):
        n = 2 ** (4 - scale) + 1  # 17, 9, 5, 3
        taps = jnp.asarray(_gaussian_taps(n, n / 5.0))
        if scale > 0:
            ref = _conv2d_sep(ref, taps)[:, ::2, ::2]
            dist = _conv2d_sep(dist, taps)[:, ::2, ::2]
        mu1 = _conv2d_sep(ref, taps)
        mu2 = _conv2d_sep(dist, taps)
        mu1_sq, mu2_sq, mu1_mu2 = mu1 * mu1, mu2 * mu2, mu1 * mu2
        sigma1_sq = jnp.clip(_conv2d_sep(ref * ref, taps) - mu1_sq, 0)
        sigma2_sq = jnp.clip(_conv2d_sep(dist * dist, taps) - mu2_sq, 0)
        sigma12 = _conv2d_sep(ref * dist, taps) - mu1_mu2
        g = sigma12 / (sigma1_sq + 1e-10)
        sv_sq = sigma2_sq - g * sigma12
        g = jnp.where(sigma1_sq < 1e-10, 0.0, g)
        sv_sq = jnp.where(sigma1_sq < 1e-10, sigma2_sq, sv_sq)
        sv_sq = jnp.where(sigma2_sq < 1e-10, 0.0, sv_sq)
        g = jnp.where(sigma2_sq < 1e-10, 0.0, g)
        sv_sq = jnp.where(g < 0, sigma2_sq, sv_sq)
        g = jnp.clip(g, 0)
        sv_sq = jnp.clip(sv_sq, 1e-10)
        num = jnp.log2(1 + g * g * sigma1_sq / (sv_sq + sigma_nsq)).sum((-1, -2))
        den = jnp.log2(1 + sigma1_sq / sigma_nsq).sum((-1, -2))
        out[f"vif_scale{scale}"] = (num / jnp.maximum(den, 1e-10)).reshape(b, f)
    return out


# ------------------------------------------------------------------- ADM -----

# Watson JPEG2000-book CSF model (libvmaf adm_tools ``dwt_quant_step``):
# log10(T/a) = k*(log10(f/(g*f0)))^2, quantizer step Q = 2*T/amplitude.
_ADM_CSF_A, _ADM_CSF_K, _ADM_CSF_F0 = 0.495, 0.466, 0.401
_ADM_CSF_G = (1.501, 1.0, 0.534, 1.0)  # orientation gains (LL, H/V, D, -)
# db2 basis-function amplitudes per (level, orientation)
_ADM_BASIS_AMP = (
    (0.62171, 0.67234, 0.67234, 0.72709),
    (0.34537, 0.41317, 0.41317, 0.49428),
    (0.18004, 0.22727, 0.22727, 0.28688),
    (0.091401, 0.11792, 0.11792, 0.15214),
)
_ADM_NORM_VIEW_DIST, _ADM_REF_DISPLAY_HEIGHT = 3.0, 1080


def _adm_rfactors(scale: int) -> Tuple[float, float]:
    """(rfactor_hv, rfactor_d): inverse Watson quantizer steps for the detail
    orientations at ``scale`` (0-based), at libvmaf's default 3H/1080 viewing."""

    def quant_step(theta: int) -> float:
        r = _ADM_NORM_VIEW_DIST * _ADM_REF_DISPLAY_HEIGHT * np.pi / 180.0
        temp = np.log10((2.0 ** (scale + 1)) * _ADM_CSF_F0 * _ADM_CSF_G[theta] / r)
        t = _ADM_CSF_A * (10.0 ** (_ADM_CSF_K * temp * temp))
        return 2.0 * t / _ADM_BASIS_AMP[scale][theta]

    return 1.0 / quant_step(1), 1.0 / quant_step(2)


@lru_cache(maxsize=64)
def _dwt_mats_1d(n: int) -> Tuple[np.ndarray, np.ndarray]:
    """((m, n) lo, (m, n) hi) analysis matrices for one libvmaf db2 DWT pass:
    output size ``m = (n+1)//2``, taps at ``2i - 1 + k`` with reflect-101 on the
    left edge and symmetric (edge-inclusive) reflection on the right — the
    alignment that matches the vmaf-torch golden (see module docstring). Dense
    matrices so the DWT runs as MXU matmuls, like the resize kernels."""
    m = (n + 1) // 2
    lo = np.zeros((m, n), np.float64)
    hi = np.zeros((m, n), np.float64)
    for i in range(m):
        for k in range(4):
            ind = 2 * i - 1 + k
            if ind < 0:
                ind = -ind
            if ind >= n:
                ind = 2 * n - ind - 1
            lo[i, ind] += _DB2_LO[k]
            hi[i, ind] += _DB2_HI[k]
    return lo.astype(np.float32), hi.astype(np.float32)


def _dwt2_db2(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One libvmaf-convention db2 DWT level of (N, H, W) -> (A, H, V, D), band
    sizes ``(dim+1)//2``, as four dense matmuls (two per axis)."""
    h, w = x.shape[-2:]
    vlo, vhi = _dwt_mats_1d(h)
    hlo, hhi = _dwt_mats_1d(w)
    lo_r = jnp.einsum("nhw,Mh->nMw", x, jnp.asarray(vlo), precision="highest")
    hi_r = jnp.einsum("nhw,Mh->nMw", x, jnp.asarray(vhi), precision="highest")
    return (
        jnp.einsum("nMw,Ww->nMW", lo_r, jnp.asarray(hlo), precision="highest"),  # A
        jnp.einsum("nMw,Ww->nMW", hi_r, jnp.asarray(hlo), precision="highest"),  # H
        jnp.einsum("nMw,Ww->nMW", lo_r, jnp.asarray(hhi), precision="highest"),  # V
        jnp.einsum("nMw,Ww->nMW", hi_r, jnp.asarray(hhi), precision="highest"),  # D
    )


def adm_features(ref_luma: jnp.ndarray, dist_luma: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """libvmaf float-ADM per scale + combined adm2 (B, F).

    Structure follows libvmaf's ``adm.c`` float path: decouple -> Watson-CSF ->
    3x3/30 contrast-mask of the additive component -> 10% border crop ->
    cube-root pooling with the ``(area/32)^(1/3)`` stabilizer, and
    ``adm2 = Σ_s num_s / Σ_s den_s``. Identity still scores exactly 1 (T == O
    makes the additive component, hence the mask and num == den)."""
    b, f, h, w = ref_luma.shape
    o = ref_luma.reshape(b * f, h, w).astype(jnp.float32)
    t = dist_luma.reshape(b * f, h, w).astype(jnp.float32)
    num_scales, eps = 4, 1e-30
    nums, dens = [], []
    for scale in range(num_scales):
        o_a, o_h, o_v, o_d = _dwt2_db2(o)
        t_a, t_h, t_v, t_d = _dwt2_db2(t)
        o = o_a
        t = t_a
        # decoupling: restored R = clip(T/O, 0, 1) * O, except within 1 degree of
        # equal orientation where the distortion is treated as purely additive
        ot_dp = o_h * t_h + o_v * t_v
        o_mag_sq = o_h * o_h + o_v * o_v + eps
        t_mag_sq = t_h * t_h + t_v * t_v + eps
        cos_1deg_sq = np.cos(np.deg2rad(1.0)) ** 2
        angle_ok = (ot_dp >= 0) & (ot_dp * ot_dp >= cos_1deg_sq * o_mag_sq * t_mag_sq)
        rests = []
        for o_s, t_s in ((o_h, t_h), (o_v, t_v), (o_d, t_d)):
            k = jnp.clip(t_s / (o_s + jnp.where(o_s >= 0, eps, -eps)), 0.0, 1.0)
            rests.append(jnp.where(angle_ok, t_s, k * o_s))
        rf_hv, rf_d = _adm_rfactors(scale)
        rf = (rf_hv, rf_hv, rf_d)
        o_bands = (o_h, o_v, o_d)
        t_bands = (t_h, t_v, t_d)
        # contrast masking: threshold = 3x3 sum (edge-padded) of the CSF'd additive
        # impairment A = T - R across all three orientations, /30 — zero when
        # T == O, so identity scores exactly 1
        mask = sum(jnp.abs((t_s - r_s) * rfi) for t_s, r_s, rfi in zip(t_bands, rests, rf)) / 30.0
        kern = jnp.ones((1, 1, 3, 3), jnp.float32)
        mask = lax.conv_general_dilated(
            jnp.pad(mask, ((0, 0), (1, 1), (1, 1)), mode="edge")[:, None], kern, (1, 1),
            "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )[:, 0]
        # libvmaf border crop: left = int(w*0.1 - 0.5), interior [left, w - left)
        hh, ww = o_h.shape[-2:]
        ch = max(int(hh * 0.1 - 0.5), 0)
        cw = max(int(ww * 0.1 - 0.5), 0)
        sl = (slice(None), slice(ch, hh - ch), slice(cw, ww - cw))
        num_s = sum(
            (jnp.clip(jnp.abs(r * rfi) - mask, 0)[sl] ** 3).sum((-1, -2))
            for r, rfi in zip(rests, rf)
        ) ** (1 / 3)
        den_s = sum(
            (jnp.abs(x * rfi)[sl] ** 3).sum((-1, -2)) for x, rfi in zip(o_bands, rf)
        ) ** (1 / 3)
        # libvmaf per-scale stabilizer: cbrt(interior_area / 32) on both sides
        extra = (((hh - 2 * ch) * (ww - 2 * cw)) / 32.0) ** (1 / 3)
        nums.append(num_s + extra)
        dens.append(den_s + extra)
    out = {}
    for scale in range(num_scales):
        out[f"adm_scale{scale}"] = (nums[scale] / dens[scale]).reshape(b, f)
    out["adm2"] = (sum(nums) / sum(dens)).reshape(b, f)
    return out


# ------------------------------------------------------------ SVR fusion -----

class VmafModel:
    """NuSVR fusion model in the libvmaf JSON layout.

    Expected schema (the ``model_dict`` of a libvmaf ``.json`` model, e.g.
    ``vmaf_v0.6.1.json``): ``feature_names`` (6 entries), ``norm_type``
    'linear_rescale' with ``slopes``/``intercepts`` (first entry = score, rest
    per-feature), RBF ``gamma``, ``rho``, ``sv_coef`` (n_sv,), ``support_vectors``
    (n_sv, n_features), optional ``score_clip`` and polynomial
    ``score_transform``.
    """

    def __init__(self, blob: Dict) -> None:
        d = blob.get("model_dict", blob)
        self.feature_names = list(d["feature_names"])
        self.slopes = np.asarray(d["slopes"], np.float64)
        self.intercepts = np.asarray(d["intercepts"], np.float64)
        model = d.get("model", d)
        self.gamma = float(model["gamma"])
        self.rho = float(model["rho"])
        self.sv_coef = np.asarray(model["sv_coef"], np.float64).reshape(-1)
        self.support_vectors = np.asarray(model["support_vectors"], np.float64)
        self.score_clip = d.get("score_clip")
        self.score_transform = d.get("score_transform")

    @classmethod
    def from_file(cls, path: str) -> "VmafModel":
        with open(os.path.expanduser(path)) as fh:
            return cls(json.load(fh))

    def predict(self, features: Dict[str, np.ndarray]) -> np.ndarray:
        """features: name -> (...,) arrays. Returns fused score, same shape."""
        x = np.stack([np.asarray(features[name], np.float64) for name in self.feature_names], axis=-1)
        shape = x.shape[:-1]
        x = x.reshape(-1, x.shape[-1])
        x = self.slopes[1:] * x + self.intercepts[1:]  # linear_rescale normalization
        d2 = ((x[:, None, :] - self.support_vectors[None]) ** 2).sum(-1)
        y = (self.sv_coef[None, :] * np.exp(-self.gamma * d2)).sum(-1) - self.rho
        y = (y - self.intercepts[0]) / self.slopes[0]  # denormalize score
        if self.score_transform:
            p = self.score_transform
            y2 = p.get("p0", 0.0) + p.get("p1", 0.0) * y + p.get("p2", 0.0) * y**2
            if p.get("out_gte_in", False):
                y2 = np.maximum(y2, y)
            y = y2
        if self.score_clip:
            y = np.clip(y, self.score_clip[0], self.score_clip[1])
        return y.reshape(shape)


def _canonical_feature_key(name: str) -> str:
    """Map a model-file feature name to the in-tree feature-dict key.

    libvmaf models name features ``VMAF_feature_<name>_score`` (e.g.
    ``'VMAF_feature_adm2_score'`` in vmaf_v0.6.1.json, sometimes quoted);
    vmaf-torch CSV tables use ``integer_<name>``. Both resolve to
    ``integer_<name>``.
    """
    key = name.strip().strip("'\"")
    if key.startswith("VMAF_feature_") and key.endswith("_score"):
        key = key[len("VMAF_feature_") : -len("_score")]
    if not key.startswith("integer_"):
        key = f"integer_{key}"
    return key


_VMAF_FEATURE_ORDER = (
    "integer_motion2", "integer_motion",
    "integer_adm2",
    "integer_adm_scale0", "integer_adm_scale1", "integer_adm_scale2", "integer_adm_scale3",
    "integer_vif_scale0", "integer_vif_scale1", "integer_vif_scale2", "integer_vif_scale3",
)


def vmaf_features(preds: jnp.ndarray, target: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """All elementary features, (B, F) each, under the reference's key names
    (float pipelines; the ``integer_`` prefix is kept for API parity)."""
    if preds.ndim != 5 or target.ndim != 5 or preds.shape[1] != 3:
        raise ValueError(
            f"Expected (batch, 3, frames, height, width) videos, got {preds.shape} and {target.shape}"
        )
    ref = calculate_luma(target)
    dist = calculate_luma(preds)
    motion, motion2 = motion_features(ref)
    out = {"integer_motion": motion, "integer_motion2": motion2}
    for key, val in vif_features(ref, dist).items():
        out[f"integer_{key}"] = val
    for key, val in adm_features(ref, dist).items():
        out[f"integer_{key}"] = val
    return out


def video_multi_method_assessment_fusion(
    preds: jnp.ndarray,
    target: jnp.ndarray,
    features: bool = False,
    model_path: Optional[str] = None,
) -> Union[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """VMAF score (B, F), optionally with the elementary feature dict
    (reference ``functional/video/vmaf.py:40-121``).

    ``model_path`` extends the reference surface: a libvmaf-format model JSON
    drives the in-tree feature + NuSVR pipeline when ``vmaf_torch`` is absent.
    """
    if _VMAF_TORCH_AVAILABLE and model_path is None:
        return _vmaf_torch_callback(preds, target, features)
    if model_path is None:
        raise ModuleNotFoundError(
            "vmaf-torch is not installed and no `model_path` was given. Install "
            "vmaf-torch (`pip install torchmetrics[video]`) for the reference path, or "
            "pass `model_path=` pointing at a libvmaf model JSON (e.g. vmaf_v0.6.1.json) "
            "to fuse the in-tree elementary features. `vmaf_features(preds, target)` "
            "computes the features without any model."
        )
    feats = vmaf_features(preds, target)
    model = VmafModel.from_file(model_path)
    lookup = {
        name: np.asarray(feats[_canonical_feature_key(name)]) for name in model.feature_names
    }
    score = jnp.asarray(model.predict(lookup))
    if features:
        return {"vmaf": score, **feats}
    return score


def _vmaf_torch_callback(preds, target, features: bool):
    """Host callback through vmaf_torch (the reference's only path)."""
    import torch
    from vmaf_torch import VMAF

    vmaf = VMAF()
    ref = torch.as_tensor(np.asarray(calculate_luma(target))).unsqueeze(1)
    dist = torch.as_tensor(np.asarray(calculate_luma(preds))).unsqueeze(1)
    b = ref.shape[0]
    scores, tables = [], []
    for i in range(b):
        r, d = ref[i].transpose(0, 1), dist[i].transpose(0, 1)  # (F,1,H,W)
        scores.append(vmaf.compute_vmaf_score(r, d).flatten())
        if features:
            tables.append(vmaf.table(r, d))
    out_score = jnp.asarray(torch.stack(scores).numpy())
    if not features:
        return out_score
    out = {"vmaf": out_score}
    for key in _VMAF_FEATURE_ORDER:
        out[key] = jnp.asarray(
            np.stack([t[key].to_numpy() if hasattr(t[key], "to_numpy") else np.asarray(t[key]) for t in tables])
        )
    return out
