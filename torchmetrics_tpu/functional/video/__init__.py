"""Video functional kernels (reference ``functional/video/``).

Unlike the reference (which only exports VMAF when the ``vmaf_torch`` wheel is
importable), the in-tree elementary features and model-file fusion path exist
unconditionally — gating happens inside the function, per path.
"""

from .vmaf import (
    VmafModel,
    calculate_luma,
    video_multi_method_assessment_fusion,
    vmaf_features,
)

__all__ = [
    "VmafModel",
    "calculate_luma",
    "video_multi_method_assessment_fusion",
    "vmaf_features",
]
