"""Fleiss' kappa (reference ``functional/nominal/fleiss_kappa.py``).

Fully jittable: the probs branch collapses through argmax + one-hot sum (static
category axis), the counts branch is already a dense (N, C) table.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _fleiss_kappa_update(ratings: jnp.ndarray, mode: str = "counts") -> jnp.ndarray:
    if mode == "probs":
        ratings = jnp.asarray(ratings)
        if ratings.ndim != 3 or not jnp.issubdtype(ratings.dtype, jnp.floating):
            raise ValueError(
                "If argument ``mode`` is 'probs', ratings must have 3 dimensions with the format"
                " [n_samples, n_categories, n_raters] and be floating point."
            )
        num_categories = ratings.shape[1]
        choices = jnp.argmax(ratings, axis=1)  # (N, R)
        return jax.nn.one_hot(choices, num_categories, dtype=jnp.int32).sum(axis=1)
    ratings = jnp.asarray(ratings)
    if ratings.ndim != 2 or jnp.issubdtype(ratings.dtype, jnp.floating):
        raise ValueError(
            "If argument ``mode`` is `counts`, ratings must have 2 dimensions with the format"
            " [n_samples, n_categories] and be none floating point."
        )
    return ratings


def _fleiss_kappa_compute(counts: jnp.ndarray) -> jnp.ndarray:
    counts = counts.astype(jnp.float32)
    total = counts.shape[0]
    num_raters = counts.sum(axis=1).max()
    p_i = counts.sum(axis=0) / (total * num_raters)
    p_j = ((counts**2).sum(axis=1) - num_raters) / (num_raters * (num_raters - 1))
    p_bar = p_j.mean()
    pe_bar = (p_i**2).sum()
    return (p_bar - pe_bar) / (1 - pe_bar + 1e-5)


def fleiss_kappa(ratings: jnp.ndarray, mode: str = "counts") -> jnp.ndarray:
    r"""Fleiss' kappa inter-rater agreement: ``(p_bar - pe_bar) / (1 - pe_bar)``.

    ``ratings`` is ``[n_samples, n_categories]`` integer counts (``mode="counts"``) or
    ``[n_samples, n_categories, n_raters]`` probabilities (``mode="probs"``).


    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import fleiss_kappa
        >>> ratings = jnp.asarray([[0, 4, 1], [2, 2, 1], [4, 0, 1], [1, 3, 1]])
        >>> fleiss_kappa(ratings, mode='counts')
        Array(0.09448675, dtype=float32)
    """
    if mode not in ["counts", "probs"]:
        raise ValueError("Argument ``mode`` must be one of ['counts', 'probs'].")
    counts = _fleiss_kappa_update(ratings, mode)
    return _fleiss_kappa_compute(counts)
