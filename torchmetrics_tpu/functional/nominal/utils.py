"""Nominal-association shared helpers (reference ``functional/nominal/utils.py``).

Update-side work (confusion-matrix accumulation) is jittable and rides the existing
one-hot-matmul bincount; compute-side work operates on a tiny ``(C, C)`` table and
runs host-side in numpy (the reference's ``_drop_empty_rows_and_cols`` is inherently
dynamic-shape, so it cannot live under jit anyway).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ...utilities.prints import rank_zero_warn


def _nominal_input_validation(nan_strategy: str, nan_replace_value: Optional[float]) -> None:
    if nan_strategy not in ["replace", "drop"]:
        raise ValueError(
            f"Argument `nan_strategy` is expected to be one of `['replace', 'drop']`, but got {nan_strategy}"
        )
    if nan_strategy == "replace" and not isinstance(nan_replace_value, (float, int)):
        raise ValueError(
            "Argument `nan_replace` is expected to be of a type `int` or `float` when `nan_strategy = 'replace`, "
            f"but got {nan_replace_value}"
        )


def _handle_nan_in_data(
    preds: np.ndarray,
    target: np.ndarray,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Replace or drop NaN rows (host-side: 'drop' is dynamic-shape)."""
    if nan_strategy == "replace":
        return np.nan_to_num(preds, nan=nan_replace_value), np.nan_to_num(target, nan=nan_replace_value)
    keep = ~(np.isnan(preds) | np.isnan(target))
    return preds[keep], target[keep]


def _drop_empty_rows_and_cols(confmat: np.ndarray) -> np.ndarray:
    confmat = confmat[confmat.sum(1) != 0]
    return confmat[:, confmat.sum(0) != 0]


def _compute_expected_freqs(confmat: np.ndarray) -> np.ndarray:
    margin_rows, margin_cols = confmat.sum(1), confmat.sum(0)
    return np.outer(margin_rows, margin_cols) / confmat.sum()


def _compute_chi_squared(confmat: np.ndarray, bias_correction: bool) -> float:
    """Chi-square independence statistic (scipy.stats.contingency semantics, incl. the
    Yates continuity correction at one degree of freedom)."""
    expected = _compute_expected_freqs(confmat)
    df = expected.size - sum(expected.shape) + expected.ndim - 1
    if df == 0:
        return 0.0
    if df == 1 and bias_correction:
        # Yates: move observed toward expected by min(0.5, |diff|). The reference
        # clamps by |sign(diff)| (always 0.5 — nominal/utils.py:53-56), over-correcting
        # when |observed-expected| < 0.5; scipy's form is used here instead.
        diff = expected - confmat
        confmat = confmat + np.sign(diff) * np.minimum(0.5, np.abs(diff))
    return float(((confmat - expected) ** 2 / expected).sum())


def _compute_phi_squared_corrected(phi_squared, num_rows, num_cols, confmat_sum) -> float:
    return max(0.0, phi_squared - ((num_rows - 1) * (num_cols - 1)) / (confmat_sum - 1))


def _compute_rows_and_cols_corrected(num_rows, num_cols, confmat_sum) -> Tuple[float, float]:
    rows_corrected = num_rows - (num_rows - 1) ** 2 / (confmat_sum - 1)
    cols_corrected = num_cols - (num_cols - 1) ** 2 / (confmat_sum - 1)
    return rows_corrected, cols_corrected


def _compute_bias_corrected_values(phi_squared, num_rows, num_cols, confmat_sum) -> Tuple[float, float, float]:
    return (
        _compute_phi_squared_corrected(phi_squared, num_rows, num_cols, confmat_sum),
        *_compute_rows_and_cols_corrected(num_rows, num_cols, confmat_sum),
    )


def _unable_to_use_bias_correction_warning(metric_name: str) -> None:
    rank_zero_warn(
        f"Unable to compute {metric_name} using bias correction. Please consider to set `bias_correction=False`."
    )
