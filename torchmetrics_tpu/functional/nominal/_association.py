"""Confusion-matrix association statistics: Cramer's V, Pearson's contingency
coefficient, Theil's U, Tschuprow's T (reference ``functional/nominal/{cramers,
pearson,theils_u,tschuprows}.py``).

All four share one sufficient statistic — a ``(C, C)`` contingency table accumulated
with the jitted one-hot-matmul bincount — and differ only in the host-side scalar
computed from it, so the update kernel lives here once.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..classification.confusion_matrix import _multiclass_confusion_matrix_update
from .utils import (
    _compute_bias_corrected_values,
    _compute_chi_squared,
    _drop_empty_rows_and_cols,
    _handle_nan_in_data,
    _nominal_input_validation,
    _unable_to_use_bias_correction_warning,
)


def _nominal_update(
    preds,
    target,
    num_classes: Optional[int] = None,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> jnp.ndarray:
    """Shared contingency-table update. 2D inputs collapse through argmax; NaN policy
    is applied host-side (drop is dynamic-shape). ``num_classes=None`` infers the
    table size from the *collapsed, NaN-handled* labels."""
    preds = np.asarray(preds)
    target = np.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    if num_classes is None:
        num_classes = int(max(preds.max(initial=0), target.max(initial=0))) + 1
    preds_j = jnp.asarray(preds.astype(np.int32))
    target_j = jnp.asarray(target.astype(np.int32))
    return _multiclass_confusion_matrix_update(preds_j, target_j, None, num_classes)


def _cramers_v_update(preds, target, num_classes, nan_strategy="replace", nan_replace_value=0.0):
    return _nominal_update(preds, target, num_classes, nan_strategy, nan_replace_value)


def _cramers_v_compute(confmat, bias_correction: bool) -> jnp.ndarray:
    confmat = _drop_empty_rows_and_cols(np.asarray(confmat, np.float64))
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction)
    phi_squared = chi_squared / cm_sum
    num_rows, num_cols = confmat.shape
    if bias_correction:
        phi_squared_corrected, rows_corrected, cols_corrected = _compute_bias_corrected_values(
            phi_squared, num_rows, num_cols, cm_sum
        )
        if min(rows_corrected, cols_corrected) == 1:
            _unable_to_use_bias_correction_warning(metric_name="Cramer's V")
            return jnp.asarray(float("nan"), jnp.float32)
        value = np.sqrt(phi_squared_corrected / min(rows_corrected - 1, cols_corrected - 1))
    else:
        value = np.sqrt(phi_squared / min(num_rows - 1, num_cols - 1))
    return jnp.asarray(np.clip(value, 0.0, 1.0), jnp.float32)


def cramers_v(
    preds,
    target,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> jnp.ndarray:
    r"""Cramer's V: ``sqrt((chi^2/n) / min(r-1, k-1))`` association between two
    categorical series (reference ``functional/nominal/cramers.py:89``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import cramers_v
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 1, 0])
        >>> cramers_v(preds, target)
        Array(0.6846532, dtype=float32)
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _cramers_v_update(preds, target, None, nan_strategy, nan_replace_value)
    return _cramers_v_compute(confmat, bias_correction)


def cramers_v_matrix(matrix, bias_correction: bool = True, nan_strategy="replace", nan_replace_value=0.0):
    """Pairwise Cramer's V over columns of an observation matrix (reference
    ``functional/nominal/cramers.py:144``)."""
    return _nominal_matrix(matrix, lambda p, t: cramers_v(p, t, bias_correction, nan_strategy, nan_replace_value))


def _pearsons_contingency_coefficient_update(preds, target, num_classes, nan_strategy="replace", nan_replace_value=0.0):
    return _nominal_update(preds, target, num_classes, nan_strategy, nan_replace_value)


def _pearsons_contingency_coefficient_compute(confmat) -> jnp.ndarray:
    confmat = _drop_empty_rows_and_cols(np.asarray(confmat, np.float64))
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction=False)
    phi_squared = chi_squared / cm_sum
    value = np.sqrt(phi_squared / (1 + phi_squared))
    return jnp.asarray(np.clip(value, 0.0, 1.0), jnp.float32)


def pearsons_contingency_coefficient(
    preds, target, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> jnp.ndarray:
    r"""Pearson's contingency coefficient ``sqrt(phi^2 / (1 + phi^2))`` (reference
    ``functional/nominal/pearson.py:77``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import pearsons_contingency_coefficient
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 1, 0])
        >>> pearsons_contingency_coefficient(preds, target)
        Array(0.73480344, dtype=float32)
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _pearsons_contingency_coefficient_update(preds, target, None, nan_strategy, nan_replace_value)
    return _pearsons_contingency_coefficient_compute(confmat)


def pearsons_contingency_coefficient_matrix(matrix, nan_strategy="replace", nan_replace_value=0.0):
    """Pairwise Pearson's contingency coefficient over matrix columns."""
    return _nominal_matrix(matrix, lambda p, t: pearsons_contingency_coefficient(p, t, nan_strategy, nan_replace_value))


def _theils_u_update(preds, target, num_classes, nan_strategy="replace", nan_replace_value=0.0):
    return _nominal_update(preds, target, num_classes, nan_strategy, nan_replace_value)


def _conditional_entropy_compute(confmat: np.ndarray) -> float:
    confmat = _drop_empty_rows_and_cols(confmat)
    total = confmat.sum()
    p_xy = confmat / total
    p_y = (confmat.sum(1) / total)[:, None]
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = p_xy * np.log(p_y / p_xy)
    return float(np.nansum(terms))


def _theils_u_compute(confmat) -> jnp.ndarray:
    confmat = _drop_empty_rows_and_cols(np.asarray(confmat, np.float64))
    s_xy = _conditional_entropy_compute(confmat)
    total = confmat.sum()
    p_x = confmat.sum(0) / total
    with np.errstate(divide="ignore", invalid="ignore"):
        s_x = -np.nansum(p_x * np.log(p_x))
    if s_x == 0:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.asarray((s_x - s_xy) / s_x, jnp.float32)


def theils_u(
    preds, target, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> jnp.ndarray:
    r"""Theil's U (uncertainty coefficient) ``(H(X) - H(X|Y)) / H(X)`` — asymmetric
    association (reference ``functional/nominal/theils_u.py:118``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import theils_u
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 1, 0])
        >>> theils_u(preds, target)
        Array(0.61806566, dtype=float32)
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _theils_u_update(preds, target, None, nan_strategy, nan_replace_value)
    return _theils_u_compute(confmat)


def theils_u_matrix(matrix, nan_strategy="replace", nan_replace_value=0.0):
    """Pairwise Theil's U over matrix columns (asymmetric — full off-diagonal)."""
    matrix = np.asarray(matrix)
    num_vars = matrix.shape[1]
    out = np.eye(num_vars, dtype=np.float32)
    for i in range(num_vars):
        for j in range(num_vars):
            if i != j:
                out[i, j] = float(theils_u(matrix[:, i], matrix[:, j], nan_strategy, nan_replace_value))
    return jnp.asarray(out)


def _tschuprows_t_update(preds, target, num_classes, nan_strategy="replace", nan_replace_value=0.0):
    return _nominal_update(preds, target, num_classes, nan_strategy, nan_replace_value)


def _tschuprows_t_compute(confmat, bias_correction: bool) -> jnp.ndarray:
    confmat = _drop_empty_rows_and_cols(np.asarray(confmat, np.float64))
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction)
    phi_squared = chi_squared / cm_sum
    num_rows, num_cols = confmat.shape
    if bias_correction:
        phi_squared_corrected, rows_corrected, cols_corrected = _compute_bias_corrected_values(
            phi_squared, num_rows, num_cols, cm_sum
        )
        if min(rows_corrected, cols_corrected) == 1:
            _unable_to_use_bias_correction_warning(metric_name="Tschuprow's T")
            return jnp.asarray(float("nan"), jnp.float32)
        value = np.sqrt(phi_squared_corrected / np.sqrt((rows_corrected - 1) * (cols_corrected - 1)))
    else:
        value = np.sqrt(phi_squared / np.sqrt((num_rows - 1) * (num_cols - 1)))
    return jnp.asarray(np.clip(value, 0.0, 1.0), jnp.float32)


def tschuprows_t(
    preds,
    target,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> jnp.ndarray:
    r"""Tschuprow's T: ``sqrt((chi^2/n) / sqrt((r-1)(k-1)))`` (reference
    ``functional/nominal/tschuprows.py:95``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import tschuprows_t
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 1, 0])
        >>> tschuprows_t(preds, target)
        Array(0.6846532, dtype=float32)
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _tschuprows_t_update(preds, target, None, nan_strategy, nan_replace_value)
    return _tschuprows_t_compute(confmat, bias_correction)


def tschuprows_t_matrix(matrix, bias_correction: bool = True, nan_strategy="replace", nan_replace_value=0.0):
    """Pairwise Tschuprow's T over matrix columns."""
    return _nominal_matrix(matrix, lambda p, t: tschuprows_t(p, t, bias_correction, nan_strategy, nan_replace_value))


def _nominal_matrix(matrix, pair_fn) -> jnp.ndarray:
    """Symmetric pairwise association matrix over observation-matrix columns."""
    matrix = np.asarray(matrix)
    num_vars = matrix.shape[1]
    out = np.eye(num_vars, dtype=np.float32)
    for i, j in [(i, j) for i in range(num_vars) for j in range(i + 1, num_vars)]:
        val = float(pair_fn(matrix[:, i], matrix[:, j]))
        out[i, j] = out[j, i] = val
    return jnp.asarray(out)
