"""Nominal tower — stateless kernels (reference ``src/torchmetrics/functional/nominal/``)."""

from ._association import (
    cramers_v,
    cramers_v_matrix,
    pearsons_contingency_coefficient,
    pearsons_contingency_coefficient_matrix,
    theils_u,
    theils_u_matrix,
    tschuprows_t,
    tschuprows_t_matrix,
)
from .fleiss_kappa import fleiss_kappa

__all__ = [
    "cramers_v",
    "cramers_v_matrix",
    "fleiss_kappa",
    "pearsons_contingency_coefficient",
    "pearsons_contingency_coefficient_matrix",
    "theils_u",
    "theils_u_matrix",
    "tschuprows_t",
    "tschuprows_t_matrix",
]
