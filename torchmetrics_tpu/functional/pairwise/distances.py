"""Pairwise distance/similarity kernels (reference ``functional/pairwise/{cosine,
euclidean,linear,manhattan,minkowski}.py``).

All five are single fused XLA expressions; cosine/linear/euclidean ride the MXU
(one matmul each). The reference upcasts euclidean/minkowski to float64 for
precision — TPU f64 is software-emulated, so here euclidean uses the
max-precision float available (f32 accumulate via the norm+matmul identity, with a
clamp at 0) and documents the envelope.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ...utilities.exceptions import TorchMetricsUserError
from .helpers import _check_input, _reduce_distance_matrix, _zero_diagonal


def _pairwise_cosine_similarity_update(x, y=None, zero_diagonal: Optional[bool] = None) -> jnp.ndarray:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x = x / jnp.linalg.norm(x, axis=1, keepdims=True)
    y = y / jnp.linalg.norm(y, axis=1, keepdims=True)
    return _zero_diagonal(x @ y.T, zero_diagonal)


def pairwise_cosine_similarity(
    x, y=None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> jnp.ndarray:
    r"""Pairwise cosine similarity ``<x,y>/(||x||*||y||)`` between rows of x and y
    (or x with itself when y is omitted, diagonal zeroed by default).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import pairwise_cosine_similarity
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_cosine_similarity(x, y)
        Array([[0.5547002 , 0.86824316],
               [0.5144958 , 0.84366155]], dtype=float32)
    """
    return _reduce_distance_matrix(_pairwise_cosine_similarity_update(x, y, zero_diagonal), reduction)


def _pairwise_euclidean_distance_update(x, y=None, zero_diagonal: Optional[bool] = None) -> jnp.ndarray:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x_norm = (x * x).sum(axis=1, keepdims=True)
    y_norm = (y * y).sum(axis=1)
    distance = jnp.clip(x_norm + y_norm - 2 * x @ y.T, 0)
    return jnp.sqrt(_zero_diagonal(distance, zero_diagonal))


def pairwise_euclidean_distance(
    x, y=None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> jnp.ndarray:
    r"""Pairwise euclidean distance via the ``||x||^2 + ||y||^2 - 2<x,y>`` identity
    (one matmul; clamped at zero against cancellation).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import pairwise_euclidean_distance
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_euclidean_distance(x, y)
        Array([[3.1622777, 2.       ],
               [5.3851647, 4.1231055]], dtype=float32)
    """
    return _reduce_distance_matrix(_pairwise_euclidean_distance_update(x, y, zero_diagonal), reduction)


def _pairwise_linear_similarity_update(x, y=None, zero_diagonal: Optional[bool] = None) -> jnp.ndarray:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    return _zero_diagonal(x @ y.T, zero_diagonal)


def pairwise_linear_similarity(
    x, y=None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> jnp.ndarray:
    r"""Pairwise linear similarity ``<x,y>`` between rows.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import pairwise_linear_similarity
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_linear_similarity(x, y)
        Array([[ 2.,  7.],
               [ 3., 11.]], dtype=float32)
    """
    return _reduce_distance_matrix(_pairwise_linear_similarity_update(x, y, zero_diagonal), reduction)


def _pairwise_manhattan_distance_update(x, y=None, zero_diagonal: Optional[bool] = None) -> jnp.ndarray:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.abs(x[:, None, :] - y[None, :, :]).sum(axis=-1)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_manhattan_distance(
    x, y=None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> jnp.ndarray:
    r"""Pairwise manhattan (L1) distance between rows.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import pairwise_manhattan_distance
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_manhattan_distance(x, y)
        Array([[4., 2.],
               [7., 5.]], dtype=float32)
    """
    return _reduce_distance_matrix(_pairwise_manhattan_distance_update(x, y, zero_diagonal), reduction)


def _pairwise_minkowski_distance_update(
    x, y=None, exponent: Union[int, float] = 2, zero_diagonal: Optional[bool] = None
) -> jnp.ndarray:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    if not (isinstance(exponent, (float, int)) and exponent >= 1):
        raise TorchMetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {exponent}")
    distance = (jnp.abs(x[:, None, :] - y[None, :, :]) ** exponent).sum(axis=-1) ** (1.0 / exponent)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_minkowski_distance(
    x,
    y=None,
    exponent: Union[int, float] = 2,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> jnp.ndarray:
    r"""Pairwise minkowski distance ``(sum |x_i - y_j|^p)^(1/p)`` between rows.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import pairwise_minkowski_distance
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_minkowski_distance(x, y, exponent=4)
        Array([[3.0092168, 2.       ],
               [5.0316973, 4.0039005]], dtype=float32)
    """
    return _reduce_distance_matrix(_pairwise_minkowski_distance_update(x, y, exponent, zero_diagonal), reduction)
