"""Continuous ranked probability score for ensemble forecasts. Parity: reference
``functional/regression/crps.py`` (_crps_update:23, _crps_compute:59)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def _crps_update(preds, target):
    """Per-batch CRPS terms: sum of mean-absolute-error terms and pairwise ensemble
    spread terms, plus the batch size (sum-reducible states).

    The O(m^2) pairwise term is one (B, m, m) elementwise abs-diff — batched and
    MXU/VPU-friendly; no sort needed for the ensemble term.
    """
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if preds.ndim != 2:
        raise ValueError(f"Expected preds of shape (batch_size, ensemble_members), but got {preds.shape}.")
    if target.shape != preds.shape[:1]:
        raise ValueError(f"Expected target of shape (batch_size,), but got {target.shape}.")
    batch_size, m = preds.shape
    if m < 2:
        raise ValueError(f"CRPS requires at least 2 ensemble members, but you provided {preds.shape}.")
    diff = jnp.sum(jnp.abs(preds - target[:, None]), axis=1) / m
    ensemble_diffs = jnp.abs(preds[:, :, None] - preds[:, None, :])
    ensemble_sum = jnp.sum(ensemble_diffs, axis=(1, 2)) / (2 * m * m)
    return batch_size, diff, ensemble_sum


def _crps_compute(batch_size, diff: Array, ensemble_sum: Array) -> Array:
    return jnp.mean(diff - ensemble_sum)


def continuous_ranked_probability_score(preds, target) -> Array:
    """Continuous ranked probability score.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import continuous_ranked_probability_score
        >>> preds = jnp.asarray([[1.0, 2.0, 3.0], [2.0, 3.0, 4.0]])
        >>> target = jnp.asarray([2.0, 3.0])
        >>> continuous_ranked_probability_score(preds, target)
        Array(0.22222224, dtype=float32)
    """
    batch_size, diff, ensemble_sum = _crps_update(preds, target)
    return _crps_compute(batch_size, diff, ensemble_sum)
