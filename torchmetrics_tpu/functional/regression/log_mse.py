"""Mean squared log error + log-cosh error. Parity: reference
``functional/regression/{log_mse,log_cosh}.py``."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from .utils import _check_data_shape_to_num_outputs

Array = jax.Array


def _mean_squared_log_error_update(preds, target):
    _check_same_shape(preds, target)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    d = jnp.log1p(preds) - jnp.log1p(target)
    return jnp.sum(d * d), target.size


def _mean_squared_log_error_compute(sum_squared_log_error: Array, num_obs) -> Array:
    return sum_squared_log_error / num_obs


def mean_squared_log_error(preds, target) -> Array:
    """Mean squared log error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import mean_squared_log_error
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 1.5, 2.0, 7.0])
        >>> mean_squared_log_error(preds, target)
        Array(0.02037413, dtype=float32)
    """
    s, n = _mean_squared_log_error_update(preds, target)
    return _mean_squared_log_error_compute(s, n)


def _unsqueeze_tensors(preds, target):
    if preds.ndim == 2:
        return preds, target
    return preds[:, None], target[:, None]


def _log_cosh_error_update(preds, target, num_outputs: int):
    _check_same_shape(preds, target)
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    preds, target = _unsqueeze_tensors(jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32))
    diff = preds - target
    # stable log(cosh(x)) = x + softplus(-2x) - log(2)
    sum_log_cosh_error = jnp.sum(diff + jax.nn.softplus(-2.0 * diff) - jnp.log(2.0), axis=0).squeeze()
    return sum_log_cosh_error, target.shape[0]


def _log_cosh_error_compute(sum_log_cosh_error: Array, num_obs) -> Array:
    return (sum_log_cosh_error / num_obs).squeeze()


def log_cosh_error(preds, target) -> Array:
    """Log cosh error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import log_cosh_error
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> log_cosh_error(preds, target)
        Array(0.16850246, dtype=float32)
    """
    preds = jnp.asarray(preds)
    num_outputs = 1 if preds.ndim == 1 else preds.shape[1]
    s, n = _log_cosh_error_update(preds, target, num_outputs)
    return _log_cosh_error_compute(s, n)
