"""Cosine similarity. Parity: reference ``functional/regression/cosine_similarity.py``."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape

Array = jax.Array


def _cosine_similarity_update(preds, target):
    _check_same_shape(preds, target)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if preds.ndim != 2:
        raise ValueError(f"Expected input to cosine similarity to be 2D tensors of shape `[N,D]` where `N` is the number of samples and `D` is the number of dimensions, but got tensor of shape {preds.shape}")
    return preds, target


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    dot = (preds * target).sum(-1)
    denom = jnp.linalg.norm(preds, axis=-1) * jnp.linalg.norm(target, axis=-1)
    sim = dot / denom
    if reduction == "sum":
        return sim.sum()
    if reduction == "mean":
        return sim.mean()
    if reduction in (None, "none"):
        return sim
    raise ValueError(f"Expected reduction to be one of `['sum', 'mean', 'none', None]` but got {reduction}")


def cosine_similarity(preds, target, reduction: Optional[str] = "sum") -> Array:
    """Cosine similarity.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import cosine_similarity
        >>> preds = jnp.asarray([[1.0, 2.0, 3.0], [1.0, 0.0, 1.0]])
        >>> target = jnp.asarray([[1.0, 2.0, 2.0], [0.5, 0.0, 1.0]])
        >>> cosine_similarity(preds, target, reduction='mean')
        Array(0.96432054, dtype=float32)
    """
    preds, target = _cosine_similarity_update(preds, target)
    return _cosine_similarity_compute(preds, target, reduction)
