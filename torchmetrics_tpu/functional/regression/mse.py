"""Mean squared error. Parity: reference ``functional/regression/mse.py``
(_mean_squared_error_update:?, mean_squared_error)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from .utils import _check_data_shape_to_num_outputs

Array = jax.Array


def _mean_squared_error_update(preds, target, num_outputs: int):
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = jnp.reshape(preds, (-1,))
        target = jnp.reshape(target, (-1,))
    _check_data_shape_to_num_outputs(preds, target, num_outputs, allow_1d_reshape=True)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    return sum_squared_error, target.shape[0]


def _mean_squared_error_compute(sum_squared_error: Array, num_obs, squared: bool = True) -> Array:
    mse = sum_squared_error / num_obs
    return mse if squared else jnp.sqrt(mse)


def mean_squared_error(preds, target, squared: bool = True, num_outputs: int = 1) -> Array:
    """MSE (or RMSE with ``squared=False``)."""
    sum_squared_error, num_obs = _mean_squared_error_update(preds, target, num_outputs)
    return _mean_squared_error_compute(sum_squared_error, num_obs, squared)
