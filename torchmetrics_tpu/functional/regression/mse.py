"""Mean squared error. Parity: reference ``functional/regression/mse.py``
(_mean_squared_error_update:?, mean_squared_error)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from .utils import _check_data_shape_to_num_outputs

Array = jax.Array


def _mean_squared_error_update(preds, target, num_outputs: int):
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = jnp.reshape(preds, (-1,))
        target = jnp.reshape(target, (-1,))
    _check_data_shape_to_num_outputs(preds, target, num_outputs, allow_1d_reshape=True)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    return sum_squared_error, target.shape[0]


def _mean_squared_error_compute(sum_squared_error: Array, num_obs, squared: bool = True) -> Array:
    mse = sum_squared_error / num_obs
    return mse if squared else jnp.sqrt(mse)


def mean_squared_error(preds, target, squared: bool = True, num_outputs: int = 1) -> Array:
    """MSE (or RMSE with ``squared=False``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import mean_squared_error
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> mean_squared_error(preds, target)
        Array(0.375, dtype=float32)
    """
    sum_squared_error, num_obs = _mean_squared_error_update(preds, target, num_outputs)
    return _mean_squared_error_compute(sum_squared_error, num_obs, squared)
