"""KL divergence + Jensen-Shannon divergence. Parity: reference
``functional/regression/{kl_divergence,js_divergence}.py``."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from ...utilities.compute import _safe_xlogy

Array = jax.Array


def _kld_check(p, q, log_prob: bool):
    _check_same_shape(p, q)
    if p.ndim != 2 or q.ndim != 2:
        raise ValueError(f"Expected both p and q distribution to be 2D but got {p.ndim} and {q.ndim} respectively")


def _kld_update(p, q, log_prob: bool):
    _kld_check(p, q, log_prob)
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    total = p.shape[0]
    if log_prob:
        measures = jnp.sum(jnp.exp(p) * (p - q), axis=-1)
    else:
        p = p / p.sum(axis=-1, keepdims=True)
        q = q / q.sum(axis=-1, keepdims=True)
        q = jnp.clip(q, min=1e-24)
        measures = _safe_xlogy(p, p / q).sum(axis=-1)
    return measures, total


def _kld_compute(measures: Array, total, reduction: Optional[str] = "mean") -> Array:
    if reduction == "sum":
        return measures.sum()
    if reduction == "mean":
        return measures.sum() / total
    if reduction in (None, "none"):
        return measures
    return measures / total


def kl_divergence(p, q, log_prob: bool = False, reduction: Optional[str] = "mean") -> Array:
    """Kl divergence.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import kl_divergence
        >>> p = jnp.asarray([[0.36, 0.48, 0.16]])
        >>> q = jnp.asarray([[1/3, 1/3, 1/3]])
        >>> kl_divergence(p, q)
        Array(0.08529959, dtype=float32)
    """
    measures, total = _kld_update(p, q, log_prob)
    return _kld_compute(measures, total, reduction)


def _jsd_update(p, q, log_prob: bool):
    _kld_check(p, q, log_prob)
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    total = p.shape[0]
    if log_prob:
        p = jnp.exp(p)
        q = jnp.exp(q)
    else:
        p = p / p.sum(axis=-1, keepdims=True)
        q = q / q.sum(axis=-1, keepdims=True)
    m = 0.5 * (p + q)
    m = jnp.clip(m, min=1e-24)
    measures = 0.5 * _safe_xlogy(p, p / m).sum(axis=-1) + 0.5 * _safe_xlogy(q, q / m).sum(axis=-1)
    return measures, total


def _jsd_compute(measures: Array, total, reduction: Optional[str] = "mean") -> Array:
    return _kld_compute(measures, total, reduction)


def jensen_shannon_divergence(p, q, log_prob: bool = False, reduction: Optional[str] = "mean") -> Array:
    """Jensen shannon divergence.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import jensen_shannon_divergence
        >>> p = jnp.asarray([[0.36, 0.48, 0.16]])
        >>> q = jnp.asarray([[1/3, 1/3, 1/3]])
        >>> jensen_shannon_divergence(p, q)
        Array(0.02245985, dtype=float32)
    """
    measures, total = _jsd_update(p, q, log_prob)
    return _jsd_compute(measures, total, reduction)
