"""Explained variance. Parity: reference
``functional/regression/explained_variance.py`` (_explained_variance_update:26,
_explained_variance_compute:47)."""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape

Array = jax.Array

ALLOWED_MULTIOUTPUT = ("raw_values", "uniform_average", "variance_weighted")


def _explained_variance_update(preds, target):
    _check_same_shape(preds, target)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    num_obs = preds.shape[0]
    sum_error = jnp.sum(target - preds, axis=0)
    diff = target - preds
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    sum_target = jnp.sum(target, axis=0)
    sum_squared_target = jnp.sum(target * target, axis=0)
    return num_obs, sum_error, sum_squared_error, sum_target, sum_squared_target


def _explained_variance_compute(
    num_obs,
    sum_error: Array,
    sum_squared_error: Array,
    sum_target: Array,
    sum_squared_target: Array,
    multioutput: str = "uniform_average",
) -> Array:
    diff_avg = sum_error / num_obs
    numerator = sum_squared_error / num_obs - diff_avg * diff_avg
    target_avg = sum_target / num_obs
    denominator = sum_squared_target / num_obs - target_avg * target_avg

    nonzero_numerator = numerator != 0
    nonzero_denominator = denominator != 0
    valid_score = nonzero_numerator & nonzero_denominator
    ratio = 1.0 - numerator / jnp.where(nonzero_denominator, denominator, 1.0)
    output_scores = jnp.where(valid_score, ratio, jnp.where(nonzero_numerator, 0.0, 1.0))

    if multioutput == "raw_values":
        return output_scores
    if multioutput == "uniform_average":
        return jnp.mean(output_scores)
    if multioutput == "variance_weighted":
        denom_sum = jnp.sum(denominator)
        return jnp.sum(denominator / denom_sum * output_scores)
    raise ValueError(f"Argument `multioutput` must be one of {ALLOWED_MULTIOUTPUT}, but got {multioutput}")


def explained_variance(preds, target, multioutput: str = "uniform_average") -> Array:
    """Explained variance.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import explained_variance
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> explained_variance(preds, target)
        Array(0.95717347, dtype=float32)
    """
    if multioutput not in ALLOWED_MULTIOUTPUT:
        raise ValueError(f"Argument `multioutput` must be one of {ALLOWED_MULTIOUTPUT}, but got {multioutput}")
    num_obs, sum_error, ss_error, sum_target, ss_target = _explained_variance_update(preds, target)
    return _explained_variance_compute(num_obs, sum_error, ss_error, sum_target, ss_target, multioutput)
