"""Mean absolute percentage error family. Parity: reference
``functional/regression/{mape,symmetric_mape,wmape}.py``."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape

Array = jax.Array

_EPS = 1.17e-06


def _mean_absolute_percentage_error_update(preds, target, epsilon: float = _EPS):
    _check_same_shape(preds, target)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target), min=epsilon)
    return jnp.sum(abs_per_error), target.size


def _mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs) -> Array:
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds, target) -> Array:
    """Mean absolute percentage error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import mean_absolute_percentage_error
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> mean_absolute_percentage_error(preds, target)
        Array(0.32738096, dtype=float32)
    """
    s, n = _mean_absolute_percentage_error_update(preds, target)
    return _mean_absolute_percentage_error_compute(s, n)


def _symmetric_mean_absolute_percentage_error_update(preds, target, epsilon: float = _EPS):
    _check_same_shape(preds, target)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    abs_per_error = 2 * jnp.abs(preds - target) / jnp.clip(jnp.abs(target) + jnp.abs(preds), min=epsilon)
    return jnp.sum(abs_per_error), target.size


def _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs) -> Array:
    return sum_abs_per_error / num_obs


def symmetric_mean_absolute_percentage_error(preds, target) -> Array:
    """Symmetric mean absolute percentage error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import symmetric_mean_absolute_percentage_error
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> symmetric_mean_absolute_percentage_error(preds, target)
        Array(0.5787879, dtype=float32)
    """
    s, n = _symmetric_mean_absolute_percentage_error_update(preds, target)
    return _symmetric_mean_absolute_percentage_error_compute(s, n)


def _weighted_mean_absolute_percentage_error_update(preds, target):
    _check_same_shape(preds, target)
    preds = jnp.asarray(preds, jnp.float32).reshape(-1)
    target = jnp.asarray(target, jnp.float32).reshape(-1)
    return jnp.sum(jnp.abs(preds - target)), jnp.sum(jnp.abs(target))


def _weighted_mean_absolute_percentage_error_compute(sum_abs_error: Array, sum_scale: Array, epsilon: float = _EPS) -> Array:
    return sum_abs_error / jnp.clip(sum_scale, min=epsilon)


def weighted_mean_absolute_percentage_error(preds, target) -> Array:
    """Weighted mean absolute percentage error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import weighted_mean_absolute_percentage_error
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> weighted_mean_absolute_percentage_error(preds, target)
        Array(0.16, dtype=float32)
    """
    sum_abs_error, sum_scale = _weighted_mean_absolute_percentage_error_update(preds, target)
    return _weighted_mean_absolute_percentage_error_compute(sum_abs_error, sum_scale)
