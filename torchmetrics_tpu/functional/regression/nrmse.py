"""Normalized root mean squared error. Parity: reference
``functional/regression/nrmse.py`` (_normalized_root_mean_squared_error_update:23)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .mse import _mean_squared_error_update

Array = jax.Array

_ALLOWED_NORM = ("mean", "range", "std", "l2")


def _normalized_root_mean_squared_error_update(preds, target, num_outputs: int, normalization: str = "mean"):
    sum_squared_error, num_obs = _mean_squared_error_update(preds, target, num_outputs)
    target = jnp.asarray(target, jnp.float32)
    target = target.reshape(-1) if num_outputs == 1 else target
    if normalization == "mean":
        denom = jnp.mean(target, axis=0)
    elif normalization == "range":
        denom = jnp.max(target, axis=0) - jnp.min(target, axis=0)
    elif normalization == "std":
        denom = jnp.std(target, axis=0)
    elif normalization == "l2":
        denom = jnp.linalg.norm(target, axis=0)
    else:
        raise ValueError(f"Argument `normalization` should be either 'mean', 'range', 'std' or 'l2', but got {normalization}")
    return sum_squared_error, num_obs, denom


def _normalized_root_mean_squared_error_compute(sum_squared_error: Array, num_obs, denom: Array) -> Array:
    rmse = jnp.sqrt(sum_squared_error / num_obs)
    return rmse / denom


def normalized_root_mean_squared_error(preds, target, normalization: str = "mean", num_outputs: int = 1) -> Array:
    """Normalized root mean squared error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import normalized_root_mean_squared_error
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> normalized_root_mean_squared_error(preds, target)
        Array(0.21299912, dtype=float32)
    """
    if normalization not in _ALLOWED_NORM:
        raise ValueError(f"Argument `normalization` should be either 'mean', 'range', 'std' or 'l2', but got {normalization}")
    sum_squared_error, num_obs, denom = _normalized_root_mean_squared_error_update(preds, target, num_outputs, normalization)
    return _normalized_root_mean_squared_error_compute(sum_squared_error, num_obs, denom)
