"""Kendall rank correlation (tau-a/b/c, optional significance test). Parity: reference
``functional/regression/kendall.py`` (_get_metric_metadata:112, _calculate_tau:153,
_calculate_p_value:197).

TPU-native formulation: the reference counts concordant/discordant pairs with a Python
loop over rows (O(n) kernel launches). Here the pair statistics come from one vectorized
(n, n) sign-comparison — a single fused XLA kernel — and tie-group statistics come from
sort + run-length ``segment_sum`` with static shapes (no ``unique``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from .utils import _check_data_shape_to_num_outputs

Array = jax.Array

_ALLOWED_VARIANTS = ("a", "b", "c")
_ALLOWED_ALTERNATIVES = ("two-sided", "less", "greater")


def _tie_stats(x: Array) -> Tuple[Array, Array, Array, Array]:
    """Per-column tie-group statistics: (Σt(t-1)/2, Σt(t-1)(t-2), Σt(t-1)(2t+5),
    number of distinct values). Static-shape via run-length segments of sorted x."""
    n = x.shape[0]
    xs = jnp.sort(x)
    change = jnp.concatenate([jnp.zeros((1,), jnp.int32), (xs[1:] != xs[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(change)
    t = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), seg, num_segments=n)
    ties = jnp.sum(t * (t - 1) / 2)
    ties_p1 = jnp.sum(t * (t - 1) * (t - 2))
    ties_p2 = jnp.sum(t * (t - 1) * (2 * t + 5))
    n_unique = jnp.sum(t > 0)
    return ties, ties_p1, ties_p2, n_unique


# Cap the materialized pairwise block at ~4M elements: memory stays O(chunk·n) instead
# of O(n²) (the full (n,n) matrix OOMs past ~100k accumulated samples).
_PAIR_BLOCK_ELEMS = 1 << 22


def _pair_counts(x: Array, y: Array) -> Tuple[Array, Array]:
    """Concordant/discordant pair counts via row-blocked (chunk, n) sign comparisons.

    Per-block counts are integer-exact (≤ 2^22·n block, counted in f32 after an exact
    int sum per block); totals accumulate in f32 — for n where pair counts exceed 2^24
    the relative error is ≤2^-24, far below tau's statistical noise.
    """
    n = x.shape[0]
    chunk = int(min(n, max(64, _PAIR_BLOCK_ELEMS // max(n, 1))))
    pad = (-n) % chunk
    xp = jnp.pad(x, (0, pad))
    yp = jnp.pad(y, (0, pad))
    total = xp.shape[0]
    rows = jnp.arange(chunk)
    cols = jnp.arange(total)

    def body(i, acc):
        start = i * chunk
        xi = jax.lax.dynamic_slice(xp, (start,), (chunk,))
        yi = jax.lax.dynamic_slice(yp, (start,), (chunk,))
        gidx = start + rows
        mask = (cols[None, :] > gidx[:, None]) & (cols[None, :] < n) & (gidx[:, None] < n)
        sx = jnp.sign(xi[:, None] - xp[None, :])
        sy = jnp.sign(yi[:, None] - yp[None, :])
        prod = sx * sy
        con = jnp.sum((prod > 0) & mask, dtype=jnp.int32).astype(jnp.float32)
        dis = jnp.sum((prod < 0) & mask, dtype=jnp.int32).astype(jnp.float32)
        return acc[0] + con, acc[1] + dis

    concordant, discordant = jax.lax.fori_loop(
        0, total // chunk, body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
    )
    return concordant, discordant


def _kendall_tau_1d(
    preds: Array, target: Array, variant: str, t_test: bool, alternative: Optional[str]
) -> Tuple[Array, Optional[Array]]:
    n = jnp.asarray(preds.shape[0], jnp.float32)
    con, dis = _pair_counts(preds, target)
    con_min_dis = (con - dis).astype(jnp.float32)
    x_ties, x_p1, x_p2, x_uniq = _tie_stats(preds)
    y_ties, y_p1, y_p2, y_uniq = _tie_stats(target)

    if variant == "a":
        tau = con_min_dis / (con + dis)
    elif variant == "b":
        total = n * (n - 1) / 2
        tau = con_min_dis / jnp.sqrt((total - x_ties) * (total - y_ties))
    else:
        min_classes = jnp.minimum(x_uniq, y_uniq).astype(jnp.float32)
        tau = 2 * con_min_dis / ((min_classes - 1) / min_classes * n * n)

    p_value = None
    if t_test:
        base = n * (n - 1) * (2 * n + 5)
        if variant == "a":
            t_value = 3 * con_min_dis / jnp.sqrt(base / 2)
        else:
            m = n * (n - 1)
            denom = (base - x_p2 - y_p2) / 18
            denom = denom + (2 * x_ties * y_ties) / m
            denom = denom + (x_p1 * y_p1) / (9 * m * (n - 2))
            t_value = con_min_dis / jnp.sqrt(denom)
        cdf = jax.scipy.stats.norm.cdf
        if alternative == "two-sided":
            p_value = 2 * (1 - cdf(jnp.abs(t_value)))
        elif alternative == "greater":
            p_value = 1 - cdf(t_value)
        else:
            p_value = cdf(t_value)
    return jnp.clip(tau, -1.0, 1.0), p_value


def _kendall_corrcoef_compute(
    preds: Array, target: Array, variant: str = "b", t_test: bool = False, alternative: Optional[str] = "two-sided"
):
    if preds.ndim == 1:
        return _kendall_tau_1d(preds, target, variant, t_test, alternative)
    taus, ps = [], []
    for i in range(preds.shape[-1]):
        tau, p = _kendall_tau_1d(preds[:, i], target[:, i], variant, t_test, alternative)
        taus.append(tau)
        ps.append(p)
    tau = jnp.stack(taus)
    p_value = jnp.stack(ps) if t_test else None
    return tau, p_value


def kendall_rank_corrcoef(
    preds,
    target,
    variant: str = "b",
    t_test: bool = False,
    alternative: Optional[str] = "two-sided",
):
    """Kendall's tau; returns ``tau`` or ``(tau, p_value)`` when ``t_test``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import kendall_rank_corrcoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> kendall_rank_corrcoef(preds, target)
        Array(1., dtype=float32)
    """
    if variant not in _ALLOWED_VARIANTS:
        raise ValueError(f"Argument `variant` is expected to be one of {_ALLOWED_VARIANTS}, but got {variant!r}")
    if not isinstance(t_test, bool):
        raise ValueError(f"Argument `t_test` is expected to be of a type `bool`, but got {t_test}.")
    if t_test and alternative not in _ALLOWED_ALTERNATIVES:
        raise ValueError(f"Argument `alternative` is expected to be one of {_ALLOWED_ALTERNATIVES}, but got {alternative!r}")
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    tau, p_value = _kendall_corrcoef_compute(preds, target, variant, t_test, alternative)
    if p_value is not None:
        return tau, p_value
    return tau
