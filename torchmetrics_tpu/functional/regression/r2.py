"""R2 score + relative squared error. Parity: reference
``functional/regression/{r2,rse}.py`` (_r2_score_update:23, _r2_score_compute:47,
_relative_squared_error_compute)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from ...utilities.prints import rank_zero_warn

Array = jax.Array


def _r2_score_update(preds, target):
    _check_same_shape(preds, target)
    if preds.ndim > 2:
        raise ValueError(f"Expected both prediction and target to be 1D or 2D tensors, but received tensors with dimension {preds.shape}")
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    sum_obs = jnp.sum(target, axis=0)
    sum_squared_obs = jnp.sum(target * target, axis=0)
    residual = target - preds
    rss = jnp.sum(residual * residual, axis=0)
    return sum_squared_obs, sum_obs, rss, target.shape[0]


def _r2_score_compute(
    sum_squared_obs: Array,
    sum_obs: Array,
    rss: Array,
    num_obs,
    adjusted: int = 0,
    multioutput: str = "uniform_average",
) -> Array:
    mean_obs = sum_obs / num_obs
    tss = sum_squared_obs - sum_obs * mean_obs
    cond = tss != 0
    raw_scores = 1 - rss / jnp.where(cond, tss, 1.0)
    raw_scores = jnp.where(cond, raw_scores, jnp.zeros_like(raw_scores))

    if multioutput == "raw_values":
        r2 = raw_scores
    elif multioutput == "uniform_average":
        r2 = jnp.mean(raw_scores)
    elif multioutput == "variance_weighted":
        tss_sum = jnp.sum(tss)
        r2 = jnp.sum(tss / tss_sum * raw_scores)
    else:
        raise ValueError(
            f"Argument `multioutput` must be either `raw_values`, `uniform_average` or `variance_weighted`. Received {multioutput}."
        )

    if adjusted < 0 or not isinstance(adjusted, int):
        raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
    if adjusted != 0:
        import numpy as np

        n = int(num_obs) if not hasattr(num_obs, "shape") or num_obs.shape == () else int(np.asarray(num_obs))
        if n - adjusted - 1 <= 0:
            rank_zero_warn(
                "More independent regressions than data points in adjusted r2 score. Falls back to standard r2 score.",
                UserWarning,
            )
        else:
            return 1 - (1 - r2) * (n - 1) / (n - adjusted - 1)
    return r2


def r2_score(preds, target, adjusted: int = 0, multioutput: str = "uniform_average") -> Array:
    """2.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import r2_score
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> r2_score(preds, target)
        Array(0.94860816, dtype=float32)
    """
    sum_squared_obs, sum_obs, rss, num_obs = _r2_score_update(preds, target)
    if num_obs < 2:
        raise ValueError("Needs at least two samples to calculate r2 score.")
    return _r2_score_compute(sum_squared_obs, sum_obs, rss, num_obs, adjusted, multioutput)


def _relative_squared_error_compute(sum_squared_obs: Array, sum_obs: Array, rss: Array, num_obs, squared: bool = True) -> Array:
    epsilon = jnp.finfo(jnp.float32).eps
    tss = jnp.sum(sum_squared_obs - sum_obs * (sum_obs / num_obs))
    rse = jnp.sum(rss) / jnp.clip(tss, min=epsilon)
    return rse if squared else jnp.sqrt(rse)


def relative_squared_error(preds, target, squared: bool = True) -> Array:
    """Relative squared error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import relative_squared_error
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> relative_squared_error(preds, target)
        Array(0.05139186, dtype=float32)
    """
    sum_squared_obs, sum_obs, rss, num_obs = _r2_score_update(preds, target)
    return _relative_squared_error_compute(sum_squared_obs, sum_obs, rss, num_obs, squared)
