"""Spearman rank correlation. Parity: reference
``functional/regression/spearman.py`` (_rank_data, _spearman_corrcoef_compute)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from .utils import _check_data_shape_to_num_outputs, _rank_data

Array = jax.Array


def _spearman_corrcoef_update(preds, target, num_outputs: int):
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if not (jnp.issubdtype(preds.dtype, jnp.floating) and jnp.issubdtype(target.dtype, jnp.floating)):
        raise TypeError(
            "Expected `preds` and `target` both to be floating point tensors, but got {preds.dtype} and {target.dtype}"
        )
    _check_same_shape(preds, target)
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    return jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1e-6) -> Array:
    if preds.ndim == 1:
        preds = _rank_data(preds)
        target = _rank_data(target)
    else:
        preds = jnp.stack([_rank_data(preds[:, i]) for i in range(preds.shape[-1])], axis=-1)
        target = jnp.stack([_rank_data(target[:, i]) for i in range(target.shape[-1])], axis=-1)

    preds_diff = preds - preds.mean(0)
    target_diff = target - target.mean(0)
    cov = (preds_diff * target_diff).mean(0)
    preds_std = jnp.sqrt((preds_diff * preds_diff).mean(0))
    target_std = jnp.sqrt((target_diff * target_diff).mean(0))
    corrcoef = cov / (preds_std * target_std + eps)
    return jnp.clip(corrcoef, -1.0, 1.0)


def spearman_corrcoef(preds, target) -> Array:
    """Spearman corrcoef.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import spearman_corrcoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> spearman_corrcoef(preds, target)
        Array(0.9999992, dtype=float32)
    """
    preds = jnp.asarray(preds)
    num_outputs = 1 if preds.ndim == 1 else preds.shape[-1]
    preds, target = _spearman_corrcoef_update(preds, target, num_outputs)
    return _spearman_corrcoef_compute(preds, target)
