"""Concordance correlation coefficient. Parity: reference
``functional/regression/concordance.py`` (_concordance_corrcoef_compute:20)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .pearson import _pearson_corrcoef_compute, _pearson_corrcoef_update

Array = jax.Array


def _concordance_corrcoef_compute(
    max_abs_dev_x: Array,
    max_abs_dev_y: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    num_total: Array,
) -> Array:
    pearson = _pearson_corrcoef_compute(max_abs_dev_x, max_abs_dev_y, var_x, var_y, corr_xy, num_total)
    var_x = var_x / (num_total - 1)
    var_y = var_y / (num_total - 1)
    return 2.0 * pearson * jnp.sqrt(var_x) * jnp.sqrt(var_y) / (var_x + var_y + (mean_x - mean_y) ** 2)


def concordance_corrcoef(preds, target) -> Array:
    """One-shot concordance correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import concordance_corrcoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> concordance_corrcoef(preds, target)
        Array(0.9777347, dtype=float32)
    """
    preds = jnp.asarray(preds)
    num_outputs = 1 if preds.ndim == 1 else preds.shape[-1]
    d = (num_outputs,) if num_outputs > 1 else ()
    zeros = jnp.zeros(d, jnp.float32)
    mean_x, mean_y, dev_x, dev_y, var_x, var_y, corr_xy, n = _pearson_corrcoef_update(
        preds, target, zeros, zeros, zeros, zeros, zeros, zeros, zeros, jnp.zeros((), jnp.float32), num_outputs
    )
    return _concordance_corrcoef_compute(dev_x, dev_y, mean_x, mean_y, var_x, var_y, corr_xy, n)
