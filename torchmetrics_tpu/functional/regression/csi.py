"""Critical success index. Parity: reference ``functional/regression/csi.py``
(_critical_success_index_update:23, _critical_success_index_compute:61)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from ...utilities.compute import _safe_divide

Array = jax.Array


def _critical_success_index_update(preds, target, threshold: float, keep_sequence_dim: Optional[int] = None):
    _check_same_shape(preds, target)
    if keep_sequence_dim is None:
        axis = None
    elif not 0 <= keep_sequence_dim < preds.ndim:
        raise ValueError(f"Expected keep_sequence_dim to be in range [0, {preds.ndim}) but got {keep_sequence_dim}")
    else:
        axis = tuple(i for i in range(preds.ndim) if i != keep_sequence_dim)
    preds_bin = jnp.asarray(preds) >= threshold
    target_bin = jnp.asarray(target) >= threshold
    hits = jnp.sum(preds_bin & target_bin, axis=axis)
    misses = jnp.sum(~preds_bin & target_bin, axis=axis)
    false_alarms = jnp.sum(preds_bin & ~target_bin, axis=axis)
    return hits, misses, false_alarms


def _critical_success_index_compute(hits: Array, misses: Array, false_alarms: Array) -> Array:
    return _safe_divide(hits, hits + misses + false_alarms)


def critical_success_index(preds, target, threshold: float, keep_sequence_dim: Optional[int] = None) -> Array:
    """Critical success index.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import critical_success_index
        >>> preds = jnp.asarray([0.2, 0.7, 0.9, 0.4])
        >>> target = jnp.asarray([0.1, 0.8, 0.6, 0.7])
        >>> critical_success_index(preds, target, 0.5)
        Array(0.6666667, dtype=float32)
    """
    hits, misses, false_alarms = _critical_success_index_update(preds, target, threshold, keep_sequence_dim)
    return _critical_success_index_compute(hits, misses, false_alarms)
