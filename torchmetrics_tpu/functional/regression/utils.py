"""Shared regression helpers. Parity: reference ``functional/regression/utils.py``
(_check_data_shape_to_num_outputs) and ``spearman.py`` (_rank_data).

``_rank_data`` is the TPU-native tie-averaged ranking: instead of host loops over
``unique`` (dynamic shapes), it sorts once and averages tied ranks with a static-shape
``segment_sum`` keyed on run-change flags — O(n log n), fully jittable."""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def _check_data_shape_to_num_outputs(preds, target, num_outputs: int, allow_1d_reshape: bool = False) -> None:
    """Check predictions/target shape against declared ``num_outputs``."""
    if preds.ndim > 2:
        raise ValueError(f"Expected both predictions and target to be either 1- or 2-dimensional tensors, but got {target.ndim} and {preds.ndim}.")
    cond1 = False
    if not allow_1d_reshape:
        cond1 = num_outputs == 1 and preds.ndim != 1
    cond2 = num_outputs > 1 and (preds.ndim < 2 or preds.shape[1] != num_outputs)
    if cond1 or cond2:
        raise ValueError(f"Expected argument `num_outputs` to match the second dimension of input, but got {num_outputs} and {preds.shape}")


def _rank_data(x: Array) -> Array:
    """1-based ranks with ties averaged (scipy ``rankdata`` semantics), jittable.

    Sort; segment tied runs via cumsum of change flags; per-segment mean position via
    ``segment_sum`` (static ``num_segments=n``); scatter back through the sort order.
    """
    x = jnp.asarray(x)
    n = x.shape[0]
    order = jnp.argsort(x)
    xs = x[order]
    change = jnp.concatenate([jnp.zeros((1,), jnp.int32), (xs[1:] != xs[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(change)
    pos = jnp.arange(1, n + 1, dtype=jnp.float32)
    seg_sum = jax.ops.segment_sum(pos, seg, num_segments=n)
    seg_cnt = jax.ops.segment_sum(jnp.ones_like(pos), seg, num_segments=n)
    mean_rank = jnp.where(seg_cnt > 0, seg_sum / jnp.maximum(seg_cnt, 1), 0.0)
    ranks_sorted = mean_rank[seg]
    return jnp.zeros_like(ranks_sorted).at[order].set(ranks_sorted)
