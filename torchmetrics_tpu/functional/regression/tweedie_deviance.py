"""Tweedie deviance score. Parity: reference
``functional/regression/tweedie_deviance.py`` (_tweedie_deviance_score_update:22)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape, _is_traced
from ...utilities.compute import _safe_xlogy

Array = jax.Array


def _tweedie_deviance_score_update(preds, targets, power: float = 0.0):
    _check_same_shape(preds, targets)
    preds = jnp.asarray(preds, jnp.float32)
    targets = jnp.asarray(targets, jnp.float32)

    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")

    # domain checks run host-side only when values are concrete (skipped under jit)
    if not _is_traced(preds, targets):
        import numpy as np

        p, t = np.asarray(preds), np.asarray(targets)
        if power == 1 and ((p <= 0).any() or (t < 0).any()):
            raise ValueError(f"For power={power}, 'preds' has to be strictly positive and 'targets' cannot be negative.")
        if power == 2 and ((p <= 0).any() or (t <= 0).any()):
            raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")

    if power == 0:
        deviance_score = jnp.square(targets - preds)
    elif power == 1:
        deviance_score = 2 * (_safe_xlogy(targets, targets / preds) + preds - targets)
    elif power == 2:
        deviance_score = 2 * (jnp.log(preds / targets) + (targets / preds) - 1)
    else:
        term_1 = jnp.power(jnp.clip(targets, min=0), 2 - power) / ((1 - power) * (2 - power))
        term_2 = targets * jnp.power(preds, 1 - power) / (1 - power)
        term_3 = jnp.power(preds, 2 - power) / (2 - power)
        deviance_score = 2 * (term_1 - term_2 + term_3)

    return jnp.sum(deviance_score), jnp.asarray(deviance_score.size, jnp.float32)


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    return sum_deviance_score / num_observations


def tweedie_deviance_score(preds, targets, power: float = 0.0) -> Array:
    """Tweedie deviance score.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import tweedie_deviance_score
        >>> preds = jnp.asarray([2.5, 0.5, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> tweedie_deviance_score(preds, target, power=1.5)
        Array(0.0262022, dtype=float32)
    """
    s, n = _tweedie_deviance_score_update(preds, targets, power)
    return _tweedie_deviance_score_compute(s, n)
