"""Minkowski distance. Parity: reference ``functional/regression/minkowski.py``."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from ...utilities.exceptions import TorchMetricsUserError

Array = jax.Array


def _minkowski_distance_update(preds, targets, p: float) -> Array:
    _check_same_shape(preds, targets)
    if not (isinstance(p, (float, int)) and p >= 1):
        raise TorchMetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {p}")
    preds = jnp.asarray(preds, jnp.float32)
    targets = jnp.asarray(targets, jnp.float32)
    return jnp.sum(jnp.power(jnp.abs(preds - targets), p))


def _minkowski_distance_compute(distance: Array, p: float) -> Array:
    return jnp.power(distance, 1.0 / p)


def minkowski_distance(preds, targets, p: float) -> Array:
    """Minkowski distance.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import minkowski_distance
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> minkowski_distance(preds, target, p=3)
        Array(1.0772173, dtype=float32)
    """
    distance = _minkowski_distance_update(preds, targets, p)
    return _minkowski_distance_compute(distance, p)
