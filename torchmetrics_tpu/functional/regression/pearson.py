"""Pearson correlation via parallel running moments. Parity: reference
``functional/regression/pearson.py`` (_pearson_corrcoef_update:24,
_pearson_corrcoef_compute:91) and ``regression/pearson.py`` (_final_aggregation).

TPU notes: the per-batch moments (mean/var/cov/n) combine with the exact Chan et al.
parallel formula — associative and commutative, so the same ``_merge_moments`` serves
batch accumulation, commless ``merge_state`` AND cross-device reduction (fold of
all-gathered per-device moments). No in-place mutation anywhere."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from ...utilities.prints import rank_zero_warn
from .utils import _check_data_shape_to_num_outputs

Array = jax.Array


def _batch_moments(preds: Array, target: Array) -> Tuple[Array, ...]:
    """Per-batch sufficient statistics (mean_x, mean_y, var_x, var_y, corr_xy, n) where
    var/corr are *unnormalized* centered sums, as in the reference."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    n = jnp.asarray(preds.shape[0], jnp.float32)
    mean_x = preds.mean(0)
    mean_y = target.mean(0)
    px = preds - mean_x
    ty = target - mean_y
    var_x = (px * px).sum(0)
    var_y = (ty * ty).sum(0)
    corr_xy = (px * ty).sum(0)
    max_abs_dev_x = jnp.max(jnp.abs(px), axis=0)
    max_abs_dev_y = jnp.max(jnp.abs(ty), axis=0)
    return mean_x, mean_y, max_abs_dev_x, max_abs_dev_y, var_x, var_y, corr_xy, n


def _merge_moments(a: Tuple[Array, ...], b: Tuple[Array, ...]) -> Tuple[Array, ...]:
    """Exact parallel combination of two moment sets (Chan et al.)."""
    mx_a, my_a, dev_xa, dev_ya, vx_a, vy_a, cxy_a, n_a = a
    mx_b, my_b, dev_xb, dev_yb, vx_b, vy_b, cxy_b, n_b = b
    n = n_a + n_b
    safe_n = jnp.where(n == 0, 1.0, n)
    delta_x = mx_b - mx_a
    delta_y = my_b - my_a
    mean_x = mx_a + delta_x * n_b / safe_n
    mean_y = my_a + delta_y * n_b / safe_n
    correction = n_a * n_b / safe_n
    var_x = vx_a + vx_b + delta_x * delta_x * correction
    var_y = vy_a + vy_b + delta_y * delta_y * correction
    corr_xy = cxy_a + cxy_b + delta_x * delta_y * correction
    # max-abs-deviation is only an instability detector; bound it by shifting each
    # side's max by its mean shift (upper bound, cheap and shape-static)
    dev_x = jnp.maximum(dev_xa + jnp.abs(mx_a - mean_x), dev_xb + jnp.abs(mx_b - mean_x))
    dev_y = jnp.maximum(dev_ya + jnp.abs(my_a - mean_y), dev_yb + jnp.abs(my_b - mean_y))
    return mean_x, mean_y, dev_x, dev_y, var_x, var_y, corr_xy, n


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    max_abs_dev_x: Array,
    max_abs_dev_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    num_prior: Array,
    num_outputs: int,
) -> Tuple[Array, ...]:
    """Fold one batch into the running moments (reference pearson.py:24-88)."""
    _check_same_shape(preds, target)
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    batch = _batch_moments(preds, target)
    return _merge_moments((mean_x, mean_y, max_abs_dev_x, max_abs_dev_y, var_x, var_y, corr_xy, num_prior), batch)


def _pearson_corrcoef_compute(
    max_abs_dev_x: Array,
    max_abs_dev_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    num_total: Array,
) -> Array:
    """Correlation from final moments (reference pearson.py:91-146)."""
    var_x = var_x / (num_total - 1)
    var_y = var_y / (num_total - 1)
    corr_xy = corr_xy / (num_total - 1)
    import numpy as np

    if not isinstance(var_x, jax.core.Tracer):
        vx, vy = np.asarray(var_x), np.asarray(var_y)
        if (vx < 1e-6).any() or (vy < 1e-6).any():
            rank_zero_warn(
                "The variance of predictions or target is close to zero. This can cause instability in Pearson correlation"
                "coefficient, leading to wrong results. Consider re-scaling the input if possible or computing using a"
                f"larger dtype (currently using {var_x.dtype}).",
                UserWarning,
            )
    corrcoef = jnp.clip(corr_xy / jnp.sqrt(var_x * var_y), -1.0, 1.0)
    return corrcoef.squeeze()


def _final_aggregation(
    means_x: Array,
    means_y: Array,
    max_abs_dev_x: Array,
    max_abs_dev_y: Array,
    vars_x: Array,
    vars_y: Array,
    corrs_xy: Array,
    nbs: Array,
) -> Tuple[Array, ...]:
    """Fold per-device moment stacks ``(world, num_outputs)`` into one moment set
    (reference regression/pearson.py:_final_aggregation) — a lax.scan-free fori fold
    would also work; world size is tiny so a Python fold is fine at trace time."""
    acc = (means_x[0], means_y[0], max_abs_dev_x[0], max_abs_dev_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0])
    for i in range(1, means_x.shape[0]):
        acc = _merge_moments(acc, (means_x[i], means_y[i], max_abs_dev_x[i], max_abs_dev_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]))
    return acc


def pearson_corrcoef(preds, target) -> Array:
    """One-shot Pearson correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import pearson_corrcoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> pearson_corrcoef(preds, target)
        Array(0.98486954, dtype=float32)
    """
    preds = jnp.asarray(preds)
    num_outputs = 1 if preds.ndim == 1 else preds.shape[-1]
    d = (num_outputs,) if num_outputs > 1 else ()
    zeros = jnp.zeros(d, jnp.float32)
    out = _pearson_corrcoef_update(
        preds, target, zeros, zeros, zeros, zeros, zeros, zeros, zeros, jnp.zeros((), jnp.float32), num_outputs
    )
    _, _, dev_x, dev_y, var_x, var_y, corr_xy, n = out
    return _pearson_corrcoef_compute(dev_x, dev_y, var_x, var_y, corr_xy, n)
