"""Functional regression metrics (stateless). Parity: reference
``functional/regression/__init__.py``."""

from .concordance import concordance_corrcoef
from .cosine_similarity import cosine_similarity
from .crps import continuous_ranked_probability_score
from .csi import critical_success_index
from .explained_variance import explained_variance
from .kendall import kendall_rank_corrcoef
from .kl_divergence import jensen_shannon_divergence, kl_divergence
from .log_mse import log_cosh_error, mean_squared_log_error
from .mae import mean_absolute_error
from .mape import (
    mean_absolute_percentage_error,
    symmetric_mean_absolute_percentage_error,
    weighted_mean_absolute_percentage_error,
)
from .minkowski import minkowski_distance
from .mse import mean_squared_error
from .nrmse import normalized_root_mean_squared_error
from .pearson import pearson_corrcoef
from .r2 import r2_score, relative_squared_error
from .spearman import spearman_corrcoef
from .tweedie_deviance import tweedie_deviance_score

__all__ = [
    "concordance_corrcoef",
    "cosine_similarity",
    "continuous_ranked_probability_score",
    "critical_success_index",
    "explained_variance",
    "jensen_shannon_divergence",
    "kendall_rank_corrcoef",
    "kl_divergence",
    "log_cosh_error",
    "mean_absolute_error",
    "mean_absolute_percentage_error",
    "mean_squared_error",
    "mean_squared_log_error",
    "minkowski_distance",
    "normalized_root_mean_squared_error",
    "pearson_corrcoef",
    "r2_score",
    "relative_squared_error",
    "spearman_corrcoef",
    "symmetric_mean_absolute_percentage_error",
    "tweedie_deviance_score",
    "weighted_mean_absolute_percentage_error",
]
