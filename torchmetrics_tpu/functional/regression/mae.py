"""Mean absolute error. Parity: reference ``functional/regression/mae.py``."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from .utils import _check_data_shape_to_num_outputs

Array = jax.Array


def _mean_absolute_error_update(preds, target, num_outputs: int = 1):
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = jnp.reshape(preds, (-1,))
        target = jnp.reshape(target, (-1,))
    _check_data_shape_to_num_outputs(preds, target, num_outputs, allow_1d_reshape=True)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    sum_abs_error = jnp.sum(jnp.abs(preds - target), axis=0)
    return sum_abs_error, target.shape[0]


def _mean_absolute_error_compute(sum_abs_error: Array, num_obs) -> Array:
    return sum_abs_error / num_obs


def mean_absolute_error(preds, target, num_outputs: int = 1) -> Array:
    """Mean absolute error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import mean_absolute_error
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> mean_absolute_error(preds, target)
        Array(0.5, dtype=float32)
    """
    sum_abs_error, num_obs = _mean_absolute_error_update(preds, target, num_outputs)
    return _mean_absolute_error_compute(sum_abs_error, num_obs)
