"""Intersection over Union — functional (reference ``functional/detection/iou.py``).

The reference wraps torchvision's ``box_iou`` and mutates the matrix in place
(``functional/detection/iou.py:24-49``); here the pairwise kernel is an in-tree jnp
kernel (``_box_ops.box_iou_matrix``) and thresholding is a ``jnp.where`` so the whole
path stays jittable.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ._box_ops import box_iou_matrix


def _family_update(
    preds: jnp.ndarray,
    target: jnp.ndarray,
    iou_threshold: Optional[float],
    replacement_val: float,
    matrix_fn: Callable,
) -> jnp.ndarray:
    """Shared update for the IoU variant family: validate, handle empty sets the way
    the reference does (square zero matrices), compute the pairwise matrix, apply the
    threshold floor."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if preds.ndim != 2 or preds.shape[-1] != 4:
        raise ValueError(f"Expected preds to be of shape (N, 4) but got {preds.shape}")
    if target.ndim != 2 or target.shape[-1] != 4:
        raise ValueError(f"Expected target to be of shape (N, 4) but got {target.shape}")
    if preds.size == 0:  # no predicted boxes (reference returns a gt-square zero matrix)
        return jnp.zeros((target.shape[0], target.shape[0]), jnp.float32)
    if target.size == 0:  # no true boxes
        return jnp.zeros((preds.shape[0], preds.shape[0]), jnp.float32)
    iou = matrix_fn(preds, target)
    if iou_threshold is not None:
        iou = jnp.where(iou < iou_threshold, replacement_val, iou)
    return iou


def _family_compute(iou: jnp.ndarray, aggregate: bool = True) -> jnp.ndarray:
    if not aggregate:
        return iou
    if iou.size == 0:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.diagonal(iou).mean()


def _iou_update(preds, target, iou_threshold: Optional[float], replacement_val: float = 0) -> jnp.ndarray:
    return _family_update(preds, target, iou_threshold, replacement_val, box_iou_matrix)


def _iou_compute(iou: jnp.ndarray, aggregate: bool = True) -> jnp.ndarray:
    return _family_compute(iou, aggregate)


def intersection_over_union(
    preds: jnp.ndarray,
    target: jnp.ndarray,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> jnp.ndarray:
    """Compute IoU between two sets of xyxy boxes (reference
    ``functional/detection/iou.py:52``). ``aggregate=True`` returns the mean of the
    matrix diagonal; otherwise the full ``(N, M)`` matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import intersection_over_union
        >>> preds = jnp.asarray([[296.55, 93.96, 314.97, 152.79], [328.94, 97.05, 342.49, 122.98]])
        >>> target = jnp.asarray([[300.00, 100.00, 315.00, 150.00], [330.00, 100.00, 350.00, 125.00]])
        >>> intersection_over_union(preds, target)
        Array(0.5991845, dtype=float32)
    """
    iou = _iou_update(preds, target, iou_threshold, replacement_val)
    return _iou_compute(iou, aggregate)
