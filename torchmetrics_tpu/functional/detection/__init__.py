"""Detection tower — stateless kernels (reference ``src/torchmetrics/functional/detection/``)."""

from .ciou import complete_intersection_over_union
from .diou import distance_intersection_over_union
from .giou import generalized_intersection_over_union
from .iou import intersection_over_union
from .map import mean_average_precision
from .panoptic_qualities import modified_panoptic_quality, panoptic_quality

__all__ = [
    "complete_intersection_over_union",
    "distance_intersection_over_union",
    "generalized_intersection_over_union",
    "intersection_over_union",
    "mean_average_precision",
    "modified_panoptic_quality",
    "panoptic_quality",
]
