"""One-shot functional COCO mAP (reference ``functional/detection/map.py:39``)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp


def mean_average_precision(
    preds: List[Dict[str, Any]],
    target: List[Dict[str, Any]],
    box_format: str = "xyxy",
    iou_type: Union[str, Tuple[str, ...]] = "bbox",
    iou_thresholds: Optional[List[float]] = None,
    rec_thresholds: Optional[List[float]] = None,
    max_detection_thresholds: Optional[List[int]] = None,
    class_metrics: bool = False,
    extended_summary: bool = False,
    average: str = "macro",
    backend: str = "pycocotools",
    warn_on_many_detections: bool = True,
) -> Dict[str, jnp.ndarray]:
    """COCO mAP/mAR over one batch of detections — the stateful metric run once."""
    from ...detection.mean_ap import MeanAveragePrecision

    metric = MeanAveragePrecision(
        box_format=box_format,
        iou_type=iou_type,
        iou_thresholds=iou_thresholds,
        rec_thresholds=rec_thresholds,
        max_detection_thresholds=max_detection_thresholds,
        class_metrics=class_metrics,
        extended_summary=extended_summary,
        average=average,
        backend=backend,
    )
    metric.warn_on_many_detections = warn_on_many_detections
    metric.update(preds, target)
    return metric.compute()
