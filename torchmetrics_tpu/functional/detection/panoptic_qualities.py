"""Panoptic Quality kernels (reference ``functional/detection/_panoptic_quality_common.py``).

The reference accumulates per-segment statistics through Python dicts and sets
(``_get_color_areas``/``_panoptic_quality_update_sample``, one dict lookup per
segment pair). Here every per-sample pass is vectorized: segment "colors"
``(category_id, instance_id)`` are encoded into int64 codes, areas and pairwise
intersections come from ``np.unique`` with counts, and the match/FP/FN filters are
boolean masks over the unique-pair table. The resulting sufficient statistics
(per-category iou_sum/tp/fp/fn) are static-shape sum states — the cross-device sync
is four psums.
"""

from __future__ import annotations

from typing import Collection, Dict, Optional, Set, Tuple

import jax.numpy as jnp
import numpy as np

from ...utilities.prints import rank_zero_warn

_SHIFT = np.int64(1) << np.int64(32)


def _parse_categories(things: Collection[int], stuffs: Collection[int]) -> Tuple[Set[int], Set[int]]:
    things_parsed = set(things)
    if len(things_parsed) < len(things):
        rank_zero_warn("The provided `things` categories contained duplicates, which have been removed.", UserWarning)
    stuffs_parsed = set(stuffs)
    if len(stuffs_parsed) < len(stuffs):
        rank_zero_warn("The provided `stuffs` categories contained duplicates, which have been removed.", UserWarning)
    if not all(isinstance(val, int) and not isinstance(val, bool) for val in things_parsed):
        raise TypeError(f"Expected argument `things` to contain `int` categories, but got {things}")
    if not all(isinstance(val, int) and not isinstance(val, bool) for val in stuffs_parsed):
        raise TypeError(f"Expected argument `stuffs` to contain `int` categories, but got {stuffs}")
    if things_parsed & stuffs_parsed:
        raise ValueError(
            f"Expected arguments `things` and `stuffs` to have distinct keys, but got {things} and {stuffs}"
        )
    if not (things_parsed | stuffs_parsed):
        raise ValueError("At least one of `things` and `stuffs` must be non-empty.")
    return things_parsed, stuffs_parsed


def _get_void_color(things: Set[int], stuffs: Set[int]) -> Tuple[int, int]:
    return 1 + max([0, *list(things), *list(stuffs)]), 0


def _get_category_id_to_continuous_id(things: Set[int], stuffs: Set[int]) -> Dict[int, int]:
    thing_map = {thing_id: idx for idx, thing_id in enumerate(sorted(things))}
    stuff_map = {stuff_id: idx + len(things) for idx, stuff_id in enumerate(sorted(stuffs))}
    return {**thing_map, **stuff_map}


def _validate_inputs(preds, target) -> None:
    if not hasattr(preds, "shape"):
        raise TypeError(f"Expected argument `preds` to be an array, but got {type(preds)}")
    if not hasattr(target, "shape"):
        raise TypeError(f"Expected argument `target` to be an array, but got {type(target)}")
    if tuple(preds.shape) != tuple(target.shape):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same shape, but got {preds.shape} and {target.shape}"
        )
    if preds.ndim < 3:
        raise ValueError(
            f"Expected argument `preds` to have at least one spatial dimension (B, *spatial_dims, 2), got {preds.shape}"
        )
    if preds.shape[-1] != 2:
        raise ValueError(
            "Expected argument `preds` to have exactly 2 channels in the last dimension (category, instance), "
            f"got {preds.shape} instead"
        )


def _preprocess_inputs(
    things: Set[int],
    stuffs: Set[int],
    inputs,
    void_color: Tuple[int, int],
    allow_unknown_category: bool,
) -> np.ndarray:
    """Flatten spatial dims, zero stuff instance ids, map unknown categories to void."""
    arr = np.asarray(inputs).astype(np.int64).reshape(inputs.shape[0], -1, 2).copy()
    cats = arr[..., 0]
    mask_stuffs = np.isin(cats, list(stuffs))
    mask_things = np.isin(cats, list(things))
    arr[..., 1] = np.where(mask_stuffs, 0, arr[..., 1])
    unknown = ~(mask_things | mask_stuffs)
    if not allow_unknown_category and unknown.any():
        raise ValueError(f"Unknown categories found: {np.unique(cats[unknown])}")
    arr[unknown] = np.asarray(void_color, np.int64)
    return arr


def _encode(colors: np.ndarray) -> np.ndarray:
    """(N, 2) colors -> int64 codes (category in the high 32 bits)."""
    return colors[..., 0] * _SHIFT + colors[..., 1]


def _panoptic_quality_update_sample(
    pred_s: np.ndarray,
    target_s: np.ndarray,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    stuffs_modified_metric: Optional[Set[int]] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized per-sample sufficient statistics (iou_sum, tp, fp, fn)."""
    modified = stuffs_modified_metric or set()
    num_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(num_categories, np.float64)
    tp = np.zeros(num_categories, np.int64)
    fp = np.zeros(num_categories, np.int64)
    fn = np.zeros(num_categories, np.int64)
    cont_of = np.vectorize(cat_id_to_continuous_id.__getitem__, otypes=[np.int64])

    # instance ids are arbitrary ints (incl. negative sentinels); remap them jointly
    # to a dense non-negative range so the int64 (category << 32 | instance) encoding
    # cannot shift into a neighboring category
    all_inst = np.concatenate([pred_s[:, 1], target_s[:, 1], np.asarray([void_color[1]], np.int64)])
    inst_values = np.unique(all_inst)
    pred_s = np.stack([pred_s[:, 0], np.searchsorted(inst_values, pred_s[:, 1])], axis=1)
    target_s = np.stack([target_s[:, 0], np.searchsorted(inst_values, target_s[:, 1])], axis=1)
    void_inst = int(np.searchsorted(inst_values, void_color[1]))

    pc = _encode(pred_s)
    tc = _encode(target_s)
    void = int(void_color[0]) * int(_SHIFT) + void_inst

    up, p_areas = np.unique(pc, return_counts=True)
    ut, t_areas = np.unique(tc, return_counts=True)
    upair, i_areas = np.unique(np.stack([pc, tc], axis=1), axis=0, return_counts=True)
    p_of, t_of = upair[:, 0], upair[:, 1]

    # per-color void overlaps, aligned to up/ut
    pred_void = np.zeros(up.shape[0], np.int64)
    mask_pv = t_of == void
    pred_void[np.searchsorted(up, p_of[mask_pv])] = i_areas[mask_pv]
    void_target = np.zeros(ut.shape[0], np.int64)
    mask_vt = p_of == void
    void_target[np.searchsorted(ut, t_of[mask_vt])] = i_areas[mask_vt]

    area_p = p_areas[np.searchsorted(up, p_of)]
    area_t = t_areas[np.searchsorted(ut, t_of)]
    pv_of = pred_void[np.searchsorted(up, p_of)]
    vt_of = void_target[np.searchsorted(ut, t_of)]

    cat_p = (p_of >> np.int64(32)).astype(np.int64)
    cat_t = (t_of >> np.int64(32)).astype(np.int64)
    cand = (t_of != void) & (cat_p == cat_t)  # void pred code has an out-of-map category
    union = area_p - pv_of + area_t - vt_of - i_areas
    iou = np.where(cand & (union > 0), i_areas / np.where(union > 0, union, 1), 0.0)

    is_modified = np.isin(cat_t, list(modified)) if modified else np.zeros_like(cand)
    matched = cand & ~is_modified & (iou > 0.5)
    mod_hit = cand & is_modified & (iou > 0)
    for mask in (matched, mod_hit):
        if mask.any():
            np.add.at(iou_sum, cont_of(cat_t[mask]), iou[mask])
    if matched.any():
        np.add.at(tp, cont_of(cat_t[matched]), 1)

    matched_p = p_of[matched]
    matched_t = t_of[matched]

    # FN: unmatched target segments not mostly void in the prediction
    t_unmatched = (ut != void) & ~np.isin(ut, matched_t)
    t_keep = t_unmatched & (void_target / t_areas <= 0.5)
    cat_fn = (ut[t_keep] >> np.int64(32)).astype(np.int64)
    cat_fn = cat_fn[~np.isin(cat_fn, list(modified))] if modified else cat_fn
    if cat_fn.size:
        np.add.at(fn, cont_of(cat_fn), 1)

    # FP: unmatched pred segments not mostly void in the target
    p_unmatched = (up != void) & ~np.isin(up, matched_p)
    p_keep = p_unmatched & (pred_void / p_areas <= 0.5)
    cat_fp = (up[p_keep] >> np.int64(32)).astype(np.int64)
    cat_fp = cat_fp[~np.isin(cat_fp, list(modified))] if modified else cat_fp
    if cat_fp.size:
        np.add.at(fp, cont_of(cat_fp), 1)

    # modified-PQ stuffs: "tp" counts target segments of that category
    if modified:
        cat_ut = (ut[ut != void] >> np.int64(32)).astype(np.int64)
        cat_mod = cat_ut[np.isin(cat_ut, list(modified))]
        if cat_mod.size:
            np.add.at(tp, cont_of(cat_mod), 1)

    return iou_sum, tp, fp, fn


def _panoptic_quality_update(
    flatten_preds: np.ndarray,
    flatten_target: np.ndarray,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    modified_metric_stuffs: Optional[Set[int]] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Batch sufficient statistics; segments are never matched across samples."""
    num_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(num_categories, np.float64)
    tp = np.zeros(num_categories, np.int64)
    fp = np.zeros(num_categories, np.int64)
    fn = np.zeros(num_categories, np.int64)
    for pred_s, target_s in zip(flatten_preds, flatten_target):
        r = _panoptic_quality_update_sample(
            pred_s, target_s, cat_id_to_continuous_id, void_color, stuffs_modified_metric=modified_metric_stuffs
        )
        iou_sum += r[0]
        tp += r[1]
        fp += r[2]
        fn += r[3]
    return iou_sum, tp, fp, fn


def _panoptic_quality_compute(
    iou_sum: jnp.ndarray,
    true_positives: jnp.ndarray,
    false_positives: jnp.ndarray,
    false_negatives: jnp.ndarray,
) -> Tuple[jnp.ndarray, ...]:
    """Per-class (pq, sq, rq) and their averages over observed classes (pure jnp)."""
    tp = true_positives.astype(jnp.float32)
    sq = jnp.where(tp > 0, iou_sum / jnp.where(tp > 0, tp, 1.0), 0.0)
    denominator = tp + 0.5 * false_positives.astype(jnp.float32) + 0.5 * false_negatives.astype(jnp.float32)
    rq = jnp.where(denominator > 0, tp / jnp.where(denominator > 0, denominator, 1.0), 0.0)
    pq = sq * rq
    seen = denominator > 0
    n_seen = seen.sum()
    safe = jnp.where(n_seen > 0, n_seen, 1)
    pq_avg = jnp.where(n_seen > 0, jnp.where(seen, pq, 0.0).sum() / safe, jnp.nan)
    sq_avg = jnp.where(n_seen > 0, jnp.where(seen, sq, 0.0).sum() / safe, jnp.nan)
    rq_avg = jnp.where(n_seen > 0, jnp.where(seen, rq, 0.0).sum() / safe, jnp.nan)
    return pq, sq, rq, pq_avg, sq_avg, rq_avg


def panoptic_quality(
    preds,
    target,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
    return_sq_and_rq: bool = False,
    return_per_class: bool = False,
) -> jnp.ndarray:
    """Compute Panoptic Quality for panoptic segmentations (reference
    ``functional/detection/panoptic_qualities.py:30``)."""
    things, stuffs = _parse_categories(things, stuffs)
    _validate_inputs(preds, target)
    void_color = _get_void_color(things, stuffs)
    cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
    flatten_preds = _preprocess_inputs(things, stuffs, preds, void_color, allow_unknown_preds_category)
    flatten_target = _preprocess_inputs(things, stuffs, target, void_color, True)
    iou_sum, tp, fp, fn = _panoptic_quality_update(flatten_preds, flatten_target, cat_id_to_continuous_id, void_color)
    pq, sq, rq, pq_avg, sq_avg, rq_avg = _panoptic_quality_compute(
        jnp.asarray(iou_sum), jnp.asarray(tp), jnp.asarray(fp), jnp.asarray(fn)
    )
    if return_per_class:
        if return_sq_and_rq:
            return jnp.stack([pq, sq, rq], axis=-1)
        return pq.reshape(1, -1)
    if return_sq_and_rq:
        return jnp.stack([pq_avg, sq_avg, rq_avg])
    return pq_avg


def modified_panoptic_quality(
    preds,
    target,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
) -> jnp.ndarray:
    """Compute Modified Panoptic Quality (stuff classes scored with the relaxed
    iou>0 rule; reference ``functional/detection/panoptic_qualities.py:175``)."""
    things, stuffs = _parse_categories(things, stuffs)
    _validate_inputs(preds, target)
    void_color = _get_void_color(things, stuffs)
    cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
    flatten_preds = _preprocess_inputs(things, stuffs, preds, void_color, allow_unknown_preds_category)
    flatten_target = _preprocess_inputs(things, stuffs, target, void_color, True)
    iou_sum, tp, fp, fn = _panoptic_quality_update(
        flatten_preds, flatten_target, cat_id_to_continuous_id, void_color, modified_metric_stuffs=stuffs
    )
    _, _, _, pq_avg, _, _ = _panoptic_quality_compute(jnp.asarray(iou_sum), jnp.asarray(tp), jnp.asarray(fp), jnp.asarray(fn))
    return pq_avg
