"""Pairwise box kernels — pure jnp, static-shape, MXU/VPU-friendly.

The reference delegates these to torchvision ops (``detection/iou.py:21``,
``functional/detection/iou.py:33``); here they are first-class jittable kernels so the
whole IoU family (and the mAP matcher built on top) stays in-graph. All kernels accept
arbitrary leading batch dimensions: ``(..., N, 4) x (..., M, 4) -> (..., N, M)``, which
is what lets the mAP evaluator vmap one fused matcher over images x area ranges x IoU
thresholds instead of the reference's per-image Python loop.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-7


def box_convert(boxes: jnp.ndarray, in_fmt: str, out_fmt: str = "xyxy") -> jnp.ndarray:
    """Convert ``(..., 4)`` boxes between xyxy / xywh / cxcywh formats."""
    if in_fmt == out_fmt:
        return boxes
    if out_fmt != "xyxy":
        raise ValueError(f"Only conversion to 'xyxy' is supported, got {out_fmt}")
    a, b, c, d = boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3]
    if in_fmt == "xywh":
        return jnp.stack([a, b, a + c, b + d], axis=-1)
    if in_fmt == "cxcywh":
        return jnp.stack([a - c / 2, b - d / 2, a + c / 2, b + d / 2], axis=-1)
    raise ValueError(f"Unsupported box format {in_fmt}")


def box_area(boxes: jnp.ndarray) -> jnp.ndarray:
    """Area of ``(..., 4)`` xyxy boxes -> ``(...,)``."""
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def _pairwise_intersection(preds: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    lt = jnp.maximum(preds[..., :, None, :2], target[..., None, :, :2])
    rb = jnp.minimum(preds[..., :, None, 2:], target[..., None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    return wh[..., 0] * wh[..., 1]


def box_iou_matrix(preds: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU: ``(..., N, 4) x (..., M, 4) -> (..., N, M)``."""
    inter = _pairwise_intersection(preds, target)
    union = box_area(preds)[..., :, None] + box_area(target)[..., None, :] - inter
    return jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)


def box_iou_matrix_crowd(preds: jnp.ndarray, target: jnp.ndarray, crowd: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU with the COCO crowd convention: for crowd ground truths the
    denominator is the detection area alone (pycocotools ``maskUtils.iou`` iscrowd
    semantics, used by the reference through its coco backend)."""
    inter = _pairwise_intersection(preds, target)
    pred_area = box_area(preds)[..., :, None]
    union = pred_area + box_area(target)[..., None, :] - inter
    denom = jnp.where(crowd[..., None, :], pred_area, union)
    return jnp.where(denom > 0, inter / jnp.where(denom > 0, denom, 1.0), 0.0)


def _enclosure_wh(preds: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    lt = jnp.minimum(preds[..., :, None, :2], target[..., None, :, :2])
    rb = jnp.maximum(preds[..., :, None, 2:], target[..., None, :, 2:])
    return jnp.clip(rb - lt, 0)


def generalized_box_iou_matrix(preds: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    """Pairwise GIoU = IoU - (enclosure - union) / enclosure."""
    inter = _pairwise_intersection(preds, target)
    union = box_area(preds)[..., :, None] + box_area(target)[..., None, :] - inter
    iou = jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)
    whi = _enclosure_wh(preds, target)
    areai = whi[..., 0] * whi[..., 1]
    return iou - jnp.where(areai > 0, (areai - union) / jnp.where(areai > 0, areai, 1.0), 0.0)


def _center_distance_ratio(preds: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    whi = _enclosure_wh(preds, target)
    diag = whi[..., 0] ** 2 + whi[..., 1] ** 2 + _EPS
    cp = (preds[..., :2] + preds[..., 2:]) / 2
    ct = (target[..., :2] + target[..., 2:]) / 2
    d = cp[..., :, None, :] - ct[..., None, :, :]
    return (d[..., 0] ** 2 + d[..., 1] ** 2) / diag


def distance_box_iou_matrix(preds: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    """Pairwise DIoU = IoU - centre-distance^2 / enclosure-diagonal^2 (eps matches
    torchvision's ``distance_box_iou``)."""
    return box_iou_matrix(preds, target) - _center_distance_ratio(preds, target)


def complete_box_iou_matrix(preds: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    """Pairwise CIoU = DIoU - alpha * v (aspect-ratio consistency term)."""
    iou = box_iou_matrix(preds, target)
    diou = iou - _center_distance_ratio(preds, target)
    wp = preds[..., 2] - preds[..., 0]
    hp = preds[..., 3] - preds[..., 1]
    wt = target[..., 2] - target[..., 0]
    ht = target[..., 3] - target[..., 1]
    v = (4 / (jnp.pi**2)) * (
        jnp.arctan(wt / ht)[..., None, :] - jnp.arctan(wp / hp)[..., :, None]
    ) ** 2
    alpha = v / (1 - iou + v + _EPS)
    return diou - alpha * v
