"""COCO mAP evaluation engine — pure JAX matcher + vectorized accumulation.

Re-implements the COCOeval algorithm (the reference delegates to the pycocotools C
extension through ``detection/helpers.py:152`` and keeps a pure-torch template at
``detection/_mean_ap.py:149``) as a TPU-first pipeline:

1. **vectorized row building**: every (class, image) cell becomes one row of a padded
   ``(rows, dmax)`` / ``(rows, gmax)`` batch via a single lexsort + group-boundary
   pass over the flat cat-state — no per-cell Python loop,
2. pairwise IoU for the whole row batch in one broadcast (host f64 for bbox, matching
   pycocotools' f64 IoU; pixel-matmul per cell for segm),
3. a **batched greedy matcher**: one ``lax.scan`` over score-sorted detection slots
   whose body is plain broadcasting over ``rows x areas x thresholds x gts`` — the
   reference's four nested Python loops (``_mean_ap.py:598-605``) collapse into one
   XLA program,
4. numpy accumulation: global stable score sort, cumsum TP/FP, precision envelope
   (reversed running max), 101-point interpolation via ``searchsorted`` — identical
   semantics to COCOeval.accumulate, including the crowd/ignore and tie-breaking
   rules (last ground-truth wins equal IoU; ignored gts only matchable when no
   non-ignored gt clears the threshold). Tested cell-for-cell against the COCOeval
   matching loop in ``tests/_coco_oracle.py``.

The matcher body deliberately avoids ``.at[].set`` scatters inside the scan: the
scatter formulation miscompiles under XLA for row batches >= 64 (batch-size-dependent
wrong matches, observed identically on CPU and TPU backends with jax 0.9) — the
one-hot | or formulation is both correct at every batch size and ~600x faster.

IoU matrices are computed in float64 on host; threshold eligibility is resolved there
too (f64 IoU vs f64 thresholds, pycocotools comparison semantics) and shipped to the
device matcher as an int32 cleared-threshold count, so the f32 IoU the matcher keeps
for best-match argmax can never flip a boundary tie (caught by the segm doctest
golden, tests/test_reference_doctest_goldens.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


# COCO area ranges: all / small / medium / large (reference _mean_ap.py:351-356)
_AREA_RANGES = np.array(
    [[0.0, 1e5**2], [0.0, 32.0**2], [32.0**2, 96.0**2], [96.0**2, 1e5**2]], np.float32
)
_AREA_KEYS = ("all", "small", "medium", "large")
_ROW_BLOCK = 8192  # matcher rows per XLA call (memory/compile trade-off)

# Default thresholds: the reference builds these with torch.linspace in FLOAT32
# (mean_ap.py:382,388) and feeds the f32-quantized values into COCOeval as f64, so
# e.g. its "0.6" IoU threshold is really 0.6000000238418579 — an exact-0.6 IoU does
# NOT clear it (the segm doctest golden, map 0.2 not 0.3, hinges on this). The exact
# values are pinned here as literals; tests/test_reference_doctest_goldens.py
# asserts them against torch.linspace.
DEFAULT_IOU_THRESHOLDS = [
    0.5, 0.550000011920929, 0.6000000238418579, 0.6499999761581421, 0.699999988079071,
    0.75, 0.800000011920929, 0.8500000238418579, 0.8999999761581421, 0.949999988079071,
]
DEFAULT_REC_THRESHOLDS = [
    0.0, 0.009999999776482582, 0.019999999552965164, 0.029999999329447746, 0.03999999910593033,
    0.04999999701976776, 0.05999999865889549, 0.07000000029802322, 0.07999999821186066, 0.08999999612569809,
    0.09999999403953552, 0.10999999940395355, 0.11999999731779099, 0.12999999523162842, 0.14000000059604645,
    0.14999999105930328, 0.1599999964237213, 0.17000000178813934, 0.17999999225139618, 0.1899999976158142,
    0.19999998807907104, 0.20999999344348907, 0.2199999988079071, 0.22999998927116394, 0.23999999463558197,
    0.25, 0.25999999046325684, 0.26999998092651367, 0.2800000011920929, 0.28999999165534973,
    0.29999998211860657, 0.3100000023841858, 0.3199999928474426, 0.32999998331069946, 0.3400000035762787,
    0.3499999940395355, 0.35999998450279236, 0.3700000047683716, 0.3799999952316284, 0.38999998569488525,
    0.3999999761581421, 0.4099999964237213, 0.41999998688697815, 0.429999977350235, 0.4399999976158142,
    0.44999998807907104, 0.4599999785423279, 0.4699999988079071, 0.47999998927116394, 0.4899999797344208,
    0.5, 0.5099999904632568, 0.5199999809265137, 0.5300000309944153, 0.5400000214576721,
    0.550000011920929, 0.5600000023841858, 0.5699999928474426, 0.5799999833106995, 0.5900000333786011,
    0.6000000238418579, 0.6100000143051147, 0.6200000047683716, 0.6299999952316284, 0.6399999856948853,
    0.6500000357627869, 0.6600000262260437, 0.6700000166893005, 0.6800000071525574, 0.6899999976158142,
    0.699999988079071, 0.7099999785423279, 0.7200000286102295, 0.7300000190734863, 0.7400000095367432,
    0.75, 0.7599999904632568, 0.7699999809265137, 0.7800000309944153, 0.7900000214576721,
    0.800000011920929, 0.8100000023841858, 0.8199999928474426, 0.8299999833106995, 0.8400000333786011,
    0.8500000238418579, 0.8600000143051147, 0.8700000047683716, 0.8799999952316284, 0.8899999856948853,
    0.8999999761581421, 0.9100000262260437, 0.9200000166893005, 0.9300000071525574, 0.9399999976158142,
    0.949999988079071, 0.9599999785423279, 0.9700000286102295, 0.9800000190734863, 0.9900000095367432,
    1.0,
]


def _mask_iou_np(dets: np.ndarray, gts: np.ndarray, crowd: np.ndarray) -> np.ndarray:
    """Host pairwise mask IoU for one cell (f64, pycocotools dtype) — per-cell device
    dispatch would dominate at COCO scale, and host BLAS handles the small pixel
    matmuls fine."""
    d = dets.reshape(dets.shape[0], -1).astype(np.float64)
    g = gts.reshape(gts.shape[0], -1).astype(np.float64)
    inter = d @ g.T
    d_area = d.sum(-1)[:, None]
    union = d_area + g.sum(-1)[None, :] - inter
    denom = np.where(crowd[None, :], d_area, union)
    return np.where(denom > 0, inter / np.where(denom > 0, denom, 1.0), 0.0)


def _box_iou_np(det: np.ndarray, gt: np.ndarray, crowd: np.ndarray) -> np.ndarray:
    """Host pairwise crowd-IoU for one (class, image) cell (f64, pycocotools dtype)."""
    det = det.astype(np.float64)
    gt = gt.astype(np.float64)
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    det_area = ((det[:, 2] - det[:, 0]) * (det[:, 3] - det[:, 1]))[:, None]
    gt_area = ((gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1]))[None, :]
    union = det_area + gt_area - inter
    denom = np.where(crowd[None, :], det_area, union)
    return np.where(denom > 0, inter / np.where(denom > 0, denom, 1.0), 0.0).astype(np.float32)


def _bucket(n: int, floor: int = 4) -> int:
    """Round up to the next power of two (compile-cache friendliness)."""
    b = floor
    while b < n:
        b *= 2
    return b


@jax.jit
def _match_kernel(
    iou: jnp.ndarray,  # (R, D, G) crowd-adjusted IoU, dets score-sorted per row
    clears: jnp.ndarray,  # (R, D, G) int32: #sorted-thresholds cleared, resolved in f64 on host
    det_valid: jnp.ndarray,  # (R, D) bool
    det_area: jnp.ndarray,  # (R, D)
    gt_valid: jnp.ndarray,  # (R, G) bool
    gt_area: jnp.ndarray,  # (R, G)
    gt_crowd: jnp.ndarray,  # (R, G) bool
    thr_idx: jnp.ndarray,  # (T,) int32: rank of each threshold in ascending order
    area_ranges: jnp.ndarray,  # (A, 2)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Greedy COCO matching over rows x area ranges x IoU thresholds in one scan.

    Threshold eligibility arrives pre-resolved as ``clears`` (``iou >= thrs[t]``
    iff ``clears > thr_idx[t]``, where ``clears`` counts cleared thresholds in
    ascending order and ``thr_idx`` is each threshold's rank — order-agnostic for
    user-supplied unsorted lists): pycocotools compares f64 IoUs against f64
    thresholds, and ties at a boundary (e.g. an exact-0.6 IoU vs the reference's
    f32-quantized 0.6000000238418579) resolve differently in f32 — caught by the
    segm doctest golden (tests/test_reference_doctest_goldens.py). The f32
    ``iou`` is then only used for best-match argmax, where pycocotools order is
    preserved.

    Returns ``det_match (R,A,T,D)``, ``det_ignore (R,A,T,D)``, ``gt_ignore (R,A,G)``.
    """
    gt_ign = (
        (gt_area[:, None, :] < area_ranges[None, :, :1])
        | (gt_area[:, None, :] > area_ranges[None, :, 1:])
        | gt_crowd[:, None, :]
        | ~gt_valid[:, None, :]
    )  # (R, A, G)
    det_out = (det_area[:, None, :] < area_ranges[None, :, :1]) | (
        det_area[:, None, :] > area_ranges[None, :, 1:]
    )  # (R, A, D)
    num_gt = iou.shape[-1]

    def step(gt_matched, d):  # gt_matched: (R, A, T, G)
        row = iou[:, d, :][:, None, None, :]  # (R,1,1,G)
        clears_row = clears[:, d, :][:, None, None, :]  # (R,1,1,G)
        cand = (
            gt_valid[:, None, None, :]
            & (~gt_matched | gt_crowd[:, None, None, :])
            & (clears_row > thr_idx[None, None, :, None])
            & det_valid[:, d][:, None, None, None]
        )
        cand_nonign = cand & ~gt_ign[:, :, None, :]
        pool = jnp.where(cand_nonign.any(-1, keepdims=True), cand_nonign, cand)
        vals = jnp.where(pool, row, -jnp.inf)
        m = num_gt - 1 - jnp.argmax(vals[..., ::-1], axis=-1)  # last argmax: later gt wins ties
        matched = pool.any(-1)  # (R,A,T)
        oh = jax.nn.one_hot(m, num_gt, dtype=bool) & matched[..., None]
        gt_matched = gt_matched | oh
        ign_of_m = (oh & gt_ign[:, :, None, :]).any(-1)  # cheap-to-compile gather of gt_ign[m]
        return gt_matched, (matched, ign_of_m)

    init = jnp.zeros((iou.shape[0], area_ranges.shape[0], thr_idx.shape[0], num_gt), bool)
    _, (dm, dig) = lax.scan(step, init, jnp.arange(iou.shape[1]))
    dm = jnp.moveaxis(dm, 0, -1)  # (R, A, T, D)
    dig = jnp.moveaxis(dig, 0, -1)
    dig = dig | (~dm & det_out[:, :, None, :])  # unmatched dets outside the range: ignored
    return dm, dig, gt_ign


class MAPInputs:
    """Per-image numpy views of the flat mAP state (reconstructed from cat rows)."""

    def __init__(
        self,
        det_boxes: List[np.ndarray],
        det_scores: List[np.ndarray],
        det_labels: List[np.ndarray],
        gt_boxes: List[np.ndarray],
        gt_labels: List[np.ndarray],
        gt_crowds: List[np.ndarray],
        gt_areas: List[np.ndarray],
        det_masks: Optional[List[np.ndarray]] = None,
        gt_masks: Optional[List[np.ndarray]] = None,
    ) -> None:
        self.det_boxes = det_boxes
        self.det_scores = det_scores
        self.det_labels = det_labels
        self.gt_boxes = gt_boxes
        self.gt_labels = gt_labels
        self.gt_crowds = gt_crowds
        self.gt_areas = gt_areas
        self.det_masks = det_masks
        self.gt_masks = gt_masks
        self.num_images = len(det_scores)

    def classes(self) -> List[int]:
        parts = [x for x in self.det_labels + self.gt_labels if x.size]
        if not parts:
            return []
        return np.unique(np.concatenate(parts)).astype(int).tolist()


def _mask_areas(masks: np.ndarray) -> np.ndarray:
    # sum over every axis but the first: reshape(n, -1) raises on n == 0 (an
    # empty-image mask stack like (0, H, W) makes -1 ambiguous)
    return masks.sum(axis=tuple(range(1, masks.ndim))).astype(np.float64)


def _det_area(inputs: MAPInputs, img: int, iou_type: str) -> np.ndarray:
    if iou_type == "segm":
        return _mask_areas(inputs.det_masks[img])
    b = inputs.det_boxes[img]
    return ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])).astype(np.float64)


def _gt_area(inputs: MAPInputs, img: int, iou_type: str) -> np.ndarray:
    provided = inputs.gt_areas[img].astype(np.float64)
    if iou_type == "segm":
        computed = _mask_areas(inputs.gt_masks[img])
    else:
        b = inputs.gt_boxes[img]
        computed = ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])).astype(np.float64)
    return np.where(provided > 0, provided, computed)


class _RowBatch:
    """Padded (class, image)-cell row arrays built in one vectorized pass."""

    __slots__ = (
        "num_rows", "dmax", "gmax", "classes", "class_slices", "row_img", "row_cls",
        "det_valid", "det_score", "det_area", "det_box", "det_src",
        "gt_valid", "gt_area", "gt_crowd", "gt_box", "gt_src",
    )


def _build_rows(
    inputs: MAPInputs, iou_type: str, max_det: int,
    det_areas_all: List[np.ndarray], gt_areas_all: List[np.ndarray],
) -> Optional[_RowBatch]:
    """Flatten every (class, image) cell into padded rows via one lexsort pass.

    Row order is class-major, image-minor, so each class owns a contiguous row
    slice; dets inside a row are score-sorted (stable) and truncated to
    ``max_det`` — exactly COCOeval's per-cell ordering.
    """
    classes = np.asarray(inputs.classes(), np.int64)
    if classes.size == 0:
        return None
    num_images = inputs.num_images
    d_sizes = np.array([x.size for x in inputs.det_labels], np.int64)
    g_sizes = np.array([x.size for x in inputs.gt_labels], np.int64)

    img_d = np.repeat(np.arange(num_images), d_sizes)
    lab_d = np.searchsorted(classes, np.concatenate(inputs.det_labels) if img_d.size else np.zeros(0, np.int64))
    score_d = np.concatenate(inputs.det_scores) if img_d.size else np.zeros(0)
    img_g = np.repeat(np.arange(num_images), g_sizes)
    lab_g = np.searchsorted(classes, np.concatenate(inputs.gt_labels) if img_g.size else np.zeros(0, np.int64))

    order_d = np.lexsort((-score_d, img_d, lab_d))
    key_d = lab_d[order_d] * num_images + img_d[order_d]
    uq_d, start_d = np.unique(key_d, return_index=True)
    cnt_d = np.diff(np.append(start_d, key_d.size))
    order_g = np.lexsort((img_g, lab_g))
    key_g = lab_g[order_g] * num_images + img_g[order_g]
    uq_g, start_g = np.unique(key_g, return_index=True)
    cnt_g = np.diff(np.append(start_g, key_g.size))

    all_keys = np.union1d(uq_d, uq_g)  # sorted: class-major, image-minor
    rb = _RowBatch()
    rb.num_rows = all_keys.size
    rb.classes = classes
    rb.row_img = (all_keys % num_images).astype(np.int64)
    rb.row_cls = (all_keys // num_images).astype(np.int64)
    lo = np.searchsorted(rb.row_cls, np.arange(classes.size), side="left")
    hi = np.searchsorted(rb.row_cls, np.arange(classes.size), side="right")
    rb.class_slices = [slice(int(a), int(b)) for a, b in zip(lo, hi)]

    # ---- dets: scatter into (rows, dmax) padding, truncating at max_det
    row_idx_d = np.repeat(np.searchsorted(all_keys, uq_d), cnt_d)
    pos_d = np.arange(key_d.size) - np.repeat(start_d, cnt_d)
    keep = pos_d < max_det
    row_idx_d, pos_d, src_d = row_idx_d[keep], pos_d[keep], order_d[keep]
    rb.dmax = _bucket(int(pos_d.max()) + 1 if pos_d.size else 1)
    rb.det_valid = np.zeros((rb.num_rows, rb.dmax), bool)
    rb.det_valid[row_idx_d, pos_d] = True
    rb.det_score = np.full((rb.num_rows, rb.dmax), -np.inf, np.float32)
    rb.det_score[row_idx_d, pos_d] = score_d[src_d]
    flat_det_area = np.concatenate(det_areas_all) if img_d.size else np.zeros(0)
    rb.det_area = np.zeros((rb.num_rows, rb.dmax), np.float32)
    rb.det_area[row_idx_d, pos_d] = flat_det_area[src_d]
    if iou_type == "bbox":
        flat_det_box = (
            np.concatenate(inputs.det_boxes).astype(np.float64).reshape(-1, 4)
            if img_d.size else np.zeros((0, 4))
        )
        rb.det_box = np.zeros((rb.num_rows, rb.dmax, 4), np.float64)
        rb.det_box[row_idx_d, pos_d] = flat_det_box[src_d]
    else:
        rb.det_box = None
    # per-row flat det source indices (pos-ordered) for segm / extended summary
    bounds_d = np.searchsorted(row_idx_d, np.arange(rb.num_rows + 1))
    rb.det_src = (src_d, bounds_d)

    # ---- gts
    row_idx_g = np.repeat(np.searchsorted(all_keys, uq_g), cnt_g)
    pos_g = np.arange(key_g.size) - np.repeat(start_g, cnt_g)
    src_g = order_g
    rb.gmax = _bucket(int(cnt_g.max()) if cnt_g.size else 1)
    rb.gt_valid = np.zeros((rb.num_rows, rb.gmax), bool)
    rb.gt_valid[row_idx_g, pos_g] = True
    flat_gt_area = np.concatenate(gt_areas_all) if img_g.size else np.zeros(0)
    rb.gt_area = np.zeros((rb.num_rows, rb.gmax), np.float32)
    rb.gt_area[row_idx_g, pos_g] = flat_gt_area[src_g]
    flat_gt_crowd = (
        np.concatenate(inputs.gt_crowds).astype(bool) if img_g.size else np.zeros(0, bool)
    )
    rb.gt_crowd = np.zeros((rb.num_rows, rb.gmax), bool)
    rb.gt_crowd[row_idx_g, pos_g] = flat_gt_crowd[src_g]
    if iou_type == "bbox":
        flat_gt_box = (
            np.concatenate(inputs.gt_boxes).astype(np.float64).reshape(-1, 4)
            if img_g.size else np.zeros((0, 4))
        )
        rb.gt_box = np.zeros((rb.num_rows, rb.gmax, 4), np.float64)
        rb.gt_box[row_idx_g, pos_g] = flat_gt_box[src_g]
    else:
        rb.gt_box = None
    bounds_g = np.searchsorted(row_idx_g, np.arange(rb.num_rows + 1))
    rb.gt_src = (src_g, bounds_g)
    return rb


def _block_iou_bbox(rb: _RowBatch, sl: slice, thrs64: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Pairwise crowd-adjusted IoU for a row block, f64 math (pycocotools dtype)
    broadcast in bounded sub-chunks: at COCO scale (dmax=gmax=128) a whole-block
    broadcast would stage multi-GB f64 temporaries, mostly padding.

    Returns ``(iou_f32, clears_i32)``: threshold eligibility is resolved here in
    f64 against the f64 thresholds (pycocotools comparison semantics) before the
    downcast, so f32 rounding can never flip a boundary tie."""
    n = sl.stop - sl.start
    out = np.empty((n, rb.dmax, rb.gmax), np.float32)
    clears = np.empty((n, rb.dmax, rb.gmax), np.int32)
    step = max(1, int(128 * 1024 * 1024 // max(1, rb.dmax * rb.gmax * 8 * 4)))
    for s in range(0, n, step):
        dbox = rb.det_box[sl.start + s : sl.start + min(s + step, n)]  # (C, dmax, 4)
        gbox = rb.gt_box[sl.start + s : sl.start + min(s + step, n)]  # (C, gmax, 4)
        lt = np.maximum(dbox[:, :, None, :2], gbox[:, None, :, :2])
        rbn = np.minimum(dbox[:, :, None, 2:], gbox[:, None, :, 2:])
        wh = np.clip(rbn - lt, 0, None)
        inter = wh[..., 0] * wh[..., 1]
        d_area = (dbox[..., 2] - dbox[..., 0]) * (dbox[..., 3] - dbox[..., 1])
        g_area = (gbox[..., 2] - gbox[..., 0]) * (gbox[..., 3] - gbox[..., 1])
        union = d_area[:, :, None] + g_area[:, None, :] - inter
        crowd = rb.gt_crowd[sl.start + s : sl.start + min(s + step, n)]
        denom = np.where(crowd[:, None, :], d_area[:, :, None], union)
        iou64 = np.where(denom > 0, inter / np.where(denom > 0, denom, 1.0), 0.0)
        out[s : s + dbox.shape[0]] = iou64
        clears[s : s + dbox.shape[0]] = np.searchsorted(thrs64, iou64.reshape(-1), side="right").reshape(iou64.shape)
    return out, clears


def _block_iou_segm(rb: _RowBatch, sl: slice, inputs: MAPInputs, thrs64: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Segm IoU per cell (pixel matmul on host); cells are ragged in H,W so the
    block can't be one broadcast like bbox. Returns ``(iou_f32, clears_i32)`` with
    f64 threshold resolution like ``_block_iou_bbox``."""
    src_d, bounds_d = rb.det_src
    src_g, bounds_g = rb.gt_src
    d_sizes = np.array([x.size for x in inputs.det_labels], np.int64)
    g_sizes = np.array([x.size for x in inputs.gt_labels], np.int64)
    d_off = np.concatenate([[0], np.cumsum(d_sizes)])
    g_off = np.concatenate([[0], np.cumsum(g_sizes)])
    iou = np.zeros((sl.stop - sl.start, rb.dmax, rb.gmax), np.float32)
    clears = np.zeros((sl.stop - sl.start, rb.dmax, rb.gmax), np.int32)
    for off, r in enumerate(range(sl.start, sl.stop)):
        ds = src_d[bounds_d[r] : bounds_d[r + 1]]
        gs = src_g[bounds_g[r] : bounds_g[r + 1]]
        if ds.size == 0 or gs.size == 0:
            continue
        img = rb.row_img[r]
        d_local = ds - d_off[img]
        g_local = gs - g_off[img]
        crowd = inputs.gt_crowds[img][g_local].astype(bool)
        cell64 = _mask_iou_np(inputs.det_masks[img][d_local], inputs.gt_masks[img][g_local], crowd)
        iou[off, : ds.size, : gs.size] = cell64
        clears[off, : ds.size, : gs.size] = np.searchsorted(
            thrs64, cell64.reshape(-1), side="right"
        ).reshape(cell64.shape)
    return iou, clears


def evaluate_map(
    inputs: MAPInputs,
    iou_type: str,
    iou_thresholds: List[float],
    rec_thresholds: List[float],
    max_detection_thresholds: List[int],
    want_ious: bool = False,
) -> Dict[str, np.ndarray]:
    """Run matching + accumulation; returns COCOeval-shaped arrays.

    ``precision``: (T, R, K, A, M); ``recall``: (T, K, A, M); ``scores`` like
    precision; ``classes``: (K,). Entries stay -1 where a (class, area) has no
    non-ignored ground truth (COCOeval convention).
    """
    num_t, num_r = len(iou_thresholds), len(rec_thresholds)
    classes_list = inputs.classes()
    num_k, num_a, num_m = len(classes_list), len(_AREA_RANGES), len(max_detection_thresholds)
    precision = -np.ones((num_t, num_r, num_k, num_a, num_m))
    recall = -np.ones((num_t, num_k, num_a, num_m))
    scores_out = -np.ones((num_t, num_r, num_k, num_a, num_m))
    max_det = max_detection_thresholds[-1]
    rec_thrs = np.asarray(rec_thresholds, np.float64)
    ious_out: Dict = {}

    det_areas_all = [_det_area(inputs, i, iou_type) for i in range(inputs.num_images)]
    gt_areas_all = [_gt_area(inputs, i, iou_type) for i in range(inputs.num_images)]
    rb = _build_rows(inputs, iou_type, max_det, det_areas_all, gt_areas_all)
    if rb is None:
        return {
            "precision": precision, "recall": recall, "scores": scores_out,
            "classes": np.asarray(classes_list, np.int32),
            **({"ious": ious_out} if want_ious else {}),
        }

    num_rows = rb.num_rows
    dm_all = np.zeros((num_rows, num_a, num_t, rb.dmax), bool)
    dig_all = np.zeros_like(dm_all)
    gt_ign_all = np.zeros((num_rows, num_a, rb.gmax), bool)

    # The matcher is an XLA program, but COCO cells are tiny (dmax/gmax <= 128):
    # accelerator round-trips (H2D + D2H per block) dominate any device win, so run
    # it on the local CPU backend by default — same compiled code, no transfers.
    # (The mesh-sharded detection path, detection/sharded.py, keeps matching on
    # device where the state already lives.)
    matcher_device = jax.local_devices(backend="cpu")[0]
    with jax.default_device(matcher_device):
        # pycocotools clamps each threshold: iou = min(t, 1 - 1e-10), so an exact
        # 1.0 IoU still clears a 1.0 threshold. `clears` counts against the SORTED
        # thresholds and each threshold gets its ascending rank, so user-supplied
        # unsorted lists resolve correctly (searchsorted needs sorted input).
        thrs_eff = np.minimum(np.asarray(iou_thresholds, np.float64), 1.0 - 1e-10)
        order = np.argsort(thrs_eff, kind="stable")
        thrs64 = thrs_eff[order]
        ranks = np.empty(len(iou_thresholds), np.int32)
        ranks[order] = np.arange(len(iou_thresholds), dtype=np.int32)
        thr_idx_j = jnp.asarray(ranks)
        area_ranges_j = jnp.asarray(_AREA_RANGES)
        for block_start in range(0, num_rows, _ROW_BLOCK):
            sl = slice(block_start, min(block_start + _ROW_BLOCK, num_rows))
            n = sl.stop - sl.start
            pad = _ROW_BLOCK if num_rows > _ROW_BLOCK else _bucket(n)
            iou_b, clears_b = (
                _block_iou_bbox(rb, sl, thrs64)
                if iou_type == "bbox"
                else _block_iou_segm(rb, sl, inputs, thrs64)
            )
            if pad > n:
                iou_b = np.concatenate([iou_b, np.zeros((pad - n, rb.dmax, rb.gmax), np.float32)])
                clears_b = np.concatenate([clears_b, np.zeros((pad - n, rb.dmax, rb.gmax), np.int32)])
            pad_rows = lambda a, fill=False: (
                a[sl] if pad == n else np.concatenate([a[sl], np.full((pad - n, *a.shape[1:]), fill, a.dtype)])
            )
            dm_b, dig_b, gt_ign_b = _match_kernel(
                jnp.asarray(iou_b),
                jnp.asarray(clears_b),
                jnp.asarray(pad_rows(rb.det_valid)),
                jnp.asarray(pad_rows(rb.det_area)),
                jnp.asarray(pad_rows(rb.gt_valid)),
                jnp.asarray(pad_rows(rb.gt_area)),
                jnp.asarray(pad_rows(rb.gt_crowd)),
                thr_idx_j,
                area_ranges_j,
            )
            dm_all[sl] = np.asarray(dm_b)[:n]
            dig_all[sl] = np.asarray(dig_b)[:n]
            gt_ign_all[sl] = np.asarray(gt_ign_b)[:n]
            if want_ious:
                src_d, bounds_d = rb.det_src
                src_g, bounds_g = rb.gt_src
                for r in range(sl.start, sl.stop):
                    nd = bounds_d[r + 1] - bounds_d[r]
                    ng = bounds_g[r + 1] - bounds_g[r]
                    ious_out[(int(rb.row_img[r]), int(rb.classes[rb.row_cls[r]]))] = iou_b[
                        r - sl.start, :nd, :ng
                    ]

    # ---- accumulate (COCOeval.accumulate semantics), per class over its row slice
    pos_in_cell = np.arange(rb.dmax)[None, :]
    for k_idx in range(num_k):
        sl = rb.class_slices[k_idx]
        if sl.start == sl.stop:
            continue
        dm = dm_all[sl]
        dig = dig_all[sl]
        gt_ign = gt_ign_all[sl]
        det_valid_c = rb.det_valid[sl]
        det_score = rb.det_score[sl]
        gt_valid_n = rb.gt_valid[sl]

        for a_idx in range(num_a):
            npig = int((~gt_ign[:, a_idx, :] & gt_valid_n).sum())
            if npig == 0:
                continue
            dm_a = np.ascontiguousarray(dm[:, a_idx, :, :].transpose(1, 0, 2).reshape(num_t, -1))
            dig_a = np.ascontiguousarray(dig[:, a_idx, :, :].transpose(1, 0, 2).reshape(num_t, -1))
            for m_idx, mdet in enumerate(max_detection_thresholds):
                sel = det_valid_c & (pos_in_cell < mdet)  # (rows_c, dmax)
                flat_scores = np.where(sel, det_score, -np.inf).reshape(-1)
                order = np.argsort(-flat_scores, kind="mergesort")
                nd = int(sel.sum())
                ord_nd = order[:nd]
                scores_sorted = flat_scores[ord_nd]
                dm_f = dm_a[:, ord_nd]
                dig_f = dig_a[:, ord_nd]
                tps = dm_f & ~dig_f
                fps = ~dm_f & ~dig_f
                tp_sum = np.cumsum(tps, axis=1, dtype=np.float64)
                fp_sum = np.cumsum(fps, axis=1, dtype=np.float64)
                for t_idx in range(num_t):
                    tp, fp = tp_sum[t_idx], fp_sum[t_idx]
                    rc = tp / npig
                    pr = tp / (fp + tp + np.spacing(1))
                    recall[t_idx, k_idx, a_idx, m_idx] = rc[-1] if nd else 0.0
                    q = np.zeros(num_r)
                    ss = np.zeros(num_r)
                    if nd:
                        pr_env = np.maximum.accumulate(pr[::-1])[::-1]
                        inds = np.searchsorted(rc, rec_thrs, side="left")
                        valid = inds < nd
                        q[valid] = pr_env[inds[valid]]
                        ss[valid] = scores_sorted[inds[valid]]
                    precision[t_idx, :, k_idx, a_idx, m_idx] = q
                    scores_out[t_idx, :, k_idx, a_idx, m_idx] = ss

    out = {
        "precision": precision,
        "recall": recall,
        "scores": scores_out,
        "classes": np.asarray(classes_list, np.int32),
    }
    if want_ious:
        out["ious"] = ious_out
    return out


def summarize(
    precision: np.ndarray,
    recall: np.ndarray,
    iou_thresholds: List[float],
    max_detection_thresholds: List[int],
    class_idx: Optional[int] = None,
) -> Dict[str, float]:
    """COCOeval.summarize: means over entries > -1, -1 when empty."""

    def _mean(arr: np.ndarray) -> float:
        vals = arr[arr > -1]
        return float(vals.mean()) if vals.size else -1.0

    k = slice(None) if class_idx is None else slice(class_idx, class_idx + 1)
    last_m = len(max_detection_thresholds) - 1
    res = {
        "map": _mean(precision[:, :, k, 0, last_m]),
        "map_small": _mean(precision[:, :, k, 1, last_m]),
        "map_medium": _mean(precision[:, :, k, 2, last_m]),
        "map_large": _mean(precision[:, :, k, 3, last_m]),
        "mar_small": _mean(recall[:, k, 1, last_m]),
        "mar_medium": _mean(recall[:, k, 2, last_m]),
        "mar_large": _mean(recall[:, k, 3, last_m]),
    }
    res["map_50"] = (
        _mean(precision[iou_thresholds.index(0.5), :, k, 0, last_m]) if 0.5 in iou_thresholds else -1.0
    )
    res["map_75"] = (
        _mean(precision[iou_thresholds.index(0.75), :, k, 0, last_m]) if 0.75 in iou_thresholds else -1.0
    )
    for m_idx, mdet in enumerate(max_detection_thresholds):
        res[f"mar_{mdet}"] = _mean(recall[:, k, 0, m_idx])
    return res
