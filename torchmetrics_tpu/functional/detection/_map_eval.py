"""COCO mAP evaluation engine — pure JAX matcher + vectorized accumulation.

Re-implements the COCOeval algorithm (the reference delegates to the pycocotools C
extension through ``detection/helpers.py:152`` and keeps a pure-torch template at
``detection/_mean_ap.py:149``) as a TPU-first pipeline:

1. per-image IoU matrices (bbox: ``_box_ops`` pairwise kernels; segm: one
   pixel-flattened matmul per image — MXU work),
2. a **batched greedy matcher**: ``lax.scan`` over score-sorted detections, vmapped
   over IoU thresholds x area ranges x images — the reference's four nested Python
   loops (``_mean_ap.py:598-605``) collapse into one XLA call per class,
3. numpy accumulation: global stable score sort, cumsum TP/FP, precision envelope
   (reversed running max), 101-point interpolation via ``searchsorted`` — identical
   semantics to COCOeval.accumulate, including the crowd/ignore and tie-breaking
   rules (last ground-truth wins equal IoU; ignored gts only matchable when no
   non-ignored gt clears the threshold).

Matching runs in float32 (TPU-native); pycocotools uses float64, so IoU values that
tie *exactly* at a threshold boundary in f64 may resolve differently — empirically
immaterial on real boxes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ._box_ops import box_iou_matrix_crowd

# COCO area ranges: all / small / medium / large (reference _mean_ap.py:351-356)
_AREA_RANGES = np.array(
    [[0.0, 1e5**2], [0.0, 32.0**2], [32.0**2, 96.0**2], [96.0**2, 1e5**2]], np.float32
)
_AREA_KEYS = ("all", "small", "medium", "large")


def mask_iou_matrix(dets: jnp.ndarray, gts: jnp.ndarray, crowd: jnp.ndarray) -> jnp.ndarray:
    """Pairwise mask IoU ``(D,H,W) x (G,H,W) -> (D,G)`` with COCO crowd semantics
    (crowd gt: denominator is the detection area). Pixel intersection is one matmul."""
    d = dets.reshape(dets.shape[0], -1).astype(jnp.float32)
    g = gts.reshape(gts.shape[0], -1).astype(jnp.float32)
    inter = d @ g.T
    d_area = d.sum(-1)[:, None]
    union = d_area + g.sum(-1)[None, :] - inter
    denom = jnp.where(crowd[None, :], d_area, union)
    return jnp.where(denom > 0, inter / jnp.where(denom > 0, denom, 1.0), 0.0)


def _bucket(n: int, floor: int = 4) -> int:
    """Round up to the next power of two (compile-cache friendliness)."""
    b = floor
    while b < n:
        b *= 2
    return b


@jax.jit
def _match_kernel(
    iou: jnp.ndarray,  # (I, D, G) crowd-adjusted IoU
    det_valid: jnp.ndarray,  # (I, D) bool, score-sorted per image
    det_area: jnp.ndarray,  # (I, D)
    gt_valid: jnp.ndarray,  # (I, G) bool
    gt_area: jnp.ndarray,  # (I, G)
    gt_crowd: jnp.ndarray,  # (I, G) bool
    iou_thrs: jnp.ndarray,  # (T,)
    area_ranges: jnp.ndarray,  # (A, 2)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Greedy COCO matching, batched over images x area ranges x IoU thresholds.

    Returns ``det_match (I,A,T,D)``, ``det_ignore (I,A,T,D)``, ``gt_ignore (I,A,G)``.
    """
    num_gt = iou.shape[-1]

    def per_image(iou_i, dval, darea, gval, garea, gcrowd):
        gt_ign_a = (
            (garea[None, :] < area_ranges[:, :1])
            | (garea[None, :] > area_ranges[:, 1:])
            | gcrowd[None, :]
            | ~gval[None, :]
        )  # (A, G)
        det_out_a = (darea[None, :] < area_ranges[:, :1]) | (darea[None, :] > area_ranges[:, 1:])  # (A, D)

        def per_at(gt_ign, thr):
            thr_eff = jnp.minimum(thr, 1.0 - 1e-10)

            def step(gt_matched, d):
                row = iou_i[d]
                cand = gval & (~gt_matched | gcrowd) & (row >= thr_eff) & dval[d]
                cand_nonign = cand & ~gt_ign
                pool = jnp.where(cand_nonign.any(), cand_nonign, cand)
                vals = jnp.where(pool, row, -jnp.inf)
                m = num_gt - 1 - jnp.argmax(vals[::-1])  # last argmax: later gt wins ties
                matched = pool.any()
                gt_matched = jnp.where(matched, gt_matched.at[m].set(True), gt_matched)
                return gt_matched, (matched, jnp.where(matched, gt_ign[m], False))

            _, (dm, dig) = lax.scan(step, jnp.zeros(num_gt, bool), jnp.arange(iou_i.shape[0]))
            return dm, dig

        dm, dig = jax.vmap(lambda gi: jax.vmap(lambda t: per_at(gi, t))(iou_thrs))(gt_ign_a)
        # (A, T, D, ...) -> unmatched dets outside the area range are ignored
        dig = dig | (~dm & det_out_a[:, None, :])
        return dm, dig, gt_ign_a

    return jax.vmap(per_image)(iou, det_valid, det_area, gt_valid, gt_area, gt_crowd)


class MAPInputs:
    """Per-image numpy views of the flat mAP state (reconstructed from cat rows)."""

    def __init__(
        self,
        det_boxes: List[np.ndarray],
        det_scores: List[np.ndarray],
        det_labels: List[np.ndarray],
        gt_boxes: List[np.ndarray],
        gt_labels: List[np.ndarray],
        gt_crowds: List[np.ndarray],
        gt_areas: List[np.ndarray],
        det_masks: Optional[List[np.ndarray]] = None,
        gt_masks: Optional[List[np.ndarray]] = None,
    ) -> None:
        self.det_boxes = det_boxes
        self.det_scores = det_scores
        self.det_labels = det_labels
        self.gt_boxes = gt_boxes
        self.gt_labels = gt_labels
        self.gt_crowds = gt_crowds
        self.gt_areas = gt_areas
        self.det_masks = det_masks
        self.gt_masks = gt_masks
        self.num_images = len(det_scores)

    def classes(self) -> List[int]:
        parts = [x for x in self.det_labels + self.gt_labels if x.size]
        if not parts:
            return []
        return np.unique(np.concatenate(parts)).astype(int).tolist()


def _det_area(inputs: MAPInputs, img: int, iou_type: str) -> np.ndarray:
    if iou_type == "segm":
        masks = inputs.det_masks[img]
        return masks.reshape(masks.shape[0], -1).sum(-1).astype(np.float64)
    b = inputs.det_boxes[img]
    return ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])).astype(np.float64)


def _gt_area(inputs: MAPInputs, img: int, iou_type: str) -> np.ndarray:
    provided = inputs.gt_areas[img].astype(np.float64)
    if iou_type == "segm":
        masks = inputs.gt_masks[img]
        computed = masks.reshape(masks.shape[0], -1).sum(-1).astype(np.float64)
    else:
        b = inputs.gt_boxes[img]
        computed = ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])).astype(np.float64)
    return np.where(provided > 0, provided, computed)


def evaluate_map(
    inputs: MAPInputs,
    iou_type: str,
    iou_thresholds: List[float],
    rec_thresholds: List[float],
    max_detection_thresholds: List[int],
    want_ious: bool = False,
) -> Dict[str, np.ndarray]:
    """Run matching + accumulation; returns COCOeval-shaped arrays.

    ``precision``: (T, R, K, A, M); ``recall``: (T, K, A, M); ``scores`` like
    precision; ``classes``: (K,). Entries stay -1 where a (class, area) has no
    non-ignored ground truth (COCOeval convention).
    """
    classes = inputs.classes()
    num_t, num_r = len(iou_thresholds), len(rec_thresholds)
    num_k, num_a, num_m = len(classes), len(_AREA_RANGES), len(max_detection_thresholds)
    precision = -np.ones((num_t, num_r, num_k, num_a, num_m))
    recall = -np.ones((num_t, num_k, num_a, num_m))
    scores_out = -np.ones((num_t, num_r, num_k, num_a, num_m))
    max_det = max_detection_thresholds[-1]
    iou_thrs_j = jnp.asarray(np.asarray(iou_thresholds, np.float32))
    area_ranges_j = jnp.asarray(_AREA_RANGES)
    rec_thrs = np.asarray(rec_thresholds, np.float64)
    ious_out: Dict = {}
    det_areas_all = [_det_area(inputs, i, iou_type) for i in range(inputs.num_images)]
    gt_areas_all = [_gt_area(inputs, i, iou_type) for i in range(inputs.num_images)]

    for k_idx, cls in enumerate(classes):
        # ---- gather per-image class-filtered, score-sorted, maxDet-truncated views
        per_img = []
        for i in range(inputs.num_images):
            d_sel = np.where(inputs.det_labels[i] == cls)[0]
            g_sel = np.where(inputs.gt_labels[i] == cls)[0]
            if d_sel.size == 0 and g_sel.size == 0:
                continue
            order = np.argsort(-inputs.det_scores[i][d_sel], kind="mergesort")[:max_det]
            per_img.append((i, d_sel[order], g_sel))
        if not per_img:
            continue

        num_i = len(per_img)
        dmax = _bucket(max((p[1].size for p in per_img), default=1) or 1)
        gmax = _bucket(max((p[2].size for p in per_img), default=1) or 1)
        ib = _bucket(num_i)

        iou_b = np.zeros((ib, dmax, gmax), np.float32)
        det_valid = np.zeros((ib, dmax), bool)
        det_area = np.zeros((ib, dmax), np.float32)
        det_score = np.full((ib, dmax), -np.inf, np.float32)
        gt_valid = np.zeros((ib, gmax), bool)
        gt_area = np.zeros((ib, gmax), np.float32)
        gt_crowd = np.zeros((ib, gmax), bool)

        for row, (i, d_sel, g_sel) in enumerate(per_img):
            nd, ng = d_sel.size, g_sel.size
            det_valid[row, :nd] = True
            det_score[row, :nd] = inputs.det_scores[i][d_sel]
            det_area[row, :nd] = det_areas_all[i][d_sel]
            gt_valid[row, :ng] = True
            gt_area[row, :ng] = gt_areas_all[i][g_sel]
            gt_crowd[row, :ng] = inputs.gt_crowds[i][g_sel].astype(bool)
            if nd and ng:
                if iou_type == "segm":
                    mat = np.asarray(
                        mask_iou_matrix(
                            jnp.asarray(inputs.det_masks[i][d_sel]),
                            jnp.asarray(inputs.gt_masks[i][g_sel]),
                            jnp.asarray(inputs.gt_crowds[i][g_sel].astype(bool)),
                        )
                    )
                else:
                    mat = np.asarray(
                        box_iou_matrix_crowd(
                            jnp.asarray(inputs.det_boxes[i][d_sel], jnp.float32),
                            jnp.asarray(inputs.gt_boxes[i][g_sel], jnp.float32),
                            jnp.asarray(inputs.gt_crowds[i][g_sel].astype(bool)),
                        )
                    )
                iou_b[row, :nd, :ng] = mat
                if want_ious:
                    ious_out[(i, cls)] = mat
            elif want_ious:
                ious_out[(i, cls)] = np.zeros((nd, ng), np.float32)

        dm, dig, gt_ign = _match_kernel(
            jnp.asarray(iou_b),
            jnp.asarray(det_valid),
            jnp.asarray(det_area),
            jnp.asarray(gt_valid),
            jnp.asarray(gt_area),
            jnp.asarray(gt_crowd),
            iou_thrs_j,
            area_ranges_j,
        )
        dm = np.asarray(dm)[:num_i]
        dig = np.asarray(dig)[:num_i]
        gt_ign = np.asarray(gt_ign)[:num_i]
        det_valid = det_valid[:num_i]
        det_score = det_score[:num_i]
        gt_valid_n = gt_valid[:num_i]

        # ---- accumulate (COCOeval.accumulate semantics)
        pos_in_img = np.broadcast_to(np.arange(dmax)[None, :], det_score.shape)
        for a_idx in range(num_a):
            npig = int((~gt_ign[:, a_idx, :] & gt_valid_n).sum())
            if npig == 0:
                continue
            dm_a = np.ascontiguousarray(dm[:, a_idx, :, :].transpose(1, 0, 2).reshape(num_t, -1))
            dig_a = np.ascontiguousarray(dig[:, a_idx, :, :].transpose(1, 0, 2).reshape(num_t, -1))
            for m_idx, mdet in enumerate(max_detection_thresholds):
                sel = det_valid & (pos_in_img < mdet)  # (I, D)
                flat_scores = np.where(sel, det_score, -np.inf).reshape(-1)
                order = np.argsort(-flat_scores, kind="mergesort")
                nd = int(sel.sum())
                ord_nd = order[:nd]
                scores_sorted = flat_scores[ord_nd]
                dm_f = dm_a[:, ord_nd]
                dig_f = dig_a[:, ord_nd]
                tps = dm_f & ~dig_f
                fps = ~dm_f & ~dig_f
                tp_sum = np.cumsum(tps, axis=1, dtype=np.float64)
                fp_sum = np.cumsum(fps, axis=1, dtype=np.float64)
                for t_idx in range(num_t):
                    tp, fp = tp_sum[t_idx], fp_sum[t_idx]
                    rc = tp / npig
                    pr = tp / (fp + tp + np.spacing(1))
                    recall[t_idx, k_idx, a_idx, m_idx] = rc[-1] if nd else 0.0
                    q = np.zeros(num_r)
                    ss = np.zeros(num_r)
                    if nd:
                        pr_env = np.maximum.accumulate(pr[::-1])[::-1]
                        inds = np.searchsorted(rc, rec_thrs, side="left")
                        valid = inds < nd
                        q[valid] = pr_env[inds[valid]]
                        ss[valid] = scores_sorted[inds[valid]]
                    precision[t_idx, :, k_idx, a_idx, m_idx] = q
                    scores_out[t_idx, :, k_idx, a_idx, m_idx] = ss

    out = {
        "precision": precision,
        "recall": recall,
        "scores": scores_out,
        "classes": np.asarray(classes, np.int32),
    }
    if want_ious:
        out["ious"] = ious_out
    return out


def summarize(
    precision: np.ndarray,
    recall: np.ndarray,
    iou_thresholds: List[float],
    max_detection_thresholds: List[int],
    class_idx: Optional[int] = None,
) -> Dict[str, float]:
    """COCOeval.summarize: means over entries > -1, -1 when empty."""

    def _mean(arr: np.ndarray) -> float:
        vals = arr[arr > -1]
        return float(vals.mean()) if vals.size else -1.0

    k = slice(None) if class_idx is None else slice(class_idx, class_idx + 1)
    last_m = len(max_detection_thresholds) - 1
    res = {
        "map": _mean(precision[:, :, k, 0, last_m]),
        "map_small": _mean(precision[:, :, k, 1, last_m]),
        "map_medium": _mean(precision[:, :, k, 2, last_m]),
        "map_large": _mean(precision[:, :, k, 3, last_m]),
        "mar_small": _mean(recall[:, k, 1, last_m]),
        "mar_medium": _mean(recall[:, k, 2, last_m]),
        "mar_large": _mean(recall[:, k, 3, last_m]),
    }
    res["map_50"] = (
        _mean(precision[iou_thresholds.index(0.5), :, k, 0, last_m]) if 0.5 in iou_thresholds else -1.0
    )
    res["map_75"] = (
        _mean(precision[iou_thresholds.index(0.75), :, k, 0, last_m]) if 0.75 in iou_thresholds else -1.0
    )
    for m_idx, mdet in enumerate(max_detection_thresholds):
        res[f"mar_{mdet}"] = _mean(recall[:, k, 0, m_idx])
    return res
