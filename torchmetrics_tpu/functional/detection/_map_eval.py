"""COCO mAP evaluation engine — pure JAX matcher + vectorized accumulation.

Re-implements the COCOeval algorithm (the reference delegates to the pycocotools C
extension through ``detection/helpers.py:152`` and keeps a pure-torch template at
``detection/_mean_ap.py:149``) as a TPU-first pipeline:

1. per-image IoU matrices (bbox: ``_box_ops`` pairwise kernels; segm: one
   pixel-flattened matmul per image — MXU work),
2. a **batched greedy matcher**: ``lax.scan`` over score-sorted detections, vmapped
   over IoU thresholds x area ranges x images — the reference's four nested Python
   loops (``_mean_ap.py:598-605``) collapse into one XLA call per class,
3. numpy accumulation: global stable score sort, cumsum TP/FP, precision envelope
   (reversed running max), 101-point interpolation via ``searchsorted`` — identical
   semantics to COCOeval.accumulate, including the crowd/ignore and tie-breaking
   rules (last ground-truth wins equal IoU; ignored gts only matchable when no
   non-ignored gt clears the threshold).

Matching runs in float32 (TPU-native); pycocotools uses float64, so IoU values that
tie *exactly* at a threshold boundary in f64 may resolve differently — empirically
immaterial on real boxes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


# COCO area ranges: all / small / medium / large (reference _mean_ap.py:351-356)
_AREA_RANGES = np.array(
    [[0.0, 1e5**2], [0.0, 32.0**2], [32.0**2, 96.0**2], [96.0**2, 1e5**2]], np.float32
)
_AREA_KEYS = ("all", "small", "medium", "large")
_ROW_BLOCK = 4096  # matcher rows per XLA call (memory/compile trade-off)


def mask_iou_matrix(dets: jnp.ndarray, gts: jnp.ndarray, crowd: jnp.ndarray) -> jnp.ndarray:
    """Pairwise mask IoU ``(D,H,W) x (G,H,W) -> (D,G)`` with COCO crowd semantics
    (crowd gt: denominator is the detection area). Pixel intersection is one matmul."""
    d = dets.reshape(dets.shape[0], -1).astype(jnp.float32)
    g = gts.reshape(gts.shape[0], -1).astype(jnp.float32)
    inter = d @ g.T
    d_area = d.sum(-1)[:, None]
    union = d_area + g.sum(-1)[None, :] - inter
    denom = jnp.where(crowd[None, :], d_area, union)
    return jnp.where(denom > 0, inter / jnp.where(denom > 0, denom, 1.0), 0.0)


def _box_iou_np(det: np.ndarray, gt: np.ndarray, crowd: np.ndarray) -> np.ndarray:
    """Host pairwise crowd-IoU for one (class, image) cell — small matrices, where a
    per-cell device dispatch would dominate at COCO scale."""
    det = det.astype(np.float64)
    gt = gt.astype(np.float64)
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    det_area = ((det[:, 2] - det[:, 0]) * (det[:, 3] - det[:, 1]))[:, None]
    gt_area = ((gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1]))[None, :]
    union = det_area + gt_area - inter
    denom = np.where(crowd[None, :], det_area, union)
    return np.where(denom > 0, inter / np.where(denom > 0, denom, 1.0), 0.0).astype(np.float32)


def _bucket(n: int, floor: int = 4) -> int:
    """Round up to the next power of two (compile-cache friendliness)."""
    b = floor
    while b < n:
        b *= 2
    return b


@jax.jit
def _match_kernel(
    iou: jnp.ndarray,  # (I, D, G) crowd-adjusted IoU
    det_valid: jnp.ndarray,  # (I, D) bool, score-sorted per image
    det_area: jnp.ndarray,  # (I, D)
    gt_valid: jnp.ndarray,  # (I, G) bool
    gt_area: jnp.ndarray,  # (I, G)
    gt_crowd: jnp.ndarray,  # (I, G) bool
    iou_thrs: jnp.ndarray,  # (T,)
    area_ranges: jnp.ndarray,  # (A, 2)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Greedy COCO matching, batched over images x area ranges x IoU thresholds.

    Returns ``det_match (I,A,T,D)``, ``det_ignore (I,A,T,D)``, ``gt_ignore (I,A,G)``.
    """
    num_gt = iou.shape[-1]

    def per_image(iou_i, dval, darea, gval, garea, gcrowd):
        gt_ign_a = (
            (garea[None, :] < area_ranges[:, :1])
            | (garea[None, :] > area_ranges[:, 1:])
            | gcrowd[None, :]
            | ~gval[None, :]
        )  # (A, G)
        det_out_a = (darea[None, :] < area_ranges[:, :1]) | (darea[None, :] > area_ranges[:, 1:])  # (A, D)

        def per_at(gt_ign, thr):
            thr_eff = jnp.minimum(thr, 1.0 - 1e-10)

            def step(gt_matched, d):
                row = iou_i[d]
                cand = gval & (~gt_matched | gcrowd) & (row >= thr_eff) & dval[d]
                cand_nonign = cand & ~gt_ign
                pool = jnp.where(cand_nonign.any(), cand_nonign, cand)
                vals = jnp.where(pool, row, -jnp.inf)
                m = num_gt - 1 - jnp.argmax(vals[::-1])  # last argmax: later gt wins ties
                matched = pool.any()
                gt_matched = jnp.where(matched, gt_matched.at[m].set(True), gt_matched)
                return gt_matched, (matched, jnp.where(matched, gt_ign[m], False))

            _, (dm, dig) = lax.scan(step, jnp.zeros(num_gt, bool), jnp.arange(iou_i.shape[0]))
            return dm, dig

        dm, dig = jax.vmap(lambda gi: jax.vmap(lambda t: per_at(gi, t))(iou_thrs))(gt_ign_a)
        # (A, T, D, ...) -> unmatched dets outside the area range are ignored
        dig = dig | (~dm & det_out_a[:, None, :])
        return dm, dig, gt_ign_a

    return jax.vmap(per_image)(iou, det_valid, det_area, gt_valid, gt_area, gt_crowd)


class MAPInputs:
    """Per-image numpy views of the flat mAP state (reconstructed from cat rows)."""

    def __init__(
        self,
        det_boxes: List[np.ndarray],
        det_scores: List[np.ndarray],
        det_labels: List[np.ndarray],
        gt_boxes: List[np.ndarray],
        gt_labels: List[np.ndarray],
        gt_crowds: List[np.ndarray],
        gt_areas: List[np.ndarray],
        det_masks: Optional[List[np.ndarray]] = None,
        gt_masks: Optional[List[np.ndarray]] = None,
    ) -> None:
        self.det_boxes = det_boxes
        self.det_scores = det_scores
        self.det_labels = det_labels
        self.gt_boxes = gt_boxes
        self.gt_labels = gt_labels
        self.gt_crowds = gt_crowds
        self.gt_areas = gt_areas
        self.det_masks = det_masks
        self.gt_masks = gt_masks
        self.num_images = len(det_scores)

    def classes(self) -> List[int]:
        parts = [x for x in self.det_labels + self.gt_labels if x.size]
        if not parts:
            return []
        return np.unique(np.concatenate(parts)).astype(int).tolist()


def _det_area(inputs: MAPInputs, img: int, iou_type: str) -> np.ndarray:
    if iou_type == "segm":
        masks = inputs.det_masks[img]
        return masks.reshape(masks.shape[0], -1).sum(-1).astype(np.float64)
    b = inputs.det_boxes[img]
    return ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])).astype(np.float64)


def _gt_area(inputs: MAPInputs, img: int, iou_type: str) -> np.ndarray:
    provided = inputs.gt_areas[img].astype(np.float64)
    if iou_type == "segm":
        masks = inputs.gt_masks[img]
        computed = masks.reshape(masks.shape[0], -1).sum(-1).astype(np.float64)
    else:
        b = inputs.gt_boxes[img]
        computed = ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])).astype(np.float64)
    return np.where(provided > 0, provided, computed)


def evaluate_map(
    inputs: MAPInputs,
    iou_type: str,
    iou_thresholds: List[float],
    rec_thresholds: List[float],
    max_detection_thresholds: List[int],
    want_ious: bool = False,
) -> Dict[str, np.ndarray]:
    """Run matching + accumulation; returns COCOeval-shaped arrays.

    ``precision``: (T, R, K, A, M); ``recall``: (T, K, A, M); ``scores`` like
    precision; ``classes``: (K,). Entries stay -1 where a (class, area) has no
    non-ignored ground truth (COCOeval convention).
    """
    classes = inputs.classes()
    num_t, num_r = len(iou_thresholds), len(rec_thresholds)
    num_k, num_a, num_m = len(classes), len(_AREA_RANGES), len(max_detection_thresholds)
    precision = -np.ones((num_t, num_r, num_k, num_a, num_m))
    recall = -np.ones((num_t, num_k, num_a, num_m))
    scores_out = -np.ones((num_t, num_r, num_k, num_a, num_m))
    max_det = max_detection_thresholds[-1]
    iou_thrs_j = jnp.asarray(np.asarray(iou_thresholds, np.float32))
    area_ranges_j = jnp.asarray(_AREA_RANGES)
    rec_thrs = np.asarray(rec_thresholds, np.float64)
    ious_out: Dict = {}
    det_areas_all = [_det_area(inputs, i, iou_type) for i in range(inputs.num_images)]
    gt_areas_all = [_gt_area(inputs, i, iou_type) for i in range(inputs.num_images)]

    # ---- flatten every (class, image) evaluation into ONE matcher batch: matching is
    # independent per pair, so classes ride the same vmapped leading axis — one XLA
    # compile per padded bucket instead of one per class
    rows: List[Tuple[int, int, np.ndarray, np.ndarray]] = []  # (k_idx, img, d_sel, g_sel)
    class_rows: List[List[int]] = [[] for _ in classes]
    for k_idx, cls in enumerate(classes):
        for i in range(inputs.num_images):
            d_sel = np.where(inputs.det_labels[i] == cls)[0]
            g_sel = np.where(inputs.gt_labels[i] == cls)[0]
            if d_sel.size == 0 and g_sel.size == 0:
                continue
            order = np.argsort(-inputs.det_scores[i][d_sel], kind="mergesort")[:max_det]
            class_rows[k_idx].append(len(rows))
            rows.append((k_idx, i, d_sel[order], g_sel))
    if not rows:
        return {
            "precision": precision, "recall": recall, "scores": scores_out,
            "classes": np.asarray(classes, np.int32), **({"ious": ious_out} if want_ious else {}),
        }

    num_rows = len(rows)
    dmax = _bucket(max((r[2].size for r in rows), default=1) or 1)
    gmax = _bucket(max((r[3].size for r in rows), default=1) or 1)

    # process the row batch in fixed-size blocks: one compile per (block, dmax, gmax)
    # bucket while bounding peak memory (a COCO-scale eval would otherwise stage a
    # multi-GB (rows, dmax, gmax) IoU tensor at once)
    dm_all = np.zeros((num_rows, len(_AREA_RANGES), num_t, dmax), bool)
    dig_all = np.zeros_like(dm_all)
    gt_ign_all = np.zeros((num_rows, len(_AREA_RANGES), gmax), bool)
    det_valid = np.zeros((num_rows, dmax), bool)
    det_score_b = np.full((num_rows, dmax), -np.inf, np.float32)
    gt_valid_b = np.zeros((num_rows, gmax), bool)

    for block_start in range(0, num_rows, _ROW_BLOCK):
        block = rows[block_start : block_start + _ROW_BLOCK]
        rb = _ROW_BLOCK if num_rows > _ROW_BLOCK else _bucket(len(block))
        iou_b = np.zeros((rb, dmax, gmax), np.float32)
        bdet_valid = np.zeros((rb, dmax), bool)
        bdet_area = np.zeros((rb, dmax), np.float32)
        bgt_valid = np.zeros((rb, gmax), bool)
        bgt_area = np.zeros((rb, gmax), np.float32)
        bgt_crowd = np.zeros((rb, gmax), bool)

        for off, (k_idx, i, d_sel, g_sel) in enumerate(block):
            nd, ng = d_sel.size, g_sel.size
            row = block_start + off
            bdet_valid[off, :nd] = True
            det_valid[row, :nd] = True
            det_score_b[row, :nd] = inputs.det_scores[i][d_sel]
            bdet_area[off, :nd] = det_areas_all[i][d_sel]
            bgt_valid[off, :ng] = True
            gt_valid_b[row, :ng] = True
            bgt_area[off, :ng] = gt_areas_all[i][g_sel]
            bgt_crowd[off, :ng] = inputs.gt_crowds[i][g_sel].astype(bool)
            if nd and ng:
                if iou_type == "segm":
                    mat = np.asarray(
                        mask_iou_matrix(
                            jnp.asarray(inputs.det_masks[i][d_sel]),
                            jnp.asarray(inputs.gt_masks[i][g_sel]),
                            jnp.asarray(inputs.gt_crowds[i][g_sel].astype(bool)),
                        )
                    )
                else:
                    mat = _box_iou_np(inputs.det_boxes[i][d_sel], inputs.gt_boxes[i][g_sel],
                                      inputs.gt_crowds[i][g_sel].astype(bool))
                iou_b[off, :nd, :ng] = mat
                if want_ious:
                    ious_out[(i, int(classes[k_idx]))] = mat
            elif want_ious:
                ious_out[(i, int(classes[k_idx]))] = np.zeros((nd, ng), np.float32)

        dm_b, dig_b, gt_ign_b = _match_kernel(
            jnp.asarray(iou_b),
            jnp.asarray(bdet_valid),
            jnp.asarray(bdet_area),
            jnp.asarray(bgt_valid),
            jnp.asarray(bgt_area),
            jnp.asarray(bgt_crowd),
            iou_thrs_j,
            area_ranges_j,
        )
        n = len(block)
        dm_all[block_start : block_start + n] = np.asarray(dm_b)[:n]
        dig_all[block_start : block_start + n] = np.asarray(dig_b)[:n]
        gt_ign_all[block_start : block_start + n] = np.asarray(gt_ign_b)[:n]

    for k_idx, cls in enumerate(classes):
        sel_rows = class_rows[k_idx]
        if not sel_rows:
            continue
        dm = dm_all[sel_rows]
        dig = dig_all[sel_rows]
        gt_ign = gt_ign_all[sel_rows]
        det_valid_c = det_valid[sel_rows]
        det_score = det_score_b[sel_rows]
        gt_valid_n = gt_valid_b[sel_rows]

        # ---- accumulate (COCOeval.accumulate semantics)
        pos_in_img = np.broadcast_to(np.arange(dmax)[None, :], det_score.shape)
        for a_idx in range(num_a):
            npig = int((~gt_ign[:, a_idx, :] & gt_valid_n).sum())
            if npig == 0:
                continue
            dm_a = np.ascontiguousarray(dm[:, a_idx, :, :].transpose(1, 0, 2).reshape(num_t, -1))
            dig_a = np.ascontiguousarray(dig[:, a_idx, :, :].transpose(1, 0, 2).reshape(num_t, -1))
            for m_idx, mdet in enumerate(max_detection_thresholds):
                sel = det_valid_c & (pos_in_img < mdet)  # (I, D)
                flat_scores = np.where(sel, det_score, -np.inf).reshape(-1)
                order = np.argsort(-flat_scores, kind="mergesort")
                nd = int(sel.sum())
                ord_nd = order[:nd]
                scores_sorted = flat_scores[ord_nd]
                dm_f = dm_a[:, ord_nd]
                dig_f = dig_a[:, ord_nd]
                tps = dm_f & ~dig_f
                fps = ~dm_f & ~dig_f
                tp_sum = np.cumsum(tps, axis=1, dtype=np.float64)
                fp_sum = np.cumsum(fps, axis=1, dtype=np.float64)
                for t_idx in range(num_t):
                    tp, fp = tp_sum[t_idx], fp_sum[t_idx]
                    rc = tp / npig
                    pr = tp / (fp + tp + np.spacing(1))
                    recall[t_idx, k_idx, a_idx, m_idx] = rc[-1] if nd else 0.0
                    q = np.zeros(num_r)
                    ss = np.zeros(num_r)
                    if nd:
                        pr_env = np.maximum.accumulate(pr[::-1])[::-1]
                        inds = np.searchsorted(rc, rec_thrs, side="left")
                        valid = inds < nd
                        q[valid] = pr_env[inds[valid]]
                        ss[valid] = scores_sorted[inds[valid]]
                    precision[t_idx, :, k_idx, a_idx, m_idx] = q
                    scores_out[t_idx, :, k_idx, a_idx, m_idx] = ss

    out = {
        "precision": precision,
        "recall": recall,
        "scores": scores_out,
        "classes": np.asarray(classes, np.int32),
    }
    if want_ious:
        out["ious"] = ious_out
    return out


def summarize(
    precision: np.ndarray,
    recall: np.ndarray,
    iou_thresholds: List[float],
    max_detection_thresholds: List[int],
    class_idx: Optional[int] = None,
) -> Dict[str, float]:
    """COCOeval.summarize: means over entries > -1, -1 when empty."""

    def _mean(arr: np.ndarray) -> float:
        vals = arr[arr > -1]
        return float(vals.mean()) if vals.size else -1.0

    k = slice(None) if class_idx is None else slice(class_idx, class_idx + 1)
    last_m = len(max_detection_thresholds) - 1
    res = {
        "map": _mean(precision[:, :, k, 0, last_m]),
        "map_small": _mean(precision[:, :, k, 1, last_m]),
        "map_medium": _mean(precision[:, :, k, 2, last_m]),
        "map_large": _mean(precision[:, :, k, 3, last_m]),
        "mar_small": _mean(recall[:, k, 1, last_m]),
        "mar_medium": _mean(recall[:, k, 2, last_m]),
        "mar_large": _mean(recall[:, k, 3, last_m]),
    }
    res["map_50"] = (
        _mean(precision[iou_thresholds.index(0.5), :, k, 0, last_m]) if 0.5 in iou_thresholds else -1.0
    )
    res["map_75"] = (
        _mean(precision[iou_thresholds.index(0.75), :, k, 0, last_m]) if 0.75 in iou_thresholds else -1.0
    )
    for m_idx, mdet in enumerate(max_detection_thresholds):
        res[f"mar_{mdet}"] = _mean(recall[:, k, 0, m_idx])
    return res
