"""Complete IoU — functional (reference ``functional/detection/ciou.py:52``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ._box_ops import complete_box_iou_matrix
from .iou import _family_compute, _family_update


def _ciou_update(preds, target, iou_threshold: Optional[float], replacement_val: float = 0) -> jnp.ndarray:
    return _family_update(preds, target, iou_threshold, replacement_val, complete_box_iou_matrix)


def _ciou_compute(iou: jnp.ndarray, aggregate: bool = True) -> jnp.ndarray:
    return _family_compute(iou, aggregate)


def complete_intersection_over_union(
    preds: jnp.ndarray,
    target: jnp.ndarray,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> jnp.ndarray:
    """Compute CIoU between two sets of xyxy boxes."""
    iou = _ciou_update(preds, target, iou_threshold, replacement_val)
    return _ciou_compute(iou, aggregate)
