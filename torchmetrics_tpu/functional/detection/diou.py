"""Distance IoU — functional (reference ``functional/detection/diou.py:52``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ._box_ops import distance_box_iou_matrix
from .iou import _family_compute, _family_update


def _diou_update(preds, target, iou_threshold: Optional[float], replacement_val: float = 0) -> jnp.ndarray:
    return _family_update(preds, target, iou_threshold, replacement_val, distance_box_iou_matrix)


def _diou_compute(iou: jnp.ndarray, aggregate: bool = True) -> jnp.ndarray:
    return _family_compute(iou, aggregate)


def distance_intersection_over_union(
    preds: jnp.ndarray,
    target: jnp.ndarray,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> jnp.ndarray:
    """Compute DIoU between two sets of xyxy boxes.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import distance_intersection_over_union
        >>> preds = jnp.asarray([[296.55, 93.96, 314.97, 152.79], [328.94, 97.05, 342.49, 122.98]])
        >>> target = jnp.asarray([[300.00, 100.00, 315.00, 150.00], [330.00, 100.00, 350.00, 125.00]])
        >>> distance_intersection_over_union(preds, target)
        Array(0.5884219, dtype=float32)
    """
    iou = _diou_update(preds, target, iou_threshold, replacement_val)
    return _diou_compute(iou, aggregate)
