"""Distance IoU — functional (reference ``functional/detection/diou.py:52``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ._box_ops import distance_box_iou_matrix
from .iou import _family_compute, _family_update


def _diou_update(preds, target, iou_threshold: Optional[float], replacement_val: float = 0) -> jnp.ndarray:
    return _family_update(preds, target, iou_threshold, replacement_val, distance_box_iou_matrix)


def _diou_compute(iou: jnp.ndarray, aggregate: bool = True) -> jnp.ndarray:
    return _family_compute(iou, aggregate)


def distance_intersection_over_union(
    preds: jnp.ndarray,
    target: jnp.ndarray,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> jnp.ndarray:
    """Compute DIoU between two sets of xyxy boxes."""
    iou = _diou_update(preds, target, iou_threshold, replacement_val)
    return _diou_compute(iou, aggregate)
