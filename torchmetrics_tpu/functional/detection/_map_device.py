"""Device-resident COCO mAP — one jit-compiled program from padded state to summary.

The host evaluator (``_map_eval.py``) stays the parity oracle; this module is the
re-homed escape hatch: the WHOLE evaluation (greedy matcher + COCOeval.accumulate +
summarize) is a single XLA program over a fixed-capacity padded row state, so the
telemetry/reliability/AOT planes apply to mAP compute exactly like any other dispatch
tag ("mapeval"), and a warm boot loads the 3s-to-derive evaluator from the AOT cache.

Layout (built host-side in ``detection/helpers.py:_build_device_rows``):

- ``det_rows`` ``(capacity, 7)`` f32: ``[img, label, score, x1, y1, x2, y2]``
- ``gt_rows``  ``(capacity, 8)`` f32: ``[img, label, iscrowd, area, x1, y1, x2, y2]``
- ``det_n`` / ``gt_n`` / ``img_n`` i32 scalars — valid-row cursors

Algorithm, fully vectorized except one dynamic-trip-count loop:

1. sort gts by cell key ``img * K + label`` (stable: in-cell order = input order, the
   pycocotools tie-break order); each det finds its cell's gt window via two
   ``searchsorted`` calls — windows are bounded by ``gt_group_cap`` (validated at
   update time), so per-det gt views are a static ``(D, Gc)`` gather,
2. per-cell score ranks from one lexsort + first-occurrence ``searchsorted``; dets
   that can match anything (valid, inside maxDet, non-empty window) are compacted to
   the front, and a ``lax.fori_loop`` with a DYNAMIC trip count walks only those —
   the body mirrors ``_map_eval._match_kernel`` (candidate pool, prefer-non-ignored,
   last-argmax tie-break) over an ``(A, T, Gc)`` window slice,
3. accumulation as segment ops: one global ``(class, -score, img, rank)`` lexsort,
   per-class TP/FP cumsums by subtracting class-start prefixes, 101-point PR
   interpolation as a scatter-max into ``(class, rec_bin)`` buckets + a reversed
   ``associative_scan`` max (the precision envelope and the ``searchsorted`` gather
   collapse into one suffix-max), and masked means reproduce ``summarize``.

Parity note: threshold eligibility is resolved in f32 (the state dtype) against the
f32-quantized thresholds, where the host oracle resolves f64 IoU vs f64 thresholds —
results are bit-identical except for IoUs within f32 rounding of a threshold
(tests/test_map_device.py fuzzes parity to 1e-4 on summary stats).

The matcher body deliberately avoids ``.at[].set`` scatters inside the loop — that
formulation miscompiles under XLA for row batches >= 64 (see ``_map_eval.py``); all
loop write-backs are ``dynamic_update_slice`` + the one-hot|or formulation. Scatters
OUTSIDE the loop (the PR-bucket scatter-max, the state-merge row append) are fine.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ._map_eval import _AREA_RANGES

_INT32_MAX = np.iinfo(np.int32).max


def build_mapeval_program(
    capacity: int,
    num_classes: int,
    gt_group_cap: int,
    iou_thresholds: List[float],
    rec_thresholds: List[float],
    max_detection_thresholds: List[int],
) -> Callable:
    """The raw (un-jitted) "mapeval" program for one (capacity, classes) signature.

    Returns ``fn(tensors, n) -> {summary scalars, per-class arrays, present mask}``
    with the ``(t, n)`` calling convention every dispatch tag shares (``n`` — the
    device update counter — is unused; compute is a pure read of the state).
    """
    D, K, Gc = int(capacity), int(num_classes), int(gt_group_cap)
    A = int(_AREA_RANGES.shape[0])
    T, R, M = len(iou_thresholds), len(rec_thresholds), len(max_detection_thresholds)
    mdet_last = int(max_detection_thresholds[-1])
    # pycocotools clamps each threshold to min(t, 1 - 1e-10) in f64 so an exact-1.0
    # IoU clears a 1.0 threshold; quantizing the clamped value to f32 keeps that
    # behavior (f32(1 - 1e-10) == 1.0 and f32 IoUs saturate at 1.0)
    thrs_np = np.minimum(np.asarray(iou_thresholds, np.float64), 1.0 - 1e-10).astype(np.float32)
    # summaries are means over all R bins, so sorting user-supplied recall
    # thresholds is observation-free (extended_summary is host-evaluator-only)
    rec_np = np.sort(np.asarray(rec_thresholds, np.float32))
    mdets_np = np.asarray(max_detection_thresholds, np.int32)
    t50 = iou_thresholds.index(0.5) if 0.5 in iou_thresholds else None
    t75 = iou_thresholds.index(0.75) if 0.75 in iou_thresholds else None
    eps = np.float32(np.spacing(np.float64(1.0)))  # COCOeval's precision denominator guard

    def fn(tensors: Dict[str, jnp.ndarray], n: Any) -> Dict[str, jnp.ndarray]:
        del n
        det, gt = tensors["det_rows"], tensors["gt_rows"]
        det_n, gt_n = tensors["det_n"], tensors["gt_n"]
        thrs = jnp.asarray(thrs_np)
        rec_t = jnp.asarray(rec_np)
        areas = jnp.asarray(_AREA_RANGES)  # (A, 2)
        slot = jnp.arange(D, dtype=jnp.int32)

        d_img = det[:, 0].astype(jnp.int32)
        d_lab = det[:, 1].astype(jnp.int32)
        d_score = det[:, 2]
        d_box = det[:, 3:7]
        g_img = gt[:, 0].astype(jnp.int32)
        g_lab = gt[:, 1].astype(jnp.int32)
        g_crowd = gt[:, 2] > 0
        g_area_user = gt[:, 3]
        g_box = gt[:, 4:8]
        dvalid = slot < det_n
        gvalid = slot < gt_n

        d_area = (d_box[:, 2] - d_box[:, 0]) * (d_box[:, 3] - d_box[:, 1])
        g_area_box = (g_box[:, 2] - g_box[:, 0]) * (g_box[:, 3] - g_box[:, 1])
        g_area = jnp.where(g_area_user > 0, g_area_user, g_area_box)

        # ---- gts sorted by cell; stable, so in-cell order stays input order (the
        # pycocotools last-argmax tie-break depends on it)
        g_key = jnp.where(gvalid, g_img * K + g_lab, _INT32_MAX)
        g_order = jnp.argsort(g_key)  # jnp.argsort is stable
        gs_key = g_key[g_order]
        gs_valid = gvalid[g_order]
        gs_lab = jnp.where(gs_valid, g_lab[g_order], K)
        gs_crowd = g_crowd[g_order] & gs_valid
        gs_area = g_area[g_order]
        gs_box = g_box[g_order]

        # ---- each det's gt window [glo, ghi) in the sorted order
        d_key = jnp.where(dvalid, d_img * K + d_lab, _INT32_MAX)
        glo = jnp.searchsorted(gs_key, d_key, side="left").astype(jnp.int32)
        ghi = jnp.searchsorted(gs_key, d_key, side="right").astype(jnp.int32)

        # ---- per-cell score rank (stable descending — COCOeval's det order)
        neg_score = jnp.where(dvalid, -d_score, jnp.inf)
        d_order = jnp.lexsort((slot, neg_score, d_key))
        key_sorted = d_key[d_order]
        cell_start = jnp.searchsorted(key_sorted, key_sorted, side="left")
        rank_sorted = (slot - cell_start).astype(jnp.int32)

        # ---- compact matchable dets to the front, keeping (cell, -score) order
        glo_sorted, ghi_sorted = glo[d_order], ghi[d_order]
        part = dvalid[d_order] & (rank_sorted < mdet_last) & (ghi_sorted > glo_sorted)
        comp = jnp.argsort(~part)
        perm = d_order[comp]
        n_match = part.sum().astype(jnp.int32)

        img_c = d_img[perm]
        valid_c = dvalid[perm]
        lab_c = jnp.where(valid_c, d_lab[perm], K)
        score_c = d_score[perm]
        box_c = d_box[perm]
        area_c = d_area[perm]
        rank_c = rank_sorted[comp]
        glo_c, ghi_c = glo_sorted[comp], ghi_sorted[comp]

        # ---- windowed gt views + crowd-adjusted pairwise IoU, outside the loop
        widx = glo_c[:, None] + jnp.arange(Gc, dtype=jnp.int32)[None, :]
        w_in = widx < ghi_c[:, None]  # (D, Gc)
        widx_cl = jnp.minimum(widx, D - 1)
        wg_box = gs_box[widx_cl]  # (D, Gc, 4)
        wg_crowd = gs_crowd[widx_cl] & w_in
        wg_area = gs_area[widx_cl]

        lt = jnp.maximum(box_c[:, None, :2], wg_box[..., :2])
        rb = jnp.minimum(box_c[:, None, 2:], wg_box[..., 2:])
        wh = jnp.clip(rb - lt, 0.0, None)
        inter = wh[..., 0] * wh[..., 1]
        wg_box_area = (wg_box[..., 2] - wg_box[..., 0]) * (wg_box[..., 3] - wg_box[..., 1])
        union = area_c[:, None] + wg_box_area - inter
        denom = jnp.where(wg_crowd, area_c[:, None], union)
        w_iou = jnp.where(denom > 0, inter / jnp.where(denom > 0, denom, 1.0), 0.0)

        wg_ign = (
            (wg_area[:, None, :] < areas[None, :, 0:1])
            | (wg_area[:, None, :] > areas[None, :, 1:2])
            | wg_crowd[:, None, :]
            | ~w_in[:, None, :]
        )  # (D, A, Gc)
        det_out = (area_c[:, None] < areas[None, :, 0]) | (area_c[:, None] > areas[None, :, 1])

        # ---- greedy matcher: dynamic trip count, window-local state updates.
        # gmatch carries Gc slack so the window slice never clamps at the tail.
        gmatch0 = jnp.zeros((A, T, D + Gc), bool)
        dm0 = jnp.zeros((D, A, T), bool)
        dig0 = jnp.zeros((D, A, T), bool)

        def body(i, carry):
            gmatch, dm, dig = carry
            lo = glo_c[i]
            wi = w_iou[i]  # (Gc,)
            win, wcr, wig = w_in[i], wg_crowd[i], wg_ign[i]
            clr = wi[None, :] >= thrs[:, None]  # (T, Gc)
            mwin = lax.dynamic_slice(gmatch, (0, 0, lo), (A, T, Gc))
            cand = win[None, None, :] & (~mwin | wcr[None, None, :]) & clr[None, :, :]
            cand_ni = cand & ~wig[:, None, :]
            pool = jnp.where(cand_ni.any(-1, keepdims=True), cand_ni, cand)
            vals = jnp.where(pool, wi[None, None, :], -jnp.inf)
            m = Gc - 1 - jnp.argmax(vals[..., ::-1], axis=-1)  # last argmax: later gt wins ties
            hit = pool.any(-1)  # (A, T)
            oh = jax.nn.one_hot(m, Gc, dtype=bool) & hit[..., None]
            gmatch = lax.dynamic_update_slice(gmatch, mwin | oh, (0, 0, lo))
            ign_of_m = (oh & wig[:, None, :]).any(-1)
            dm = lax.dynamic_update_slice(dm, hit[None], (i, 0, 0))
            dig = lax.dynamic_update_slice(dig, ign_of_m[None], (i, 0, 0))
            return gmatch, dm, dig

        _, dm, dig = lax.fori_loop(0, n_match, body, (gmatch0, dm0, dig0))
        dig = dig | (~dm & det_out[:, :, None])  # unmatched dets outside the range: ignored

        # ---- COCOeval.accumulate: one global sort, per-class segment cumsums
        sel_lab = jnp.where(valid_c & (rank_c < mdet_last), lab_c, K)
        acc = jnp.lexsort((rank_c, img_c, jnp.where(sel_lab < K, -score_c, jnp.inf), sel_lab))
        lab_s = sel_lab[acc]
        rank_s = rank_c[acc]
        dm_s, dig_s = dm[acc], dig[acc]

        mdets = jnp.asarray(mdets_np)
        sel = (lab_s[:, None] < K) & (rank_s[:, None] < mdets[None, :])  # (D, M)
        cls_start = jnp.searchsorted(lab_s, jnp.arange(K, dtype=jnp.int32), side="left").astype(jnp.int32)
        cls_end = jnp.searchsorted(lab_s, jnp.arange(K, dtype=jnp.int32), side="right").astype(jnp.int32)
        lab_cl = jnp.minimum(lab_s, K - 1)

        # summarize() reads precision at the LAST maxDet only, and the extended
        # precision/scores tensors never leave the device — so the whole PR-curve
        # pipeline runs on (D, A, T), M-free (3x less traffic than the host layout)
        tps = (dm_s & ~dig_s).astype(jnp.float32)  # (D, A, T); every segment row
        fps = (~dm_s & ~dig_s).astype(jnp.float32)  # already has rank < mdet_last
        tp_cum_g = jnp.cumsum(tps, axis=0)
        fp_cum_g = jnp.cumsum(fps, axis=0)
        has_prefix = (cls_start > 0)[:, None, None]
        base_tp = jnp.where(has_prefix, tp_cum_g[jnp.maximum(cls_start - 1, 0)], 0.0)  # (K, A, T)
        base_fp = jnp.where(has_prefix, fp_cum_g[jnp.maximum(cls_start - 1, 0)], 0.0)
        tp = tp_cum_g - base_tp[lab_cl]
        fp = fp_cum_g - base_fp[lab_cl]

        gs_ign = (
            (gs_area[:, None] < areas[None, :, 0])
            | (gs_area[:, None] > areas[None, :, 1])
            | gs_crowd[:, None]
        )  # (D, A)
        counted = (gs_valid[:, None] & ~gs_ign).astype(jnp.float32)
        npig = jax.ops.segment_sum(counted, gs_lab, num_segments=K + 1)[:K]  # (K, A)
        npig_d = npig[lab_cl]  # (D, A)
        rc = jnp.where(npig_d[:, :, None] > 0, tp / jnp.maximum(npig_d, 1.0)[:, :, None], 0.0)
        pr = tp / (tp + fp + eps)

        # precision envelope = suffix max of pr within each class segment (a flip +
        # forward segmented-max scan; XLA CPU serializes large scatters, so the
        # scatter-into-recall-bins formulation is ~6x slower than this)
        seg_end = jnp.concatenate([lab_s[:-1] != lab_s[1:], jnp.ones((1,), bool)])
        flag_r = seg_end[::-1][:, None, None]

        def seg_max(a, b):
            va, fa = a
            vb, fb = b
            return jnp.where(fb, vb, jnp.maximum(va, vb)), fa | fb

        env_r, _ = lax.associative_scan(seg_max, (pr[::-1], flag_r))
        pr_env = env_r[::-1]  # (D, A, T)

        # 101-point interpolation: rc is non-decreasing within a class segment, so
        # q[c, r] = pr_env[lower_bound(rc[seg_c], rec_thrs[r])] — one vectorized
        # binary search over (K, R, A, T) replaces the host's per-cell searchsorted
        lane = jnp.arange(A * T, dtype=jnp.int32).reshape(1, 1, A, T)
        rc_lin = rc.reshape(-1)
        lo = jnp.broadcast_to(cls_start[:, None, None, None], (K, R, A, T))
        hi = jnp.broadcast_to(cls_end[:, None, None, None], (K, R, A, T))
        thr = rec_t[None, :, None, None]
        for _ in range(max(D.bit_length(), 1)):
            mid = (lo + hi) // 2
            v = rc_lin[mid * (A * T) + lane]
            go_right = (v < thr) & (mid < hi)
            lo = jnp.where(go_right, mid + 1, lo)
            hi = jnp.where(go_right, hi, mid)
        found = lo < cls_end[:, None, None, None]
        q_idx = jnp.minimum(lo, D - 1) * (A * T) + lane
        q = jnp.where(found, pr_env.reshape(-1)[q_idx], 0.0)  # (K, R, A, T)

        tp_tot = jnp.stack(
            [
                jax.ops.segment_sum(
                    (dm_s & ~dig_s & sel[:, m, None, None]).astype(jnp.float32), lab_s, num_segments=K + 1
                )[:K]
                for m in range(M)
            ],
            axis=-1,
        )  # (K, A, T, M)
        nd_cnt = jax.ops.segment_sum(sel.astype(jnp.float32), lab_s, num_segments=K + 1)[:K]  # (K, M)
        valid_cell = npig > 0  # (K, A)
        rec_raw = jnp.where(
            nd_cnt[:, None, None, :] > 0, tp_tot / jnp.maximum(npig, 1.0)[:, :, None, None], 0.0
        )
        recall = jnp.where(valid_cell[:, :, None, None], rec_raw, -1.0)  # (K, A, T, M)
        q = jnp.where(valid_cell[:, None, :, None], q, -1.0)  # (K, R, A, T)

        # ---- summarize: masked means are exactly the host's mean-over-entries > -1
        # (inside a valid cell every entry is >= 0; invalid cells are uniform -1)
        lastm = M - 1

        def _precision_mean(a_idx: int, t_idx=None):
            block = q[:, :, a_idx, :]  # (K, R, T)
            if t_idx is not None:
                block = block[:, :, t_idx : t_idx + 1]
            w = valid_cell[:, a_idx].astype(jnp.float32)
            cnt = w.sum() * (block.shape[1] * block.shape[2])
            return jnp.where(cnt > 0, (block * w[:, None, None]).sum() / jnp.maximum(cnt, 1.0), -1.0)

        def _recall_mean(a_idx: int, m_idx: int):
            block = recall[:, a_idx, :, m_idx]  # (K, T)
            w = valid_cell[:, a_idx].astype(jnp.float32)
            cnt = w.sum() * block.shape[1]
            return jnp.where(cnt > 0, (block * w[:, None]).sum() / jnp.maximum(cnt, 1.0), -1.0)

        out: Dict[str, jnp.ndarray] = {
            "map": _precision_mean(0),
            "map_small": _precision_mean(1),
            "map_medium": _precision_mean(2),
            "map_large": _precision_mean(3),
            "mar_small": _recall_mean(1, lastm),
            "mar_medium": _recall_mean(2, lastm),
            "mar_large": _recall_mean(3, lastm),
            "map_50": _precision_mean(0, t50) if t50 is not None else jnp.float32(-1.0),
            "map_75": _precision_mean(0, t75) if t75 is not None else jnp.float32(-1.0),
        }
        for m_idx, mdet in enumerate(max_detection_thresholds):
            out[f"mar_{mdet}"] = _recall_mean(0, m_idx)

        pc_q = q[:, :, 0, :]  # (K, R, T)
        out["map_per_class"] = jnp.where(valid_cell[:, 0], pc_q.sum((1, 2)) / (R * T), -1.0)
        out["mar_per_class"] = jnp.where(valid_cell[:, 0], recall[:, 0, :, lastm].sum(1) / T, -1.0)

        det_seen = jax.ops.segment_sum(
            dvalid.astype(jnp.int32), jnp.where(dvalid, d_lab, K), num_segments=K + 1
        )[:K]
        gt_seen = jax.ops.segment_sum(
            gvalid.astype(jnp.int32), jnp.where(gvalid, g_lab, K), num_segments=K + 1
        )[:K]
        out["present"] = (det_seen + gt_seen) > 0
        return out

    return fn
