"""Clustering shared helpers (reference ``functional/clustering/utils.py``).

Cluster labels are arbitrary integers, so the contingency machinery is inherently
dynamic-shape (``unique``); it runs host-side in numpy at compute time. The heavy
per-sample accumulation for these metrics is just label storage (cat states) — there
is no device hot loop to win.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np

from ...utilities.checks import _check_same_shape


def check_cluster_labels(preds, target) -> None:
    """Validate shapes and that labels are real, discrete values."""
    _check_same_shape(preds, target)
    for x in (preds, target):
        dt = np.asarray(x).dtype
        if not (np.issubdtype(dt, np.integer) or np.issubdtype(dt, np.floating)):
            raise ValueError(
                f"Expected real, discrete values for x but received {np.asarray(preds).dtype} and {np.asarray(target).dtype}."
            )
        if np.issubdtype(dt, np.floating) and not np.all(np.mod(np.asarray(x), 1) == 0):
            raise ValueError(
                f"Expected real, discrete values for x but received {np.asarray(preds).dtype} and {np.asarray(target).dtype}."
            )


def calculate_entropy(x) -> float:
    """Shannon entropy of a label vector (log form against roundoff)."""
    x = np.asarray(x).reshape(-1)
    if x.size == 0:
        return 1.0
    p = np.bincount(np.unique(x, return_inverse=True)[1])
    p = p[p > 0]
    if p.size == 1:
        return 0.0
    n = p.sum()
    return float(-np.sum((p / n) * (np.log(p) - np.log(n))))


def calculate_generalized_mean(x: np.ndarray, p: Union[int, str]) -> float:
    """Generalized mean with the string shortcuts used by the MI normalizers."""
    x = np.asarray(x, np.float64)
    if np.iscomplexobj(x) or np.any(x < 0):
        raise ValueError("`x` must contain positive real numbers")
    if isinstance(p, str):
        if p == "min":
            return float(x.min())
        if p == "geometric":
            return float(np.exp(np.mean(np.log(x))))
        if p == "arithmetic":
            return float(x.mean())
        if p == "max":
            return float(x.max())
        raise ValueError("'method' must be 'min', 'geometric', 'arithmetic', or 'max'")
    return float(np.mean(x**p) ** (1.0 / p))


def _validate_average_method_arg(average_method: str) -> None:
    if average_method not in ("min", "geometric", "arithmetic", "max"):
        raise ValueError(
            "Expected argument `average_method` to be one of `min`, `geometric`, `arithmetic`, `max`,"
            f" but got {average_method}"
        )


def calculate_contingency_matrix(preds, target, eps: Optional[float] = None, sparse: bool = False) -> np.ndarray:
    """Contingency matrix of shape ``(n_classes_target, n_classes_preds)``."""
    if eps is not None and sparse is True:
        raise ValueError("Cannot specify `eps` and return sparse tensor.")
    preds = np.asarray(preds).reshape(-1)
    target = np.asarray(target).reshape(-1)
    if preds.ndim != 1 or target.ndim != 1:
        raise ValueError(f"Expected 1d `preds` and `target` but got {preds.ndim} and {target.ndim}.")
    preds_classes, preds_idx = np.unique(preds, return_inverse=True)
    target_classes, target_idx = np.unique(target, return_inverse=True)
    contingency = np.zeros((target_classes.size, preds_classes.size), np.float64)
    np.add.at(contingency, (target_idx, preds_idx), 1)
    if eps is not None:
        contingency = contingency + eps
    return contingency


def calculate_pair_cluster_confusion_matrix(
    preds=None, target=None, contingency: Optional[np.ndarray] = None
) -> np.ndarray:
    """2x2 pair confusion matrix over all sample pairs (sklearn
    ``pair_confusion_matrix`` semantics; not symmetric)."""
    if preds is None and target is None and contingency is None:
        raise ValueError("Must provide either `preds` and `target` or `contingency`.")
    if preds is not None and target is not None and contingency is not None:
        raise ValueError("Must provide either `preds` and `target` or `contingency`, not both.")
    if contingency is None:
        contingency = calculate_contingency_matrix(preds, target)
    n_samples = contingency.sum()
    n_c = contingency.sum(axis=1)
    n_k = contingency.sum(axis=0)
    sum_squares = (contingency**2).sum()
    pair_matrix = np.zeros((2, 2), np.float64)
    pair_matrix[1, 1] = sum_squares - n_samples
    pair_matrix[0, 1] = (contingency @ n_k).sum() - sum_squares
    pair_matrix[1, 0] = (contingency.T @ n_c).sum() - sum_squares
    pair_matrix[0, 0] = n_samples**2 - pair_matrix[0, 1] - pair_matrix[1, 0] - sum_squares
    return pair_matrix


def _validate_intrinsic_cluster_data(data, labels) -> None:
    data = np.asarray(data)
    labels = np.asarray(labels)
    if data.ndim != 2:
        raise ValueError(f"Expected 2D data, got {data.ndim}D data instead")
    if not np.issubdtype(data.dtype, np.floating):
        raise ValueError(f"Expected floating point data, got {data.dtype} data instead")
    if labels.ndim != 1:
        raise ValueError(f"Expected 1D labels, got {labels.ndim}D labels instead")


def _validate_intrinsic_labels_to_samples(num_labels: int, num_samples: int) -> None:
    if not 1 < num_labels < num_samples:
        raise ValueError(
            "Number of detected clusters must be greater than one and less than the number of samples."
            f" Got {num_labels} clusters and {num_samples} samples."
        )


def _cluster_views(data: np.ndarray, labels: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Zero-index labels; return (inverse_labels, counts, centroids)."""
    _, inverse = np.unique(labels, return_inverse=True)
    num_labels = int(inverse.max()) + 1 if inverse.size else 0
    counts = np.bincount(inverse, minlength=num_labels).astype(np.float64)
    centroids = np.zeros((num_labels, data.shape[1]), np.float64)
    np.add.at(centroids, inverse, data)
    centroids /= counts[:, None]
    return inverse, counts, centroids
