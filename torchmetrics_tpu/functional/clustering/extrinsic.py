"""Extrinsic (label-vs-label) clustering metrics (reference
``functional/clustering/{mutual_info_score,adjusted_mutual_info_score,
normalized_mutual_info_score,rand_score,adjusted_rand_score,fowlkes_mallows_index,
homogeneity_completeness_v_measure,cluster_accuracy}.py``).

All operate on the contingency table of two label vectors; see ``utils.py`` for why
these computes run host-side. ``cluster_accuracy`` uses scipy's Hungarian solver
instead of the reference's optional ``torch_linear_assignment`` wheel.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from .utils import (
    _validate_average_method_arg,
    calculate_contingency_matrix,
    calculate_entropy,
    calculate_generalized_mean,
    calculate_pair_cluster_confusion_matrix,
    check_cluster_labels,
)


def _as_scalar(x: float) -> jnp.ndarray:
    return jnp.asarray(float(x), jnp.float32)


# ------------------------------------------------------------- mutual information

def _mutual_info_score_update(preds, target) -> np.ndarray:
    check_cluster_labels(preds, target)
    return calculate_contingency_matrix(preds, target)


def _mutual_info_score_compute(contingency: np.ndarray) -> float:
    n = contingency.sum()
    u = contingency.sum(axis=1)
    v = contingency.sum(axis=0)
    if u.size == 1 or v.size == 1:
        return 0.0
    nzu, nzv = np.nonzero(contingency)
    nz = contingency[nzu, nzv]
    log_outer = np.log(u[nzu]) + np.log(v[nzv])
    mutual_info = nz / n * (np.log(n) + np.log(nz) - log_outer)
    return float(mutual_info.sum())


def mutual_info_score(preds, target) -> jnp.ndarray:
    r"""Mutual information between two clusterings (reference
    ``functional/clustering/mutual_info_score.py:65``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import mutual_info_score
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> mutual_info_score(preds, target)
        Array(0.50040245, dtype=float32)
    """
    return _as_scalar(_mutual_info_score_compute(_mutual_info_score_update(preds, target)))


def expected_mutual_info_score(contingency: np.ndarray, n_samples: int) -> float:
    """Expected MI under the hypergeometric null (sklearn
    ``_expected_mutual_info_fast`` semantics, vectorized over the inner sum)."""
    contingency = np.asarray(contingency, np.float64)
    a = contingency.sum(axis=1)
    b = contingency.sum(axis=0)
    if a.size == 1 or b.size == 1:
        return 0.0
    max_n = int(max(a.max(), b.max())) + 1
    nijs = np.arange(max_n, dtype=np.float64)
    nijs[0] = 1.0
    term1 = nijs / n_samples
    log_a, log_b = np.log(a), np.log(b)
    log_nnij = np.log(n_samples) + np.log(nijs)
    from scipy.special import gammaln

    gln_a = gammaln(a + 1)
    gln_b = gammaln(b + 1)
    gln_na = gammaln(n_samples - a + 1)
    gln_nb = gammaln(n_samples - b + 1)
    gln_nnij = gammaln(nijs + 1) + gammaln(n_samples + 1)
    emi = 0.0
    for i in range(a.size):
        for j in range(b.size):
            start = int(max(1, a[i] - n_samples + b[j]))
            end = int(min(a[i], b[j])) + 1
            if end <= start:
                continue
            nij = np.arange(start, end, dtype=np.float64)
            term2 = log_nnij[start:end] - log_a[i] - log_b[j]
            gln = (
                gln_a[i]
                + gln_b[j]
                + gln_na[i]
                + gln_nb[j]
                - gln_nnij[start:end]
                - gammaln(a[i] - nij + 1)
                - gammaln(b[j] - nij + 1)
                - gammaln(n_samples - a[i] - b[j] + nij + 1)
            )
            emi += float((term1[start:end] * term2 * np.exp(gln)).sum())
    return emi


def adjusted_mutual_info_score(preds, target, average_method: str = "arithmetic") -> jnp.ndarray:
    r"""Adjusted mutual information: ``(MI - E[MI]) / (normalizer - E[MI])``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import adjusted_mutual_info_score
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> adjusted_mutual_info_score(preds, target)
        Array(-0.25, dtype=float32)
    """
    _validate_average_method_arg(average_method)
    contingency = _mutual_info_score_update(preds, target)
    mutual_info = _mutual_info_score_compute(contingency)
    n_samples = int(np.asarray(target).size)
    emi = expected_mutual_info_score(contingency, n_samples)
    normalizer = calculate_generalized_mean(
        np.array([calculate_entropy(preds), calculate_entropy(target)]), average_method
    )
    denominator = normalizer - emi
    eps = float(np.finfo(np.float32).eps)
    denominator = min(denominator, -eps) if denominator < 0 else max(denominator, eps)
    return _as_scalar((mutual_info - emi) / denominator)


def normalized_mutual_info_score(preds, target, average_method: str = "arithmetic") -> jnp.ndarray:
    r"""Normalized mutual information: ``MI / generalized_mean(H(preds), H(target))``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import normalized_mutual_info_score
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> normalized_mutual_info_score(preds, target)
        Array(0.474351, dtype=float32)
    """
    check_cluster_labels(preds, target)
    _validate_average_method_arg(average_method)
    mutual_info = _mutual_info_score_compute(_mutual_info_score_update(preds, target))
    if abs(mutual_info) <= np.finfo(np.float32).eps:
        return _as_scalar(mutual_info)
    normalizer = calculate_generalized_mean(
        np.array([calculate_entropy(preds), calculate_entropy(target)]), average_method
    )
    return _as_scalar(mutual_info / normalizer)


# --------------------------------------------------------------------- rand family

def _rand_score_update(preds, target) -> np.ndarray:
    check_cluster_labels(preds, target)
    return calculate_contingency_matrix(preds, target)


def _rand_score_compute(contingency: np.ndarray) -> float:
    pair_matrix = calculate_pair_cluster_confusion_matrix(contingency=contingency)
    numerator = pair_matrix.diagonal().sum()
    denominator = pair_matrix.sum()
    if numerator == denominator or denominator == 0:
        return 1.0
    return float(numerator / denominator)


def rand_score(preds, target) -> jnp.ndarray:
    r"""Rand index: fraction of sample pairs on which the clusterings agree.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import rand_score
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> rand_score(preds, target)
        Array(0.6, dtype=float32)
    """
    return _as_scalar(_rand_score_compute(_rand_score_update(preds, target)))


def _adjusted_rand_score_compute(contingency: np.ndarray) -> float:
    (tn, fp), (fn, tp) = calculate_pair_cluster_confusion_matrix(contingency=contingency)
    if fn == 0 and fp == 0:
        return 1.0
    return float(2.0 * (tp * tn - fn * fp) / ((tp + fn) * (fn + tn) + (tp + fp) * (fp + tn)))


def adjusted_rand_score(preds, target) -> jnp.ndarray:
    r"""Chance-adjusted Rand index.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import adjusted_rand_score
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> adjusted_rand_score(preds, target)
        Array(-0.25, dtype=float32)
    """
    return _as_scalar(_adjusted_rand_score_compute(_rand_score_update(preds, target)))


def _fowlkes_mallows_index_update(preds, target) -> Tuple[np.ndarray, int]:
    check_cluster_labels(preds, target)
    return calculate_contingency_matrix(preds, target), int(np.asarray(preds).size)


def _fowlkes_mallows_index_compute(contingency: np.ndarray, n: int) -> float:
    tk = (contingency**2).sum() - n
    if np.isclose(tk, 0):
        return 0.0
    pk = (contingency.sum(axis=0) ** 2).sum() - n
    qk = (contingency.sum(axis=1) ** 2).sum() - n
    return float(np.sqrt(tk / pk) * np.sqrt(tk / qk))


def fowlkes_mallows_index(preds, target) -> jnp.ndarray:
    r"""Fowlkes-Mallows index: geometric mean of pairwise precision and recall.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import fowlkes_mallows_index
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> fowlkes_mallows_index(preds, target)
        Array(0., dtype=float32)
    """
    contingency, n = _fowlkes_mallows_index_update(preds, target)
    return _as_scalar(_fowlkes_mallows_index_compute(contingency, n))


# -------------------------------------------- homogeneity / completeness / v-measure

def _homogeneity_score_compute(preds, target) -> Tuple[float, float, float, float]:
    check_cluster_labels(preds, target)
    if np.asarray(target).size == 0:
        return 0.0, 0.0, 0.0, 0.0
    entropy_target = calculate_entropy(target)
    entropy_preds = calculate_entropy(preds)
    mutual_info = _mutual_info_score_compute(_mutual_info_score_update(preds, target))
    homogeneity = mutual_info / entropy_target if entropy_target else 1.0
    return homogeneity, mutual_info, entropy_preds, entropy_target


def homogeneity_score(preds, target) -> jnp.ndarray:
    r"""Homogeneity: each cluster contains only members of a single class.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import homogeneity_score
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> homogeneity_score(preds, target)
        Array(0.474351, dtype=float32)
    """
    return _as_scalar(_homogeneity_score_compute(preds, target)[0])


def _completeness_score_compute(preds, target) -> Tuple[float, float]:
    homogeneity, mutual_info, entropy_preds, _ = _homogeneity_score_compute(preds, target)
    completeness = mutual_info / entropy_preds if entropy_preds else 1.0
    return completeness, homogeneity


def completeness_score(preds, target) -> jnp.ndarray:
    r"""Completeness: all members of a class are assigned to the same cluster.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import completeness_score
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> completeness_score(preds, target)
        Array(0.474351, dtype=float32)
    """
    return _as_scalar(_completeness_score_compute(preds, target)[0])


def v_measure_score(preds, target, beta: float = 1.0) -> jnp.ndarray:
    r"""V-measure: weighted harmonic mean of homogeneity and completeness.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import v_measure_score
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> v_measure_score(preds, target)
        Array(0.474351, dtype=float32)
    """
    completeness, homogeneity = _completeness_score_compute(preds, target)
    if homogeneity + completeness == 0.0:
        return _as_scalar(1.0)
    return _as_scalar((1 + beta) * homogeneity * completeness / (beta * homogeneity + completeness))


# ------------------------------------------------------------------ cluster accuracy

def _cluster_accuracy_compute(confmat: np.ndarray) -> float:
    from scipy.optimize import linear_sum_assignment

    confmat = np.asarray(confmat, np.float64)
    row_ind, col_ind = linear_sum_assignment(confmat.max() - confmat)
    return float(confmat[row_ind, col_ind].sum() / confmat.sum())


def cluster_accuracy(preds, target, num_classes: int) -> jnp.ndarray:
    r"""Clustering accuracy: optimal one-to-one label assignment (Hungarian solve via
    scipy; the reference needs the optional ``torch_linear_assignment`` wheel).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import cluster_accuracy
        >>> preds = jnp.asarray([2, 1, 0, 1, 0])
        >>> target = jnp.asarray([0, 2, 1, 1, 0])
        >>> cluster_accuracy(preds, target, num_classes=3)
        Array(0.6, dtype=float32)
    """
    from ..classification.confusion_matrix import multiclass_confusion_matrix

    check_cluster_labels(preds, target)
    confmat = multiclass_confusion_matrix(jnp.asarray(preds), jnp.asarray(target), num_classes=num_classes)
    return _as_scalar(_cluster_accuracy_compute(np.asarray(confmat)))
