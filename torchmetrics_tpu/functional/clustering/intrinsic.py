"""Intrinsic (data + labels) clustering scores (reference
``functional/clustering/{calinski_harabasz_score,davies_bouldin_score,dunn_index}.py``).

The reference loops over clusters with boolean indexing; here cluster sums/centroids
come from one scatter-add pass and the rest is dense matrix arithmetic.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .utils import _cluster_views, _validate_intrinsic_cluster_data, _validate_intrinsic_labels_to_samples


def calinski_harabasz_score(data, labels) -> jnp.ndarray:
    r"""Calinski-Harabasz score: between/within dispersion ratio.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import calinski_harabasz_score
        >>> data = jnp.asarray([[0.0, 0.0], [0.5, 0.0], [10.0, 10.0], [10.5, 10.0], [20.0, 0.0], [20.5, 0.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> calinski_harabasz_score(data, labels)
        Array(2133.3333, dtype=float32)
    """
    data = np.asarray(data, np.float64)
    labels = np.asarray(labels)
    _validate_intrinsic_cluster_data(data, labels)
    inverse, counts, centroids = _cluster_views(data, labels)
    num_labels = counts.size
    num_samples = data.shape[0]
    _validate_intrinsic_labels_to_samples(num_labels, num_samples)
    mean = data.mean(axis=0)
    between = (((centroids - mean) ** 2).sum(axis=1) * counts).sum()
    within = ((data - centroids[inverse]) ** 2).sum()
    if within == 0:
        return jnp.asarray(1.0, jnp.float32)
    return jnp.asarray(between * (num_samples - num_labels) / (within * (num_labels - 1.0)), jnp.float32)


def davies_bouldin_score(data, labels) -> jnp.ndarray:
    r"""Davies-Bouldin score: mean worst-case ratio of intra-cluster spread to
    centroid separation.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import davies_bouldin_score
        >>> data = jnp.asarray([[0.0, 0.0], [0.5, 0.0], [10.0, 10.0], [10.5, 10.0], [20.0, 0.0], [20.5, 0.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> davies_bouldin_score(data, labels)
        Array(0.03535534, dtype=float32)
    """
    data = np.asarray(data, np.float64)
    labels = np.asarray(labels)
    _validate_intrinsic_cluster_data(data, labels)
    inverse, counts, centroids = _cluster_views(data, labels)
    num_labels = counts.size
    _validate_intrinsic_labels_to_samples(num_labels, data.shape[0])
    dists = np.sqrt(((data - centroids[inverse]) ** 2).sum(axis=1))
    intra = np.zeros(num_labels, np.float64)
    np.add.at(intra, inverse, dists)
    intra /= counts
    diff = centroids[:, None, :] - centroids[None, :, :]
    centroid_distances = np.sqrt((diff**2).sum(axis=-1))
    if np.allclose(intra, 0) or np.allclose(centroid_distances, 0):
        return jnp.asarray(0.0, jnp.float32)
    centroid_distances[centroid_distances == 0] = np.inf
    combined = intra[None, :] + intra[:, None]
    scores = (combined / centroid_distances).max(axis=1)
    return jnp.asarray(scores.mean(), jnp.float32)


def dunn_index(data, labels, p: float = 2) -> jnp.ndarray:
    r"""Dunn index: min inter-centroid distance over max intra-cluster radius.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import dunn_index
        >>> data = jnp.asarray([[0.0, 0.0], [0.5, 0.0], [10.0, 10.0], [10.5, 10.0], [20.0, 0.0], [20.5, 0.0]])
        >>> labels = jnp.asarray([0, 0, 1, 1, 2, 2])
        >>> dunn_index(data, labels)
        Array(56.568542, dtype=float32)
    """
    data = np.asarray(data, np.float64)
    labels = np.asarray(labels)
    _validate_intrinsic_cluster_data(data, labels)
    inverse, counts, centroids = _cluster_views(data, labels)
    num_labels = counts.size
    _validate_intrinsic_labels_to_samples(num_labels, data.shape[0])
    # inter-cluster distances over all centroid pairs (upper triangle)
    iu = np.triu_indices(num_labels, k=1)
    inter = np.linalg.norm(centroids[iu[0]] - centroids[iu[1]], ord=p, axis=1)
    radii = np.linalg.norm(data - centroids[inverse], ord=p, axis=1)
    max_intra = np.zeros(num_labels, np.float64)
    np.maximum.at(max_intra, inverse, radii)
    return jnp.asarray(inter.min() / max_intra.max(), jnp.float32)
