"""Clustering tower — stateless kernels (reference ``src/torchmetrics/functional/clustering/``)."""

from .extrinsic import (
    adjusted_mutual_info_score,
    adjusted_rand_score,
    cluster_accuracy,
    completeness_score,
    fowlkes_mallows_index,
    homogeneity_score,
    mutual_info_score,
    normalized_mutual_info_score,
    rand_score,
    v_measure_score,
)
from .intrinsic import calinski_harabasz_score, davies_bouldin_score, dunn_index

__all__ = [
    "adjusted_mutual_info_score",
    "adjusted_rand_score",
    "calinski_harabasz_score",
    "cluster_accuracy",
    "completeness_score",
    "davies_bouldin_score",
    "dunn_index",
    "fowlkes_mallows_index",
    "homogeneity_score",
    "mutual_info_score",
    "normalized_mutual_info_score",
    "rand_score",
    "v_measure_score",
]
