"""Shape tower — stateless kernels (reference ``src/torchmetrics/functional/shape/``)."""

from .procrustes import procrustes_disparity

__all__ = ["procrustes_disparity"]
