"""Procrustes disparity (reference ``functional/shape/procrustes.py:23``).

Batched orthogonal Procrustes analysis — centering, Frobenius normalization, one
batched SVD (``jnp.linalg.svd`` maps to XLA's batched SVD), rotation + uniform scale,
then the squared residual. Everything is one jittable expression.
"""

from __future__ import annotations

from typing import Tuple, Union

import jax.numpy as jnp

from ...utilities.checks import _check_same_shape


def procrustes_disparity(
    point_cloud1: jnp.ndarray, point_cloud2: jnp.ndarray, return_all: bool = False
) -> Union[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """Batched Procrustes analysis (scipy.spatial.procrustes semantics over a leading
    batch axis). Returns per-sample disparity, plus scale and rotation when
    ``return_all=True``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import procrustes_disparity
        >>> point_set1 = jnp.asarray([[[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 1.0]]])
        >>> point_set2 = jnp.asarray([[[0.0, 0.0], [2.0, 0.0], [2.0, 2.0], [0.0, 2.0]]])
        >>> procrustes_disparity(point_set1, point_set2)
        Array([7.1054274e-15], dtype=float32)
    """
    point_cloud1 = jnp.asarray(point_cloud1, jnp.float32)
    point_cloud2 = jnp.asarray(point_cloud2, jnp.float32)
    _check_same_shape(point_cloud1, point_cloud2)
    if point_cloud1.ndim != 3:
        raise ValueError(
            "Expected both datasets to be 3D tensors of shape (N, M, D), where N is the batch size, M is the number of"
            f" data points and D is the dimensionality of the data points, but got {point_cloud1.ndim} dimensions."
        )
    point_cloud1 = point_cloud1 - point_cloud1.mean(axis=1, keepdims=True)
    point_cloud2 = point_cloud2 - point_cloud2.mean(axis=1, keepdims=True)
    point_cloud1 = point_cloud1 / jnp.linalg.norm(point_cloud1, axis=(1, 2), keepdims=True)
    point_cloud2 = point_cloud2 / jnp.linalg.norm(point_cloud2, axis=(1, 2), keepdims=True)

    u, w, vt = jnp.linalg.svd(
        jnp.swapaxes(jnp.matmul(jnp.swapaxes(point_cloud2, 1, 2), point_cloud1), 1, 2), full_matrices=False
    )
    rotation = jnp.matmul(u, vt)
    scale = w.sum(axis=1, keepdims=True)
    point_cloud2 = scale[:, None] * jnp.matmul(point_cloud2, jnp.swapaxes(rotation, 1, 2))
    disparity = ((point_cloud1 - point_cloud2) ** 2).sum(axis=(1, 2))
    if return_all:
        return disparity, scale, rotation
    return disparity
