"""Functional metrics API (stateless one-shot kernels). Parity: reference
``functional/__init__.py`` (104 top-level exports).

Every domain package declares its public surface in its own ``__all__``; this module
aggregates them so the flat ``torchmetrics_tpu.functional.<fn>`` namespace stays in
lock-step with the per-domain namespaces as domains are added."""

from torchmetrics_tpu.functional import audio, classification, clustering, detection, image, multimodal, nominal, pairwise, regression, retrieval, segmentation, shape, text, video
from torchmetrics_tpu.functional.audio import *  # noqa: F401,F403
from torchmetrics_tpu.functional.classification import *  # noqa: F401,F403
from torchmetrics_tpu.functional.regression import *  # noqa: F401,F403
from torchmetrics_tpu.functional.retrieval import *  # noqa: F401,F403
from torchmetrics_tpu.functional.clustering import *  # noqa: F401,F403
from torchmetrics_tpu.functional.detection import *  # noqa: F401,F403
from torchmetrics_tpu.functional.image import *  # noqa: F401,F403
from torchmetrics_tpu.functional.multimodal import *  # noqa: F401,F403
from torchmetrics_tpu.functional.nominal import *  # noqa: F401,F403
from torchmetrics_tpu.functional.pairwise import *  # noqa: F401,F403
from torchmetrics_tpu.functional.shape import *  # noqa: F401,F403
from torchmetrics_tpu.functional.text import *  # noqa: F401,F403
from torchmetrics_tpu.functional.segmentation import *  # noqa: F401,F403
from torchmetrics_tpu.functional.video import *  # noqa: F401,F403

# Reference quirk mirrored for drop-in parity: `torchmetrics.functional`'s top-level
# `peak_signal_noise_ratio` is the deprecated wrapper with `data_range=3.0`
# (reference functional/__init__.py:63), while `functional.image`'s export requires
# `data_range`. The compat alias shadows the strict image export here only.
from torchmetrics_tpu.functional.image.psnr import (  # noqa: E402
    _compat_peak_signal_noise_ratio as peak_signal_noise_ratio,  # noqa: F811
)

__all__ = [
    *classification.__all__,
    *regression.__all__,
    *retrieval.__all__,
    *audio.__all__,
    *clustering.__all__,
    *detection.__all__,
    *image.__all__,
    *multimodal.__all__,
    *nominal.__all__,
    *pairwise.__all__,
    *shape.__all__,
    *text.__all__,
    *segmentation.__all__,
    *video.__all__,
]

# Factory-built entry points (stat-scores family, task dispatchers) have no
# source `def` to carry a docstring example; attach the generated ones at import
# so help() shows them (executed in CI by tests/test_doctest_examples.py).
try:  # pragma: no cover - absent only before the generator first runs
    from torchmetrics_tpu.functional._doctest_examples import EXAMPLES as _DOCTEST_EXAMPLES
except ImportError:
    _DOCTEST_EXAMPLES = {}
def _attach_doctest_examples() -> None:
    for name, example in _DOCTEST_EXAMPLES.items():
        fn = globals().get(name)
        if fn is not None and ">>>" not in (fn.__doc__ or ""):
            title = name.replace("_", " ").capitalize()
            fn.__doc__ = (fn.__doc__ or f"{title}.") + example


_attach_doctest_examples()
