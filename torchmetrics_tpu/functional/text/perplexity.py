"""Perplexity (reference ``functional/text/perplexity.py``).

Fully jittable: one log-softmax gather with ignore-index masking — the only text
metric whose update is a device kernel end to end.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _check_shape_and_type_consistency(preds, target) -> None:
    if preds.ndim != 3:
        raise ValueError(
            "Input tensor `preds` is expected to have 3 dimensions, [batch_size, seq_len, vocab_size],"
            f" but got {preds.ndim}."
        )
    if target.ndim != 2:
        raise ValueError(
            f"Input tensor `target` is expected to have 2 dimensions, [batch_size, seq_len], but got {target.ndim}."
        )
    if preds.shape[:2] != target.shape:
        raise ValueError(
            "Input tensors `preds` and `target` are expected to have equaling first two dimensions,"
            f" [batch_size, seq_len], but got {preds.shape[:2]} and {target.shape}."
        )
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise TypeError(f"Input tensor `preds` is expected to be of floating point type but got {preds.dtype}.")
    if not jnp.issubdtype(target.dtype, jnp.integer):
        raise TypeError(f"Input tensor `target` is expected to be of integer type but got {target.dtype}.")


def _perplexity_update(preds, target, ignore_index: Optional[int] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_shape_and_type_consistency(preds, target)
    log_probs = jax.nn.log_softmax(preds.reshape(-1, preds.shape[-1]), axis=-1)
    target = target.reshape(-1)
    if ignore_index is not None:
        mask = target != ignore_index
        target = jnp.where(mask, target, 0)
    else:
        mask = jnp.ones_like(target, bool)
    picked = jnp.take_along_axis(log_probs, target[:, None], axis=1)[:, 0]
    total_log_probs = -(jnp.where(mask, picked, 0.0)).sum()
    count = mask.sum()
    return total_log_probs, count


def _perplexity_compute(total, count) -> jnp.ndarray:
    return jnp.exp(total / count)


def perplexity(preds, target, ignore_index: Optional[int] = None) -> jnp.ndarray:
    """exp of the mean negative log-likelihood of the target tokens under ``preds``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import perplexity
        >>> preds = jnp.asarray([[[0.2, 0.4, 0.4], [0.5, 0.2, 0.3]]])
        >>> target = jnp.asarray([[1, 0]])
        >>> perplexity(jnp.log(preds), target)
        Array(2.236068, dtype=float32)
    """
    total, count = _perplexity_update(preds, target, ignore_index)
    return _perplexity_compute(total, count)
