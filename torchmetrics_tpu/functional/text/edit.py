"""Edit (Levenshtein) distance (reference ``functional/text/edit.py``)."""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp

from .ter import _levenshtein_with_trace


def _edit_distance_update(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    substitution_cost: int = 1,
) -> jnp.ndarray:
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if not all(isinstance(x, str) for x in preds):
        raise ValueError(f"Expected all values in argument `preds` to be string type, but got {preds}")
    if not all(isinstance(x, str) for x in target):
        raise ValueError(f"Expected all values in argument `target` to be string type, but got {target}")
    if len(preds) != len(target):
        raise ValueError(
            f"Expected argument `preds` and `target` to have same length, but got {len(preds)} and {len(target)}"
        )
    # beam-limited DP like the reference's _LevenshteinEditDistance (beam width 25):
    # the beam is part of the reference's observable behavior on length-disparate pairs
    distance = [_levenshtein_with_trace(list(p), list(t), substitution_cost)[0] for p, t in zip(preds, target)]
    return jnp.asarray(distance, jnp.int32)


def _edit_distance_compute(
    edit_scores: jnp.ndarray,
    num_elements,
    reduction: Optional[str] = "mean",
) -> jnp.ndarray:
    if edit_scores.size == 0:
        return jnp.asarray(0, jnp.int32)
    if reduction == "mean":
        return edit_scores.sum() / num_elements
    if reduction == "sum":
        return edit_scores.sum()
    if reduction is None or reduction == "none":
        return edit_scores
    raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")


def edit_distance(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    substitution_cost: int = 1,
    reduction: Optional[str] = "mean",
) -> jnp.ndarray:
    """Character-level Levenshtein distance with configurable substitution cost.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import edit_distance
        >>> edit_distance(['rain'], ['shine'])
        Array(3., dtype=float32)
    """
    distance = _edit_distance_update(preds, target, substitution_cost)
    return _edit_distance_compute(distance, num_elements=distance.size, reduction=reduction)
