"""InfoLM (reference ``functional/text/infolm.py``; Colombo et al., AAAI 2022).

Information measures between masked-LM token distributions of predicted and
reference sentences. The distribution of a sentence is the (idf-weighted) average
over positions of ``softmax(logits[pos] / temperature)`` with position ``pos``
masked out — one MLM forward per position, exactly the reference pipeline
(``functional/text/infolm.py:368-425``).

The masked LM is pluggable through the same seam BERTScore uses:
``model_name_or_path`` loads a HF ``AutoModelForMaskedLM`` from the *local* cache
(no egress), or ``model`` + ``user_tokenizer`` supply a custom pipeline. The
information measures themselves (``functional/text/infolm.py:57-210``) are
self-contained jnp math.

Known deliberate divergence: the reference sorts sentences by length for batching
and then applies the sorting permutation a second time instead of inverting it
(``functional/text/infolm.py:539-541`` indexing with the output of
``helper_embedding_metric.py:79-84``), so its sentence-level scores come back
mis-ordered — and when predictions and references have different length
orderings, it pairs the wrong sentences. This implementation keeps input order
(no sorting is needed: there is no per-batch recompile to amortize under XLA's
static shapes). Corpus means agree with the reference whenever preds and targets
share a length ordering; ``tests/test_infolm.py`` checks parity modulo the
reference's permutation.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...utilities.imports import _module_available

_TRANSFORMERS_AVAILABLE = _module_available("transformers")

_ALLOWED_INFORMATION_MEASURE = (
    "kl_divergence",
    "alpha_divergence",
    "beta_divergence",
    "ab_divergence",
    "renyi_divergence",
    "l1_distance",
    "l2_distance",
    "l_infinity_distance",
    "fisher_rao_distance",
)


class _InformationMeasure:
    """Vectorized information measures over ``(batch, vocab)`` distributions.

    Validation rules mirror the reference (``functional/text/infolm.py:104-136``):
    alpha required (and not in {0, 1}) for alpha divergence, not 1 for Rényi;
    beta required (not in {0, -1}) for beta divergence; AB divergence needs
    alpha, beta and alpha+beta all nonzero.
    """

    def __init__(
        self,
        information_measure: str,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
    ) -> None:
        if information_measure not in _ALLOWED_INFORMATION_MEASURE:
            raise ValueError(
                f"Argument `information_measure` expected one of {_ALLOWED_INFORMATION_MEASURE}, "
                f"got {information_measure}"
            )
        self.information_measure = information_measure
        needs_alpha = ("alpha_divergence", "ab_divergence", "renyi_divergence")
        if information_measure in needs_alpha and not isinstance(alpha, float):
            raise ValueError(f"Parameter `alpha` is expected to be defined for {information_measure}.")
        if information_measure in ("beta_divergence", "ab_divergence") and not isinstance(beta, float):
            raise ValueError(f"Parameter `beta` is expected to be defined for {information_measure}.")
        if information_measure == "alpha_divergence" and (not isinstance(alpha, float) or alpha in (0, 1)):
            raise ValueError(
                f"Parameter `alpha` is expected to be float differened from 0 and 1 for {information_measure}."
            )
        if information_measure == "beta_divergence" and (not isinstance(beta, float) or beta in (0, -1)):
            raise ValueError(
                f"Parameter `beta` is expected to be float differened from 0 and -1 for {information_measure}."
            )
        if information_measure == "ab_divergence" and (
            alpha is None or beta is None or 0 in (alpha, beta, alpha + beta)
        ):
            raise ValueError(
                "Parameters `alpha`, `beta` and their sum are expected to be differened from 0 for "
                f"{information_measure}."
            )
        if information_measure == "renyi_divergence" and (not isinstance(alpha, float) or alpha == 1):
            raise ValueError(f"Parameter `alpha` is expected to be float differened from 1 for {information_measure}.")
        self.alpha = alpha or 0.0
        self.beta = beta or 0.0

    def __call__(self, preds_dist: jnp.ndarray, target_dist: jnp.ndarray) -> jnp.ndarray:
        p = jnp.asarray(preds_dist)
        t = jnp.asarray(target_dist)
        fn = getattr(self, f"_{self.information_measure}")
        return jnp.nan_to_num(fn(p, t))

    @staticmethod
    def _kl_divergence(p, t):
        return jnp.sum(t * jnp.log(p / t), axis=-1)

    def _alpha_divergence(self, p, t):
        a = self.alpha
        return (1 - jnp.sum(t**a * p ** (1 - a), axis=-1)) / (a * (a - 1))

    def _ab_divergence(self, p, t, alpha: Optional[float] = None):
        a = self.alpha if alpha is None else alpha
        b = self.beta
        x = jnp.log(jnp.sum(t ** (b + a), axis=-1)) / (b * (b + a))
        y = jnp.log(jnp.sum(p ** (b + a), axis=-1)) / (a * (b + a))
        z = jnp.log(jnp.sum(t**a * p**b, axis=-1)) / (a * b)
        return x + y - z

    def _beta_divergence(self, p, t):
        return self._ab_divergence(p, t, alpha=1.0)

    def _renyi_divergence(self, p, t):
        a = self.alpha
        return jnp.log(jnp.sum(t**a * p ** (1 - a), axis=-1)) / (a - 1)

    @staticmethod
    def _l1_distance(p, t):
        return jnp.sum(jnp.abs(t - p), axis=-1)

    @staticmethod
    def _l2_distance(p, t):
        return jnp.sqrt(jnp.sum((t - p) ** 2, axis=-1))

    @staticmethod
    def _l_infinity_distance(p, t):
        return jnp.max(jnp.abs(t - p), axis=-1)

    @staticmethod
    def _fisher_rao_distance(p, t):
        return 2 * jnp.arccos(jnp.clip(jnp.sqrt(p * t).sum(-1), 0, 1))


def _load_hf_masked_lm(model_name_or_path: str):
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`infolm` metric with default models requires `transformers` package be installed."
            " Either install with `pip install transformers>=4.4` or `pip install torchmetrics[text]`."
        )
    import torch
    from transformers import AutoModelForMaskedLM, AutoTokenizer

    try:
        tokenizer = AutoTokenizer.from_pretrained(model_name_or_path, local_files_only=True)
        hf_model = AutoModelForMaskedLM.from_pretrained(model_name_or_path, local_files_only=True)
    except OSError as err:
        raise ModuleNotFoundError(
            f"Model {model_name_or_path!r} is not in the local HF cache and this environment has "
            "no network egress to download it. Pre-populate the cache offline, or pass "
            "`model` + `user_tokenizer` for a custom masked-LM pipeline."
        ) from err
    hf_model.eval()

    def forward(input_ids: np.ndarray, attention_mask: np.ndarray) -> np.ndarray:
        with torch.no_grad():
            out = hf_model(torch.as_tensor(np.asarray(input_ids)), torch.as_tensor(np.asarray(attention_mask)))
        return out.logits.numpy()

    max_length = getattr(hf_model.config, "max_length", 512)
    return tokenizer, forward, max_length


def _special_tokens_map(tokenizer: Any) -> Dict[str, int]:
    """mask/pad/sep/cls ids (reference ``functional/text/infolm.py:322-339``)."""
    return {
        "mask_token_id": tokenizer.mask_token_id,
        "pad_token_id": tokenizer.pad_token_id,
        "sep_token_id": tokenizer.sep_token_id,
        "cls_token_id": tokenizer.cls_token_id,
    }


def _token_mask(input_ids: np.ndarray, special: Dict[str, int]) -> np.ndarray:
    """1 for content tokens, 0 for pad/sep/cls (reference ``infolm.py:342-365``)."""
    bad = (
        (input_ids == special["pad_token_id"])
        | (input_ids == special["sep_token_id"])
        | (input_ids == special["cls_token_id"])
    )
    return ~bad


def _tokens_idf(input_ids: np.ndarray) -> Dict[int, float]:
    """log((N+1)/(df+1)) over full padded rows — the reference counts special and
    pad tokens too (``helper_embedding_metric.py:242-261``), which zeroes their idf."""
    num = input_ids.shape[0]
    df: Counter = Counter()
    for row in input_ids:
        df.update(set(row.tolist()))
    weights = {tok: float(np.log((num + 1) / (cnt + 1))) for tok, cnt in df.items()}
    weights["__default__"] = float(np.log(num + 1))
    return weights


def _sentence_distributions(
    forward: Callable,
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    temperature: float,
    idf: bool,
    special: Dict[str, int],
    batch_size: int,
) -> np.ndarray:
    """(B, vocab) discrete distribution per sentence: idf-weighted average over
    positions of the MLM's softened softmax with that position masked."""
    num = input_ids.shape[0]
    idf_lookup = _tokens_idf(input_ids) if idf else None
    chunks = []
    for start in range(0, num, batch_size):
        ids = input_ids[start : start + batch_size]
        mask = attention_mask[start : start + batch_size]
        tok_mask = _token_mask(ids, special)
        # trim to the batch's longest attended sequence (reference collator)
        l_eff = int(mask.sum(1).max()) if ids.size else 0
        ids = ids[:, :l_eff]
        mask = mask[:, :l_eff]
        tok_mask = tok_mask[:, :l_eff]
        if idf:
            default = idf_lookup["__default__"]
            idf_w = np.vectorize(lambda t: idf_lookup.get(int(t), default), otypes=[np.float32])(ids)
        acc = None
        for pos in range(l_eff):
            ids_m = ids.copy()
            ids_m[:, pos] = special["mask_token_id"]
            logits = np.asarray(forward(ids_m, mask))[:, pos, :]
            prob = np.asarray(jax.nn.softmax(jnp.asarray(logits, jnp.float32) / temperature, axis=-1))
            w = tok_mask[:, pos].astype(np.float32)
            if idf:
                w = w * idf_w[:, pos]
            contrib = prob * w[:, None]
            acc = contrib if acc is None else acc + contrib
        denom = (tok_mask * (idf_w if idf else 1.0)).sum(1).astype(np.float32)
        if acc is None:
            acc = np.zeros((ids.shape[0], 1), np.float32)
        chunks.append(acc / denom[:, None])
    return np.concatenate(chunks) if chunks else np.zeros((0, 1), np.float32)


def _infolm_prepare(
    model_name_or_path: Optional[str],
    model: Optional[Callable],
    user_tokenizer: Any,
    max_length: Optional[int],
) -> Tuple[Any, Callable, int, Dict[str, int]]:
    if model is not None:
        if user_tokenizer is None:
            raise ValueError("A custom `model` must be accompanied by a `user_tokenizer`.")
        tokenizer, forward = user_tokenizer, model
        max_len = max_length or 512
    else:
        tokenizer, forward, model_max = _load_hf_masked_lm(model_name_or_path or "bert-base-uncased")
        max_len = max_length or model_max
    return tokenizer, forward, max_len, _special_tokens_map(tokenizer)


def _infolm_tokenize(tokenizer: Any, texts: Sequence[str], max_length: int) -> Dict[str, np.ndarray]:
    out = tokenizer(list(texts), padding="max_length", max_length=max_length, truncation=True, return_tensors="np")
    return {"input_ids": np.asarray(out["input_ids"]), "attention_mask": np.asarray(out["attention_mask"])}


def _infolm_compute(
    forward: Callable,
    preds_tok: Dict[str, np.ndarray],
    target_tok: Dict[str, np.ndarray],
    temperature: float,
    idf: bool,
    measure: _InformationMeasure,
    special: Dict[str, int],
    batch_size: int,
) -> jnp.ndarray:
    preds_dist = _sentence_distributions(
        forward, preds_tok["input_ids"], preds_tok["attention_mask"], temperature, idf, special, batch_size
    )
    target_dist = _sentence_distributions(
        forward, target_tok["input_ids"], target_tok["attention_mask"], temperature, idf, special, batch_size
    )
    return measure(preds_dist, target_dist)


def infolm(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    model_name_or_path: str = "bert-base-uncased",
    temperature: float = 0.25,
    information_measure: str = "kl_divergence",
    idf: bool = True,
    alpha: Optional[float] = None,
    beta: Optional[float] = None,
    device: Optional[Any] = None,
    max_length: Optional[int] = None,
    batch_size: int = 64,
    num_threads: int = 0,
    verbose: bool = True,
    return_sentence_level_score: bool = False,
    model: Optional[Callable] = None,
    user_tokenizer: Any = None,
) -> Union[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Corpus-level InfoLM score (reference ``functional/text/infolm.py:553-662``).

    ``model``/``user_tokenizer`` extend the reference surface with the BERTScore
    seam so any masked LM (jax or torch) can drive the pipeline.
    """
    preds = [preds] if isinstance(preds, str) else list(preds)
    target = [target] if isinstance(target, str) else list(target)
    measure = _InformationMeasure(information_measure, alpha, beta)
    tokenizer, forward, max_len, special = _infolm_prepare(model_name_or_path, model, user_tokenizer, max_length)
    preds_tok = _infolm_tokenize(tokenizer, preds, max_len)
    target_tok = _infolm_tokenize(tokenizer, target, max_len)
    scores = _infolm_compute(forward, preds_tok, target_tok, temperature, idf, measure, special, batch_size)
    if return_sentence_level_score:
        return scores.mean(), scores
    return scores.mean()
