"""ASR error rates: CER / WER / MER / WIL / WIP (reference
``functional/text/{cer,wer,mer,wil,wip}.py``).

All five share one host-side tokenize + edit-distance pass and differ only in which
counts they keep, so a single update computes every statistic and each public facade
picks its slice.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax.numpy as jnp

from .helper import _as_list, _edit_distance

TextInput = Union[str, Sequence[str]]


def _asr_counts(preds: TextInput, target: TextInput, char_level: bool) -> Tuple[float, float, float, float]:
    """Returns (edit_errors, sum_max_len, target_total, preds_total)."""
    preds = _as_list(preds)
    target = _as_list(target)
    errors = total = target_total = preds_total = 0.0
    for pred, tgt in zip(preds, target):
        pred_tokens = list(pred) if char_level else pred.split()
        tgt_tokens = list(tgt) if char_level else tgt.split()
        errors += _edit_distance(pred_tokens, tgt_tokens)
        total += max(len(tgt_tokens), len(pred_tokens))
        target_total += len(tgt_tokens)
        preds_total += len(pred_tokens)
    return errors, total, target_total, preds_total


def _cer_update(preds: TextInput, target: TextInput):
    errors, _, target_total, _ = _asr_counts(preds, target, char_level=True)
    return jnp.asarray(errors), jnp.asarray(target_total)


def _cer_compute(errors, total):
    return errors / total


def char_error_rate(preds: TextInput, target: TextInput) -> jnp.ndarray:
    """CER = character edit distance / reference characters.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import char_error_rate
        >>> preds = ['this is the prediction']
        >>> target = ['this is the reference']
        >>> char_error_rate(preds, target)
        Array(0.3809524, dtype=float32, weak_type=True)
    """
    return _cer_compute(*_cer_update(preds, target))


def _wer_update(preds: TextInput, target: TextInput):
    errors, _, target_total, _ = _asr_counts(preds, target, char_level=False)
    return jnp.asarray(errors), jnp.asarray(target_total)


def _wer_compute(errors, total):
    return errors / total


def word_error_rate(preds: TextInput, target: TextInput) -> jnp.ndarray:
    """WER = word edit distance / reference words.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import word_error_rate
        >>> preds = ['this is the prediction']
        >>> target = ['this is the reference']
        >>> word_error_rate(preds, target)
        Array(0.25, dtype=float32, weak_type=True)
    """
    return _wer_compute(*_wer_update(preds, target))


def _mer_update(preds: TextInput, target: TextInput):
    errors, total, _, _ = _asr_counts(preds, target, char_level=False)
    return jnp.asarray(errors), jnp.asarray(total)


def _mer_compute(errors, total):
    return errors / total


def match_error_rate(preds: TextInput, target: TextInput) -> jnp.ndarray:
    """MER = word edit distance / max(reference, prediction) words.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import match_error_rate
        >>> preds = ['this is the prediction']
        >>> target = ['this is the reference']
        >>> match_error_rate(preds, target)
        Array(0.25, dtype=float32, weak_type=True)
    """
    return _mer_compute(*_mer_update(preds, target))


def _wil_wip_update(preds: TextInput, target: TextInput):
    errors, total, target_total, preds_total = _asr_counts(preds, target, char_level=False)
    # the reference folds hits as (edit_sum - maxlen_sum) into its "errors" state
    # (functional/text/wil.py:52) — kept verbatim for state-layout parity
    return jnp.asarray(errors - total), jnp.asarray(target_total), jnp.asarray(preds_total)


def _wil_compute(errors, target_total, preds_total):
    return 1 - ((errors / target_total) * (errors / preds_total))


def word_information_lost(preds: TextInput, target: TextInput) -> jnp.ndarray:
    """WIL = 1 - hit-rate product over reference and prediction lengths.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import word_information_lost
        >>> preds = ['this is the prediction']
        >>> target = ['this is the reference']
        >>> word_information_lost(preds, target)
        Array(0.4375, dtype=float32, weak_type=True)
    """
    return _wil_compute(*_wil_wip_update(preds, target))


def _wip_compute(errors, target_total, preds_total):
    return (errors / target_total) * (errors / preds_total)


def word_information_preserved(preds: TextInput, target: TextInput) -> jnp.ndarray:
    """WIP = hit-rate product over reference and prediction lengths.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import word_information_preserved
        >>> preds = ['this is the prediction']
        >>> target = ['this is the reference']
        >>> word_information_preserved(preds, target)
        Array(0.5625, dtype=float32, weak_type=True)
    """
    return _wip_compute(*_wil_wip_update(preds, target))
