"""chrF / chrF++ score (reference ``functional/text/chrf.py``).

Host-side character/word n-gram counting (plain float dicts instead of the reference's
per-n-gram tensors) feeding fixed-shape per-order count vectors — six ``(n,)`` sum
states. The corpus F-score is a tiny jnp expression.
"""

from __future__ import annotations

import string
from collections import defaultdict
from itertools import chain
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

_EPS_SMOOTHING = 1e-16
_PUNCTUATIONS = set(string.punctuation)


def _get_characters(sentence: str, whitespace: bool) -> List[str]:
    if whitespace:
        return list(sentence)
    return list(sentence.strip().replace(" ", ""))


def _separate_word_and_punctuation(word: str) -> List[str]:
    if len(word) == 1:
        return [word]
    if word[-1] in _PUNCTUATIONS:
        return [word[:-1], word[-1]]
    if word[0] in _PUNCTUATIONS:
        return [word[0], word[1:]]
    return [word]


def _get_words_and_punctuation(sentence: str) -> List[str]:
    return list(chain.from_iterable(_separate_word_and_punctuation(word) for word in sentence.strip().split()))


def _ngram_counts(tokens: List[str], n_gram_order: int) -> Dict[int, Dict[tuple, float]]:
    ngrams: Dict[int, Dict[tuple, float]] = {n: defaultdict(float) for n in range(1, n_gram_order + 1)}
    for n in range(1, n_gram_order + 1):
        for i in range(len(tokens) - n + 1):
            ngrams[n][tuple(tokens[i : i + n])] += 1
    return ngrams


def _sentence_counts(sentence: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool):
    if lowercase:
        sentence = sentence.lower()
    char_counts = _ngram_counts(_get_characters(sentence, whitespace), n_char_order)
    word_counts = _ngram_counts(_get_words_and_punctuation(sentence), n_word_order)
    char_totals = np.asarray([sum(char_counts[n].values()) for n in range(1, n_char_order + 1)])
    word_totals = np.asarray([sum(word_counts[n].values()) for n in range(1, n_word_order + 1)])
    return char_counts, word_counts, char_totals, word_totals


def _matches(hyp_counts, ref_counts, order: int) -> np.ndarray:
    out = np.zeros(order)
    for n in range(1, order + 1):
        out[n - 1] = sum(min(ref_counts[n][g], c) for g, c in hyp_counts[n].items() if g in ref_counts[n])
    return out


def _fscore(
    matching_char, matching_word, hyp_char, hyp_word, ref_char, ref_word, n_order: float, beta: float
) -> float:
    def per_order(matching, ref, hyp):
        precision = np.where(hyp > 0, matching / np.where(hyp > 0, hyp, 1.0), 0.0)
        recall = np.where(ref > 0, matching / np.where(ref > 0, ref, 1.0), 0.0)
        denominator = np.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
        return (1 + beta**2) * precision * recall / denominator

    return float(
        (per_order(matching_char, ref_char, hyp_char).sum() + per_order(matching_word, ref_word, hyp_word).sum())
        / n_order
    )


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int,
    n_word_order: int,
    beta: float,
    lowercase: bool,
    whitespace: bool,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, List[float]]:
    """Per-call contribution: (preds_char, preds_word, target_char, target_word,
    matching_char, matching_word) count vectors + sentence-level scores."""
    if isinstance(preds, str):
        preds = [preds]
    target = [[t] if isinstance(t, str) else t for t in target]
    n_order = float(n_char_order + n_word_order)
    tot = [np.zeros(n_char_order), np.zeros(n_word_order), np.zeros(n_char_order), np.zeros(n_word_order),
           np.zeros(n_char_order), np.zeros(n_word_order)]
    sentence_scores: List[float] = []
    for pred, targets in zip(preds, target):
        p_char_counts, p_word_counts, p_char_tot, p_word_tot = _sentence_counts(
            pred, n_char_order, n_word_order, lowercase, whitespace
        )
        best = (0.0, np.zeros(n_char_order), np.zeros(n_word_order), np.zeros(n_char_order), np.zeros(n_word_order))
        for tgt in targets:
            t_char_counts, t_word_counts, t_char_tot, t_word_tot = _sentence_counts(
                tgt, n_char_order, n_word_order, lowercase, whitespace
            )
            m_char = _matches(p_char_counts, t_char_counts, n_char_order)
            m_word = _matches(p_word_counts, t_word_counts, n_word_order)
            f = _fscore(m_char, m_word, p_char_tot, p_word_tot, t_char_tot, t_word_tot, n_order, beta)
            if f > best[0]:
                best = (f, m_char, m_word, t_char_tot, t_word_tot)
        sentence_scores.append(best[0])
        tot[0] += p_char_tot
        tot[1] += p_word_tot
        tot[2] += best[3]
        tot[3] += best[4]
        tot[4] += best[1]
        tot[5] += best[2]
    return (*tot, sentence_scores)


def _chrf_score_compute(
    preds_char, preds_word, target_char, target_word, matching_char, matching_word, n_order: float, beta: float
) -> jnp.ndarray:
    return jnp.asarray(
        _fscore(
            np.asarray(matching_char), np.asarray(matching_word), np.asarray(preds_char), np.asarray(preds_word),
            np.asarray(target_char), np.asarray(target_word), n_order, beta,
        ),
        jnp.float32,
    )


def _validate_chrf_args(n_char_order, n_word_order, beta) -> None:
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """chrF (``n_word_order=0``) / chrF++ (default) score against the best-matching
    reference per sentence.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import chrf_score
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> chrf_score(preds, target)
        Array(0.86404645, dtype=float32)
    """
    _validate_chrf_args(n_char_order, n_word_order, beta)
    n_order = float(n_char_order + n_word_order)
    *totals, sentence_scores = _chrf_score_update(
        preds, target, n_char_order, n_word_order, beta, lowercase, whitespace
    )
    score = _chrf_score_compute(*totals, n_order, beta)
    if return_sentence_level_score:
        return score, jnp.asarray(sentence_scores, jnp.float32)
    return score
