"""BERTScore (reference ``functional/text/bert.py``; Zhang et al., ICLR 2020).

The contextual embedder is pluggable: ``model_name_or_path`` loads a HF model from the
*local* cache (no egress), or ``model`` + ``user_tokenizer`` (+ optional
``user_forward_fn``) supply a custom pipeline — the same seam the reference exposes.
The matching math (normalized embeddings, special-token masking, IDF weighting, greedy
cosine alignment) is one fused jnp einsum pipeline.

Known deliberate divergence: the reference sorts sentences by length for batching and
applies the sorting permutation a second time instead of inverting it
(``functional/text/bert.py:563-567`` indexing with the output of
``helper_embedding_metric.py:79-84``), so its per-sentence scores come back
mis-ordered — and when predictions and references have different length orderings it
greedily matches the wrong sentence pairs. This implementation keeps input order
(there is no per-batch recompile to amortize under XLA's static shapes);
``tests/test_bertscore_hf.py`` checks parity modulo the reference's permutation.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ...utilities.imports import _module_available

_TRANSFORMERS_AVAILABLE = _module_available("transformers")


def _load_hf(model_name_or_path: str, num_layers: Optional[int]):
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`bert_score` metric with default models requires `transformers` package be installed."
            " Either install with `pip install transformers>=4.4` or `pip install torchmetrics[text]`."
        )
    import torch
    from transformers import AutoModel, AutoTokenizer

    try:
        tokenizer = AutoTokenizer.from_pretrained(model_name_or_path, local_files_only=True)
        hf_model = AutoModel.from_pretrained(model_name_or_path, local_files_only=True)
    except OSError as err:  # HF raises OSError subclasses for cache misses
        raise ModuleNotFoundError(
            f"Model {model_name_or_path!r} is not in the local HF cache and this environment has "
            "no network egress to download it. Pre-populate the cache offline, or pass "
            "`model` + `user_tokenizer` for a custom embedding pipeline."
        ) from err
    hf_model.eval()

    def forward(input_ids: np.ndarray, attention_mask: np.ndarray) -> np.ndarray:
        with torch.no_grad():
            out = hf_model(
                torch.as_tensor(input_ids), torch.as_tensor(attention_mask), output_hidden_states=True
            )
        layer = num_layers if num_layers is not None else -1
        return out.hidden_states[layer].numpy()

    return tokenizer, forward


def _tokenize(tokenizer, texts: List[str], max_length: int, truncation: bool) -> Dict[str, np.ndarray]:
    out = tokenizer(
        texts, padding=True, truncation=truncation, max_length=max_length if truncation else None,
        return_tensors="np",
    )
    return {"input_ids": np.asarray(out["input_ids"]), "attention_mask": np.asarray(out["attention_mask"])}


def _process_attention_mask_for_special_tokens(attention_mask: np.ndarray) -> np.ndarray:
    """Zero out the first token (CLS) and the last attended token (SEP) per row
    (reference helper_embedding_metric semantics)."""
    mask = attention_mask.copy().astype(np.float32)
    mask[:, 0] = 0
    last = attention_mask.sum(axis=1).astype(int) - 1
    mask[np.arange(mask.shape[0]), np.clip(last, 0, None)] = 0
    return mask


def _idf_weights(input_ids: np.ndarray, attention_mask: np.ndarray) -> Dict[int, float]:
    """log((N+1)/(df+1)) document-frequency IDF over the corpus rows; unseen tokens
    default to log(N+1) (reference helper_embedding_metric.py:259-261)."""
    num_docs = input_ids.shape[0]
    df: Counter = Counter()
    for row, mask in zip(input_ids, attention_mask):
        df.update(set(row[mask.astype(bool)].tolist()))
    weights = {tok: float(np.log((num_docs + 1) / (cnt + 1))) for tok, cnt in df.items()}
    weights["__default__"] = float(np.log(num_docs + 1))
    return weights


def _apply_idf(input_ids: np.ndarray, weights: Dict[int, float]) -> np.ndarray:
    default = weights.get("__default__", 0.0)
    lookup = np.vectorize(lambda t: weights.get(int(t), default), otypes=[np.float32])
    return lookup(input_ids)


def _pad_to(arr: np.ndarray, length: int, value: float = 0) -> np.ndarray:
    if arr.shape[1] >= length:
        return arr
    pad = np.full((arr.shape[0], length - arr.shape[1], *arr.shape[2:]), value, arr.dtype)
    return np.concatenate([arr, pad], axis=1)


def _embed(
    forward: Callable,
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    target_len: int,
    idf: bool,
    idf_lookup: Optional[Dict[int, float]],
    batch_size: int,
):
    """Normalized, special-token-masked embeddings + per-token scale weights."""
    emb_chunks = []
    for start in range(0, input_ids.shape[0], batch_size):
        emb_chunks.append(np.asarray(forward(input_ids[start : start + batch_size], attention_mask[start : start + batch_size])))
    emb = np.concatenate(emb_chunks) if emb_chunks else np.zeros((0, input_ids.shape[1], 1))
    emb = emb / np.clip(np.linalg.norm(emb, axis=-1, keepdims=True), 1e-12, None)
    processed_mask = _process_attention_mask_for_special_tokens(attention_mask)
    emb = emb * processed_mask[:, :, None]
    if idf:
        scale = _apply_idf(input_ids, idf_lookup) * processed_mask
    else:
        scale = processed_mask.astype(np.float32)
    scale = scale / np.clip(scale.sum(-1, keepdims=True), 1e-12, None)
    return _pad_to(emb, target_len), _pad_to(scale, target_len)


def _score_pairs(p_emb, p_scale, t_emb, t_scale):
    cos = jnp.einsum("bpd,brd->bpr", jnp.asarray(p_emb), jnp.asarray(t_emb))
    precision = (cos.max(axis=2) * jnp.asarray(p_scale)).sum(-1)
    recall = (cos.max(axis=1) * jnp.asarray(t_scale)).sum(-1)
    f1 = 2 * precision * recall / jnp.clip(precision + recall, 1e-12)
    return precision, recall, f1


def bert_score(
    preds: Union[str, Sequence[str], Dict[str, np.ndarray]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]], Dict[str, np.ndarray]],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Callable] = None,
    user_tokenizer: Any = None,
    user_forward_fn: Optional[Callable] = None,
    verbose: bool = False,
    idf: bool = False,
    device: Optional[Any] = None,
    max_length: int = 512,
    batch_size: int = 64,
    num_threads: int = 0,
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    baseline_url: Optional[str] = None,
    truncation: bool = False,
    score_fn: Optional[Callable] = None,
) -> Dict[str, jnp.ndarray]:
    """BERTScore precision/recall/F1 via greedy cosine matching of contextual
    embeddings. Multiple references per prediction score as the best F1.

    ``score_fn(p_emb, p_scale, t_emb, t_scale) -> (precision, recall, f1)`` replaces
    the default matching pipeline (:func:`_score_pairs`) — the seam the ``BERTScore``
    metric class uses to route scoring through its jitted, AOT-cacheable "escore"
    dispatch program instead of tracing fresh every compute."""
    if all_layers:
        raise ValueError("`all_layers=True` is only meaningful with per-layer baselines; use num_layers instead.")
    if rescale_with_baseline:
        raise ModuleNotFoundError(
            "`rescale_with_baseline` requires downloading the published baseline files, which an "
            "air-gapped environment cannot do."
        )
    if isinstance(preds, str):
        preds = [preds]
    multi_ref = (
        not isinstance(target, (str, dict))
        and len(target) > 0
        and isinstance(target[0], (list, tuple))
    )
    if multi_ref:
        results = []
        for ref_idx in range(max(len(t) for t in target)):
            flat_refs = [t[min(ref_idx, len(t) - 1)] for t in target]
            results.append(
                bert_score(
                    preds, flat_refs, model_name_or_path, num_layers, all_layers, model, user_tokenizer,
                    user_forward_fn, verbose, idf, device, max_length, batch_size, num_threads,
                    False, lang, rescale_with_baseline, baseline_path, baseline_url, truncation,
                    score_fn=score_fn,
                )
            )
        f1s = jnp.stack([r["f1"] for r in results])
        best = jnp.argmax(f1s, axis=0)
        pick = lambda key: jnp.take_along_axis(jnp.stack([r[key] for r in results]), best[None], axis=0)[0]
        return {"precision": pick("precision"), "recall": pick("recall"), "f1": pick("f1")}
    if isinstance(target, str):
        target = [target]

    if model is not None:
        if user_tokenizer is None and not isinstance(preds, dict):
            raise ValueError("The model must be accompanied by a `user_tokenizer` (or pre-tokenized dict inputs).")
        forward = (lambda ids, mask: user_forward_fn(model, {"input_ids": ids, "attention_mask": mask})) if user_forward_fn else model
        tokenizer = user_tokenizer
    else:
        tokenizer, forward = _load_hf(model_name_or_path or "roberta-large", num_layers)

    if isinstance(preds, dict):
        preds_tok = {"input_ids": np.asarray(preds["input_ids"]), "attention_mask": np.asarray(preds["attention_mask"])}
        target_tok = {"input_ids": np.asarray(target["input_ids"]), "attention_mask": np.asarray(target["attention_mask"])}
    else:
        preds_tok = _tokenize(tokenizer, list(preds), max_length, truncation)
        target_tok = _tokenize(tokenizer, list(target), max_length, truncation)
    if preds_tok["input_ids"].shape[0] != target_tok["input_ids"].shape[0]:
        raise ValueError("Number of predicted and reference sentences must be the same.")

    idf_lookup = _idf_weights(target_tok["input_ids"], target_tok["attention_mask"]) if idf else None
    target_len = max(preds_tok["input_ids"].shape[1], target_tok["input_ids"].shape[1])
    p_emb, p_scale = _embed(forward, preds_tok["input_ids"], preds_tok["attention_mask"], target_len, idf, idf_lookup, batch_size)
    t_emb, t_scale = _embed(forward, target_tok["input_ids"], target_tok["attention_mask"], target_len, idf, idf_lookup, batch_size)
    precision, recall, f1 = (score_fn or _score_pairs)(p_emb, p_scale, t_emb, t_scale)
    out = {"precision": precision, "recall": recall, "f1": f1}
    if return_hash:
        out["hash"] = f"{model_name_or_path}_L{num_layers}_idf={idf}"
    return out
