"""Extended Edit Distance (reference ``functional/text/eed.py``; Stanchev, Wang, Ney,
"EED: Extended Edit Distance Measure for Machine Translation", WMT 2019).

The CDER-style character DP with long-jump penalties runs host-side with the inner
deletion chain folded into a numpy prefix-min; sentence scores are cat rows.
"""

from __future__ import annotations

import re
import unicodedata
from math import inf
from typing import List, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .helper import _as_list


def _eed_function(
    hyp: str,
    ref: str,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """Character-level CDER alignment with long jumps at reference spaces and a
    coverage penalty for repeated visits."""
    hyp_chars = np.array(list(hyp)) if hyp else np.empty(0, dtype="<U1")
    n = len(hyp_chars)
    visits = np.full(n + 1, -1, np.int64)
    row = np.ones(n + 1)
    row[0] = 0.0
    for w in range(1, len(ref) + 1):
        ref_char = ref[w - 1]
        # candidate costs without the sequential deletion chain
        base = np.empty(n + 1)
        base[0] = row[0] + 1.0
        match_cost = row[:-1] + (hyp_chars != ref_char).astype(np.float64)
        base[1:] = np.minimum(match_cost, row[1:] + insertion)
        # deletion chain folded SEQUENTIALLY: a prefix-min with (i-k)*deletion rounds
        # differently from repeated `+deletion` and flips argmin tie-breaks (and with
        # them the coverage/long-jump terms) vs the published DP
        next_row = base.tolist()
        for i in range(1, n + 1):
            chained = next_row[i - 1] + deletion
            if chained < next_row[i]:
                next_row[i] = chained
        next_row = np.asarray(next_row)
        min_index = int(np.argmin(next_row))
        visits[min_index] += 1
        if ref_char == " ":
            next_row = np.minimum(next_row, alpha + next_row[min_index])
        row = next_row
    coverage = rho * float(np.where(visits >= 0, visits, 1).sum())
    return min(1.0, (row[-1] + coverage) / (float(len(ref)) + coverage))


def _preprocess_en(sentence: str) -> str:
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    sentence = sentence.rstrip()
    for pattern, replacement in ((".", " ."), ("!", " !"), ("?", " ?"), (",", " ,")):
        sentence = sentence.replace(pattern, replacement)
    for pattern, replacement in (
        (r"\s+", r" "),
        (r"(\d) ([.,]) (\d)", r"\1\2\3"),
        (r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1."),
    ):
        sentence = re.sub(pattern, replacement, sentence)
    for pattern, replacement in (("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")):
        sentence = sentence.replace(pattern, replacement)
    return f" {sentence} "


def _preprocess_ja(sentence: str) -> str:
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> List[float]:
    """Per-sentence best-reference EED scores."""
    preds = _as_list(preds)
    target = [[t] if isinstance(t, str) else list(t) for t in target]
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    if language == "en":
        preprocess = _preprocess_en
    elif language == "ja":
        preprocess = _preprocess_ja
    else:
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
    scores: List[float] = []
    for pred, refs in zip(preds, target):
        pred_p = preprocess(pred)
        best = inf
        for ref in refs:
            score = _eed_function(pred_p, preprocess(ref), alpha, rho, deletion, insertion)
            best = min(best, score)
        scores.append(best)
    return scores


def _eed_compute(sentence_level_scores) -> jnp.ndarray:
    arr = jnp.asarray(sentence_level_scores, jnp.float32)
    return arr.mean() if arr.size else jnp.asarray(0.0, jnp.float32)


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> Union[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Corpus EED averaged over sentence-level best-reference scores.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import extended_edit_distance
        >>> preds = ['this is the prediction']
        >>> extended_edit_distance(preds, [['this is the reference']])
        Array(0.38345864, dtype=float32)
    """
    for name, val in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
        if not isinstance(val, float) or val < 0:
            raise ValueError(f"Parameter `{name}` is expected to be a non-negative float.")
    scores = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    average = _eed_compute(scores)
    if return_sentence_level_score:
        return average, jnp.asarray(scores, jnp.float32)
    return average
