"""ROUGE score (reference ``functional/text/rouge.py``; algorithm follows the official
google-research rouge_scorer semantics).

Host-side tokenization/LCS producing per-sentence (precision, recall, fmeasure)
triples; the stateful class keeps them as cat rows per rouge key.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ...utilities.imports import _NLTK_AVAILABLE

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    "rouge1": 1, "rouge2": 2, "rouge3": 3, "rouge4": 4, "rouge5": 5,
    "rouge6": 6, "rouge7": 7, "rouge8": 8, "rouge9": 9, "rougeL": "L", "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")


_PUNKT_STATE = {"checked": False, "available": False}


def _punkt_available() -> bool:
    if _PUNKT_STATE["checked"]:
        return _PUNKT_STATE["available"]
    _PUNKT_STATE["checked"] = True
    if _NLTK_AVAILABLE:
        import nltk

        try:
            nltk.data.find("tokenizers/punkt_tab")
            _PUNKT_STATE["available"] = True
        except LookupError:
            try:
                nltk.download("punkt_tab", quiet=True, force=False, halt_on_error=False, raise_on_error=True)
                _PUNKT_STATE["available"] = True
            except Exception:
                _PUNKT_STATE["available"] = False
    return _PUNKT_STATE["available"]


def _split_sentence(x: str) -> Sequence[str]:
    """Sentence splitter for ROUGE-Lsum. Uses nltk punkt when available; otherwise a
    regex fallback (the reference hard-fails without the punkt download — an offline
    TPU pod shouldn't)."""
    x = re.sub("<n>", "", x)  # remove pegasus newline char
    if _punkt_available():
        import nltk

        return nltk.sent_tokenize(x)
    return [s for s in re.split(r"(?<=[.!?])\s+", x.strip()) if s]


def _compute_metrics(hits_or_lcs: int, pred_len: int, target_len: int) -> Dict[str, float]:
    precision = hits_or_lcs / pred_len
    recall = hits_or_lcs / target_len
    if precision == recall == 0.0:
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    fmeasure = 2 * precision * recall / (precision + recall)
    return {"precision": precision, "recall": recall, "fmeasure": fmeasure}


def _lcs_table(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> np.ndarray:
    """LCS DP table (numpy row sweep over the equality matrix)."""
    n, m = len(target_tokens), len(pred_tokens)
    table = np.zeros((n + 1, m + 1), np.int64)
    pred_arr = np.asarray(pred_tokens, object)
    for i in range(1, n + 1):
        eq = pred_arr == target_tokens[i - 1]
        row = table[i]
        prev = table[i - 1]
        for j in range(1, m + 1):  # LCS recurrence is inherently sequential in j
            row[j] = prev[j - 1] + 1 if eq[j - 1] else max(prev[j], row[j - 1])
    return table


def _lcs(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> int:
    return int(_lcs_table(pred_tokens, target_tokens)[-1, -1])


def _backtracked_lcs(table: np.ndarray, pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> List[int]:
    i, j = len(pred_tokens), len(target_tokens)
    out: List[int] = []
    while i > 0 and j > 0:
        if pred_tokens[i - 1] == target_tokens[j - 1]:
            out.insert(0, j - 1)
            i -= 1
            j -= 1
        elif table[j][i - 1] > table[j - 1][i]:
            i -= 1
        else:
            j -= 1
    return out


def _union_lcs(pred_tokens_list: Sequence[Sequence[str]], target_tokens: Sequence[str]) -> List[str]:
    indices: set = set()
    for pred_tokens in pred_tokens_list:
        table = _lcs_table(pred_tokens, target_tokens)  # indexed [target_j][pred_i]
        indices.update(_backtracked_lcs(table, pred_tokens, target_tokens))
    return [target_tokens[i] for i in sorted(indices)]


def _normalize_and_tokenize_text(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> List[str]:
    text = normalizer(text) if callable(normalizer) else re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if callable(tokenizer) else re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if (isinstance(x, str) and len(x) > 0)]


def _rouge_n_score(pred: Sequence[str], target: Sequence[str], n_gram: int) -> Dict[str, float]:
    def _create_ngrams(tokens: Sequence[str], n: int) -> Counter:
        return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))

    pred_ngrams, target_ngrams = _create_ngrams(pred, n_gram), _create_ngrams(target, n_gram)
    pred_len, target_len = sum(pred_ngrams.values()), sum(target_ngrams.values())
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    hits = sum(min(pred_ngrams[w], target_ngrams[w]) for w in set(pred_ngrams))
    return _compute_metrics(hits, max(pred_len, 1), max(target_len, 1))


def _rouge_l_score(pred: Sequence[str], target: Sequence[str]) -> Dict[str, float]:
    pred_len, target_len = len(pred), len(target)
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    return _compute_metrics(_lcs(pred, target), pred_len, target_len)


def _rouge_lsum_score(pred: Sequence[Sequence[str]], target: Sequence[Sequence[str]]) -> Dict[str, float]:
    pred_len = sum(map(len, pred))
    target_len = sum(map(len, target))
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    pred_counts = Counter()
    target_counts = Counter()
    for sentence in pred:
        pred_counts.update(sentence)
    for sentence in target:
        target_counts.update(sentence)
    hits = 0
    for tgt in target:
        for token in _union_lcs(pred, tgt):
            if pred_counts[token] > 0 and target_counts[token] > 0:
                hits += 1
                pred_counts[token] -= 1
                target_counts[token] -= 1
    return _compute_metrics(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: List[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, float]]]:
    """Per-sentence (best- or avg-over-references) score triples per rouge key."""
    results: Dict[Union[int, str], List[Dict[str, float]]] = {k: [] for k in rouge_keys_values}
    for pred_raw, target_raw in zip(preds, target):
        pred = _normalize_and_tokenize_text(pred_raw, stemmer, normalizer, tokenizer)
        if "Lsum" in rouge_keys_values:
            pred_lsum = [
                _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer) for s in _split_sentence(pred_raw)
            ]
        per_ref: List[Dict[Union[int, str], Dict[str, float]]] = []
        for target_raw_inner in target_raw:
            tgt = _normalize_and_tokenize_text(target_raw_inner, stemmer, normalizer, tokenizer)
            scores: Dict[Union[int, str], Dict[str, float]] = {}
            for rouge_key in rouge_keys_values:
                if isinstance(rouge_key, int):
                    scores[rouge_key] = _rouge_n_score(pred, tgt, rouge_key)
                elif rouge_key == "L":
                    scores[rouge_key] = _rouge_l_score(pred, tgt)
                else:  # Lsum
                    target_lsum = [
                        _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer)
                        for s in _split_sentence(target_raw_inner)
                    ]
                    scores[rouge_key] = _rouge_lsum_score(pred_lsum, target_lsum)
            per_ref.append(scores)
        for rouge_key in rouge_keys_values:
            if accumulate == "best":
                best = max(per_ref, key=lambda s: s[rouge_key]["fmeasure"])
                results[rouge_key].append(best[rouge_key])
            else:
                avg = {
                    t: float(np.mean([s[rouge_key][t] for s in per_ref]))
                    for t in ("precision", "recall", "fmeasure")
                }
                results[rouge_key].append(avg)
    return results


def _rouge_score_compute(sentence_results: Dict[str, List[float]]) -> Dict[str, jnp.ndarray]:
    return {k: jnp.asarray(np.mean(v), jnp.float32) for k, v in sentence_results.items()} if sentence_results else {}


def _resolve_rouge_keys(rouge_keys: Union[str, Tuple[str, ...]]) -> Tuple[Tuple[str, ...], List[Union[int, str]]]:
    if isinstance(rouge_keys, str):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")
    return tuple(rouge_keys), [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]


def _make_stemmer(use_stemmer: bool):
    if not use_stemmer:
        return None
    if not _NLTK_AVAILABLE:
        raise ModuleNotFoundError("Stemmer requires that `nltk` is installed. Use `pip install nltk`.")
    import nltk

    return nltk.stem.porter.PorterStemmer()


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, jnp.ndarray]:
    """ROUGE-N/L/Lsum precision/recall/F over the best (or averaged) reference.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import rouge_score
        >>> {k: round(float(v), 4) for k, v in rouge_score(['the cat is on the mat'], [['a cat is on the mat']], rouge_keys='rouge1').items()}
        {'rouge1_fmeasure': 0.8333, 'rouge1_precision': 0.8333, 'rouge1_recall': 0.8333}
    """
    if accumulate not in ALLOWED_ACCUMULATE_VALUES:
        raise ValueError(
            f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
        )
    stemmer = _make_stemmer(use_stemmer)
    keys, key_values = _resolve_rouge_keys(rouge_keys)
    if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
        # a flat list of strings is multi-reference for a single pred, else one ref each
        target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]
    sentence_results = _rouge_score_update(preds, target, key_values, accumulate, stemmer, normalizer, tokenizer)
    output: Dict[str, List[float]] = {}
    for key, key_value in zip(keys, key_values):
        for tp in ("fmeasure", "precision", "recall"):
            output[f"{key}_{tp}"] = [s[tp] for s in sentence_results[key_value]]
    return _rouge_score_compute(output)
