"""BLEU score (reference ``functional/text/bleu.py``).

Host-side n-gram counting producing four device-side sum states (numerator /
denominator per order, prediction / reference lengths — reference ``text/bleu.py:92-95``);
the final geometric mean + brevity penalty is pure jnp.
"""

from __future__ import annotations

from collections import Counter
from typing import Callable, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .helper import _count_ngram


def _tokenize_fn(sentence: str) -> Sequence[str]:
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[np.ndarray, np.ndarray, float, float]:
    """Returns (numerator, denominator, preds_len, target_len) contributions."""
    target_tok = [[tokenizer(line) if line else [] for line in t] for t in target]
    preds_tok = [tokenizer(line) if line else [] for line in preds]
    numerator = np.zeros(n_gram)
    denominator = np.zeros(n_gram)
    preds_len = 0.0
    target_len = 0.0
    for pred, targets in zip(preds_tok, target_tok):
        preds_len += len(pred)
        target_len_list = [len(tgt) for tgt in targets]
        target_len_diff = [abs(len(pred) - x) for x in target_len_list]
        target_len += target_len_list[target_len_diff.index(min(target_len_diff))]
        preds_counter: Counter = _count_ngram(pred, n_gram)
        target_counter: Counter = Counter()
        for tgt in targets:
            target_counter |= _count_ngram(tgt, n_gram)
        ngram_counter_clip = preds_counter & target_counter
        for counter_clip in ngram_counter_clip:
            numerator[len(counter_clip) - 1] += ngram_counter_clip[counter_clip]
        for counter in preds_counter:
            denominator[len(counter) - 1] += preds_counter[counter]
    return numerator, denominator, preds_len, target_len


def _bleu_score_compute(
    preds_len, target_len, numerator, denominator, n_gram: int, weights: Sequence[float], smooth: bool
) -> jnp.ndarray:
    numerator = jnp.asarray(numerator, jnp.float32)
    denominator = jnp.asarray(denominator, jnp.float32)
    preds_len = jnp.asarray(preds_len, jnp.float32)
    target_len = jnp.asarray(target_len, jnp.float32)
    if smooth:
        precision_scores = (numerator + 1.0) / (denominator + 1.0)
        precision_scores = precision_scores.at[0].set(numerator[0] / denominator[0])
    else:
        precision_scores = numerator / denominator
    log_precision_scores = jnp.asarray(list(weights), jnp.float32) * jnp.log(precision_scores)
    geometric_mean = jnp.exp(jnp.sum(log_precision_scores))
    brevity_penalty = jnp.where(preds_len > target_len, 1.0, jnp.exp(1 - (target_len / preds_len)))
    score = brevity_penalty * geometric_mean
    return jnp.where(jnp.min(numerator) == 0.0, 0.0, score)


def _resolve_weights(n_gram: int, weights: Optional[Sequence[float]]) -> Sequence[float]:
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    return weights if weights is not None else [1.0 / n_gram] * n_gram


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> jnp.ndarray:
    """Corpus BLEU of machine-translated text against one or more references.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import bleu_score
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> bleu_score(preds, target)
        Array(0.75983566, dtype=float32)
    """
    preds_ = [preds] if isinstance(preds, str) else preds
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
    if len(preds_) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
    weights = _resolve_weights(n_gram, weights)
    numerator, denominator, preds_len, target_len = _bleu_score_update(preds_, target_, n_gram)
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)
