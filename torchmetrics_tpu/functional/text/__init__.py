"""Text tower — stateless kernels (reference ``src/torchmetrics/functional/text/``)."""

from .bert import bert_score
from .infolm import infolm
from .asr import (
    char_error_rate,
    match_error_rate,
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)
from .bleu import bleu_score
from .chrf import chrf_score
from .edit import edit_distance
from .eed import extended_edit_distance
from .perplexity import perplexity
from .rouge import rouge_score
from .sacre_bleu import sacre_bleu_score
from .squad import squad
from .ter import translation_edit_rate

__all__ = [
    "bert_score",
    "infolm",
    "bleu_score",
    "char_error_rate",
    "chrf_score",
    "edit_distance",
    "extended_edit_distance",
    "match_error_rate",
    "perplexity",
    "rouge_score",
    "sacre_bleu_score",
    "squad",
    "translation_edit_rate",
    "word_error_rate",
    "word_information_lost",
    "word_information_preserved",
]
