"""Text metric helpers (reference ``functional/text/helper.py``).

String processing is host-side by design (SURVEY §2.6): tokenization and edit-distance
DP run on CPU, and only the resulting sufficient statistics become device arrays. The
edit-distance inner loop is vectorized with numpy (row-sweep DP) rather than the
reference's pure-Python cell loop.
"""

from __future__ import annotations

from collections import Counter
from typing import List, Sequence, Union

import numpy as np


def _edit_distance(prediction_tokens: Sequence, reference_tokens: Sequence, substitution_cost: int = 1) -> int:
    """Levenshtein distance between two token sequences (numpy row-sweep DP)."""
    n, m = len(prediction_tokens), len(reference_tokens)
    if n == 0:
        return m
    if m == 0:
        return n
    # map tokens to ints for vectorized equality
    vocab = {}
    a = np.asarray([vocab.setdefault(t, len(vocab)) for t in prediction_tokens], np.int64)
    b = np.asarray([vocab.setdefault(t, len(vocab)) for t in reference_tokens], np.int64)
    prev = np.arange(m + 1, dtype=np.int64)
    offsets = np.arange(m + 1, dtype=np.int64)
    for i in range(1, n + 1):
        sub = prev[:-1] + np.where(b != a[i - 1], substitution_cost, 0)
        delete = prev[1:] + 1
        vals = np.concatenate(([i], np.minimum(sub, delete)))
        # fold sequential insertions via prefix-min: cur[j] = min_{k<=j} vals[k] + (j-k)
        prev = np.minimum.accumulate(vals - offsets) + offsets
    return int(prev[m])


def _count_ngram(ngram_input_list: Sequence[str], n_gram: int) -> Counter:
    """Counts of all 1..n grams of a token list."""
    ngram_counter: Counter = Counter()
    for i in range(1, n_gram + 1):
        for j in range(len(ngram_input_list) - i + 1):
            ngram_counter[tuple(ngram_input_list[j : i + j])] += 1
    return ngram_counter


def _as_list(x: Union[str, Sequence[str]]) -> List[str]:
    return [x] if isinstance(x, str) else list(x)
