"""SacreBLEU (reference ``functional/text/sacre_bleu.py``; tokenizers follow the
public sacrebleu definitions — the tokenization rules ARE the compatibility surface).

Supported tokenizers: ``none``, ``13a`` (default), ``zh``, ``intl`` (needs the
``regex`` package), ``char``. The mecab/flores tokenizers require optional wheels not
present in this environment and raise a clear error.
"""

from __future__ import annotations

import re
from typing import ClassVar, Optional, Sequence, Union

import jax.numpy as jnp

from ...utilities.imports import _REGEX_AVAILABLE
from .bleu import _bleu_score_compute, _bleu_score_update, _resolve_weights

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")
_UCODE_RANGES = (
    ("㐀", "䶵"), ("一", "龥"), ("龦", "龻"), ("豈", "鶴"),
    ("侮", "頻"), ("並", "龎"), (" 0", "⩭6"), ("⾀0", "⾡d"),
    ("＀", "￯"), ("⺀", "⻿"), ("　", "〿"), ("㇀", "㇯"),
    ("⼀", "⿟"), ("⿰", "⿿"), ("㄀", "ㄯ"), ("ㆠ", "ㆿ"),
    ("︐", "︙"), ("︰", "﹏"), ("☀", "⛿"), ("✀", "➿"),
    ("㈀", "㋿"), ("㌀", "㏿"),
)


class _SacreBLEUTokenizer:
    """WMT-style tokenizers (sacrebleu semantics)."""

    _REGEX = (
        (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
        (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
        (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
        (re.compile(r"([0-9])(-)"), r"\1 \2 "),
    )
    _TOKENIZE_FN: ClassVar[dict] = {
        "none": "_tokenize_base",
        "13a": "_tokenize_13a",
        "zh": "_tokenize_zh",
        "intl": "_tokenize_international",
        "char": "_tokenize_char",
    }

    def __init__(self, tokenize: str = "13a", lowercase: bool = False) -> None:
        self._check_tokenizers_validity(tokenize)
        self.tokenize_fn = getattr(self, self._TOKENIZE_FN[tokenize])
        self.lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        tokenized_line = self.tokenize_fn(line)
        return self._lower(tokenized_line, self.lowercase).split()

    @classmethod
    def _check_tokenizers_validity(cls, tokenize: str) -> None:
        if tokenize not in cls._TOKENIZE_FN:
            raise ValueError(
                f"Argument `tokenize` expected to be one of {list(cls._TOKENIZE_FN)} but got {tokenize}."
            )
        if tokenize == "intl" and not _REGEX_AVAILABLE:
            raise ModuleNotFoundError(
                "`'intl'` tokenization requires that `regex` is installed. Use `pip install regex`."
            )

    @staticmethod
    def _lower(line: str, lowercase: bool) -> str:
        return line.lower() if lowercase else line

    @classmethod
    def _tokenize_regex(cls, line: str) -> str:
        for _re, repl in cls._REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @staticmethod
    def _is_chinese_char(uchar: str) -> bool:
        return any(start <= uchar <= end for start, end in _UCODE_RANGES)

    @classmethod
    def _tokenize_base(cls, line: str) -> str:
        return line

    @classmethod
    def _tokenize_13a(cls, line: str) -> str:
        line = line.replace("<skipped>", "").replace("-\n", "").replace("\n", " ")
        if "&" in line:
            line = line.replace("&quot;", '"').replace("&amp;", "&").replace("&lt;", "<").replace("&gt;", ">")
        return cls._tokenize_regex(f" {line} ")

    @classmethod
    def _tokenize_zh(cls, line: str) -> str:
        line = line.strip()
        line_in_chars = ""
        for char in line:
            if cls._is_chinese_char(char):
                line_in_chars += f" {char} "
            else:
                line_in_chars += char
        return cls._tokenize_regex(line_in_chars)

    @classmethod
    def _tokenize_international(cls, line: str) -> str:
        import regex

        int_regex = (
            (regex.compile(r"(\P{N})(\p{P})"), r"\1 \2 "),
            (regex.compile(r"(\p{P})(\P{N})"), r" \1 \2"),
            (regex.compile(r"(\p{S})"), r" \1 "),
        )
        for _re, repl in int_regex:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @classmethod
    def _tokenize_char(cls, line: str) -> str:
        return " ".join(char for char in line)

    @classmethod
    def tokenize(cls, line: str, tokenize: str, lowercase: bool = False) -> Sequence[str]:
        cls._check_tokenizers_validity(tokenize)
        tokenized_line = getattr(cls, cls._TOKENIZE_FN[tokenize])(line)
        return cls._lower(tokenized_line, lowercase).split()


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> jnp.ndarray:
    """BLEU with sacrebleu's standardized tokenization pipeline.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import sacre_bleu_score
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> sacre_bleu_score(preds, target)
        Array(0.75983566, dtype=float32)
    """
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
    if len(preds) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target_)}")
    weights = _resolve_weights(n_gram, weights)
    tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
    numerator, denominator, preds_len, target_len = _bleu_score_update(preds, target_, n_gram, tokenizer)
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)
