"""Translation Edit Rate (reference ``functional/text/ter.py``; algorithm follows the
Tercom/sacrebleu semantics: greedy block-shift search over a trace-producing,
beam-limited Levenshtein alignment).

All work is host-side; the class keeps two scalar sum states (edits, reference
length).
"""

from __future__ import annotations

import math
import re
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .helper import _as_list

_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_MAX_SHIFT_CANDIDATES = 1000
_BEAM_WIDTH = 25
_INT_INFINITY = int(1e16)

# edit-operation codes for the trace
_NOTHING, _SUB, _INS, _DEL, _UNDEF = 0, 1, 2, 3, 4


class _TercomTokenizer:
    """Tercom normalization/tokenization (sacrebleu ``tokenizer_ter`` semantics)."""

    _ASIAN_PUNCTUATION = r"([、。〈-】〔-〟｡-･・])"
    _FULL_WIDTH_PUNCTUATION = r"([．，？：；！＂（）])"

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
    ) -> None:
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support

    def __call__(self, sentence: str) -> str:
        if not sentence:
            return ""
        if self.lowercase:
            sentence = sentence.lower()
        if self.normalize:
            sentence = self._normalize_general_and_western(sentence)
            if self.asian_support:
                sentence = self._normalize_asian(sentence)
        if self.no_punctuation:
            sentence = self._remove_punct(sentence)
            if self.asian_support:
                sentence = self._remove_asian_punct(sentence)
        return " ".join(sentence.split())

    @staticmethod
    def _normalize_general_and_western(sentence: str) -> str:
        sentence = f" {sentence} "
        rules = [
            (r"\n-", ""),
            (r"\n", " "),
            (r"&quot;", '"'),
            (r"&amp;", "&"),
            (r"&lt;", "<"),
            (r"&gt;", ">"),
            (r"([{-~[-` -&(-+:-@/])", r" \1 "),
            (r"'s ", r" 's "),
            (r"'s$", r" 's"),
            (r"([^0-9])([\.,])", r"\1 \2 "),
            (r"([\.,])([^0-9])", r" \1 \2"),
            (r"([0-9])(-)", r"\1 \2 "),
        ]
        for pattern, replacement in rules:
            sentence = re.sub(pattern, replacement, sentence)
        return sentence

    @classmethod
    def _normalize_asian(cls, sentence: str) -> str:
        sentence = re.sub(r"([一-鿿㐀-䶿])", r" \1 ", sentence)
        sentence = re.sub(r"([㇀-㇯⺀-⻿])", r" \1 ", sentence)
        sentence = re.sub(r"([㌀-㏿豈-﫿︰-﹏])", r" \1 ", sentence)
        sentence = re.sub(r"([㈀-㼢])", r" \1 ", sentence)
        sentence = re.sub(r"(^|^[぀-ゟ])([぀-ゟ]+)(?=$|^[぀-ゟ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[゠-ヿ])([゠-ヿ]+)(?=$|^[゠-ヿ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[ㇰ-ㇿ])([ㇰ-ㇿ]+)(?=$|^[ㇰ-ㇿ])", r"\1 \2 ", sentence)
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r" \1 ", sentence)
        return re.sub(cls._FULL_WIDTH_PUNCTUATION, r" \1 ", sentence)

    @staticmethod
    def _remove_punct(sentence: str) -> str:
        return re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)

    @classmethod
    def _remove_asian_punct(cls, sentence: str) -> str:
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r"", sentence)
        return re.sub(cls._FULL_WIDTH_PUNCTUATION, r"", sentence)


def _levenshtein_with_trace(
    pred: List[str], ref: List[str], op_substitute: int = 1
) -> Tuple[int, List[int]]:
    """Beam-limited Levenshtein with backtrace (Tercom beam + tie preference
    substitute > delete > insert; the beam mirrors sacrebleu's lib_ter and is part of
    the compatibility surface — it changes results on length-disparate pairs)."""
    n, m = len(pred), len(ref)
    cost = [[_INT_INFINITY] * (m + 1) for _ in range(n + 1)]
    op = [[_UNDEF] * (m + 1) for _ in range(n + 1)]
    for j in range(m + 1):
        cost[0][j] = j
        op[0][j] = _INS
    length_ratio = m / n if pred else 1.0
    beam_width = math.ceil(length_ratio / 2 + _BEAM_WIDTH) if length_ratio / 2 > _BEAM_WIDTH else _BEAM_WIDTH
    for i in range(1, n + 1):
        pseudo_diag = math.floor(i * length_ratio)
        min_j = max(0, pseudo_diag - beam_width)
        max_j = m + 1 if i == n else min(m + 1, pseudo_diag + beam_width)
        for j in range(min_j, max_j):
            if j == 0:
                cost[i][j] = cost[i - 1][j] + 1
                op[i][j] = _DEL
            else:
                if pred[i - 1] == ref[j - 1]:
                    cands = ((cost[i - 1][j - 1], _NOTHING),)
                else:
                    cands = ((cost[i - 1][j - 1] + op_substitute, _SUB),)
                cands += ((cost[i - 1][j] + 1, _DEL), (cost[i][j - 1] + 1, _INS))
                for c, o in cands:
                    if cost[i][j] > c:
                        cost[i][j] = c
                        op[i][j] = o
    # backtrace
    trace: List[int] = []
    i, j = n, m
    while i > 0 or j > 0:
        o = op[i][j]
        trace.append(o)
        if o in (_NOTHING, _SUB):
            i -= 1
            j -= 1
        elif o == _INS:
            j -= 1
        elif o == _DEL:
            i -= 1
        else:  # pragma: no cover - beam always covers the backtrace path
            raise ValueError("Unknown operation in edit-distance backtrace")
    trace.reverse()
    return cost[n][m], trace


def _flip_trace(trace: List[int]) -> List[int]:
    return [_DEL if o == _INS else _INS if o == _DEL else o for o in trace]


def _trace_to_alignment(trace: List[int]) -> Tuple[Dict[int, int], List[int], List[int]]:
    """Alignment + per-side error flags from an edit trace, derived via cumulative
    position counters: the reference side advances on match/substitute/delete, the
    hypothesis side on match/substitute/insert; a reference position aligns to the
    hypothesis position current when it was consumed, and a position is an "error"
    unless its op was a match."""
    ops = np.asarray(trace, np.int64) if trace else np.zeros(0, np.int64)
    ref_step = ops != _INS
    hyp_step = ops != _DEL
    ref_pos = np.cumsum(ref_step) - 1
    hyp_pos = np.cumsum(hyp_step) - 1
    alignments = dict(zip(ref_pos[ref_step].tolist(), hyp_pos[ref_step].tolist()))
    ref_errors = (ops[ref_step] != _NOTHING).astype(int).tolist()
    hyp_errors = (ops[hyp_step] != _NOTHING).astype(int).tolist()
    return alignments, ref_errors, hyp_errors


def _find_shifted_pairs(pred_words: List[str], target_words: List[str]) -> Iterator[Tuple[int, int, int]]:
    """Common-run candidates ``(pred_start, target_start, 1..run_length)`` for every
    word shared between the sequences, found through a position index of the target
    side. Runs are capped by the Tercom shift-size/distance limits; enumeration is
    (pred_start, target_start, length)-ascending, which the candidate-budget cutoff
    depends on."""
    where_in_target: Dict[str, List[int]] = {}
    for j, word in enumerate(target_words):
        where_in_target.setdefault(word, []).append(j)
    for i, word in enumerate(pred_words):
        for j in where_in_target.get(word, ()):
            if abs(j - i) > _MAX_SHIFT_DIST:
                continue
            run = 1
            while (
                run < _MAX_SHIFT_SIZE - 1
                and i + run < len(pred_words)
                and j + run < len(target_words)
                and pred_words[i + run] == target_words[j + run]
            ):
                run += 1
            for length in range(1, run + 1):
                yield i, j, length


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Move ``words[start:start+length]`` so it lands at trace position ``target``:
    remove the block, then re-insert it (insertion index shifts down by the block
    length once the removal happens before it)."""
    block = words[start : start + length]
    rest = words[:start] + words[start + length :]
    ins = target - length if target > start + length else target
    return rest[:ins] + block + rest[ins:]


def _candidate_insertion_points(alignments: Dict[int, int], target_start: int, length: int) -> List[int]:
    """Hypothesis-side insertion indices for a block aimed at ``target_start``: just
    before the aligned position of each trace slot ``target_start-1 .. target_start+
    length-1``, stopping at the first unaligned slot. Aligned positions are
    non-decreasing, so set-dedup equals the adjacent-dedup Tercom performs."""
    out: List[int] = []
    for slot in range(target_start - 1, target_start + length):
        if slot == -1:
            idx = 0
        elif slot in alignments:
            idx = alignments[slot] + 1
        else:
            break
        if not out or idx != out[-1]:
            out.append(idx)
    return out


def _shift_words(
    pred_words: List[str],
    target_words: List[str],
    checked_candidates: int,
) -> Tuple[int, List[str], int]:
    """One round of the greedy Tercom shift search; returns the best gain."""
    edit_distance, inv_trace = _levenshtein_with_trace(pred_words, target_words)
    alignments, target_errors, pred_errors = _trace_to_alignment(_flip_trace(inv_trace))

    def gain_of(shifted: List[str]) -> int:
        return edit_distance - _levenshtein_with_trace(shifted, target_words)[0]

    best: Optional[tuple] = None
    for pred_start, target_start, length in _find_shifted_pairs(pred_words, target_words):
        span_already_right = sum(pred_errors[pred_start : pred_start + length]) == 0
        target_span_matched = sum(target_errors[target_start : target_start + length]) == 0
        shifts_within_itself = pred_start <= alignments[target_start] < pred_start + length
        if span_already_right or target_span_matched or shifts_within_itself:
            continue
        for idx in _candidate_insertion_points(alignments, target_start, length):
            shifted_words = _perform_shift(pred_words, pred_start, length, idx)
            # ties prefer longer blocks, then earlier sources, then earlier targets
            candidate = (gain_of(shifted_words), length, -pred_start, -idx, shifted_words)
            checked_candidates += 1
            if best is None or candidate > best:
                best = candidate
        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break
    if best is None:
        return 0, pred_words, checked_candidates
    return best[0], best[4], checked_candidates


def _translation_edit_rate(pred_words: List[str], target_words: List[str]) -> float:
    """Shifts + remaining edit distance between one hypothesis and one reference."""
    if len(target_words) == 0:
        return 0.0
    num_shifts = 0
    checked_candidates = 0
    input_words = pred_words
    while True:
        delta, new_input_words, checked_candidates = _shift_words(input_words, target_words, checked_candidates)
        if checked_candidates >= _MAX_SHIFT_CANDIDATES or delta <= 0:
            break
        num_shifts += 1
        input_words = new_input_words
    edit_distance, _ = _levenshtein_with_trace(input_words, target_words)
    return float(num_shifts + edit_distance)


def _compute_sentence_statistics(pred_words: List[str], target_words: List[List[str]]) -> Tuple[float, float]:
    tgt_lengths = 0.0
    best_num_edits = 2e16
    for tgt_words in target_words:
        # NOTE: argument order follows the reference (ter.py:371): the reference
        # sentence is the one being shifted toward the hypothesis
        num_edits = _translation_edit_rate(tgt_words, pred_words)
        tgt_lengths += len(tgt_words)
        if num_edits < best_num_edits:
            best_num_edits = num_edits
    avg_tgt_len = tgt_lengths / len(target_words) if target_words else 0.0
    return best_num_edits, avg_tgt_len


def _compute_ter_score_from_statistics(num_edits: float, tgt_length: float) -> float:
    if tgt_length > 0 and num_edits > 0:
        return num_edits / tgt_length
    if tgt_length == 0 and num_edits > 0:
        return 1.0
    return 0.0


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    tokenizer: _TercomTokenizer,
) -> Tuple[float, float, List[float]]:
    """Per-call (total_edits, total_target_length, sentence_ter) contribution."""
    preds = _as_list(preds)
    target = [[t] if isinstance(t, str) else list(t) for t in target]
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    total_num_edits = 0.0
    total_tgt_length = 0.0
    sentence_ter: List[float] = []
    for pred, tgt in zip(preds, target):
        tgt_words_ = [tokenizer(_tgt.rstrip()).split() for _tgt in tgt]
        pred_words_ = tokenizer(pred.rstrip()).split()
        num_edits, tgt_length = _compute_sentence_statistics(pred_words_, tgt_words_)
        total_num_edits += num_edits
        total_tgt_length += tgt_length
        sentence_ter.append(_compute_ter_score_from_statistics(num_edits, tgt_length))
    return total_num_edits, total_tgt_length, sentence_ter


def _ter_compute(total_num_edits, total_tgt_length) -> jnp.ndarray:
    return jnp.asarray(
        _compute_ter_score_from_statistics(float(total_num_edits), float(total_tgt_length)), jnp.float32
    )


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Corpus TER (Tercom/sacrebleu-compatible block-shift edit rate).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import translation_edit_rate
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> translation_edit_rate(preds, target)
        Array(0.15384616, dtype=float32)
    """
    for name, val in (
        ("normalize", normalize), ("no_punctuation", no_punctuation),
        ("lowercase", lowercase), ("asian_support", asian_support),
    ):
        if not isinstance(val, bool):
            raise ValueError(f"Expected argument `{name}` to be of type boolean but got {val}.")
    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    total_num_edits, total_tgt_length, sentence_ter = _ter_update(preds, target, tokenizer)
    score = _ter_compute(total_num_edits, total_tgt_length)
    if return_sentence_level_score:
        return score, jnp.asarray(sentence_ter, jnp.float32)
    return score
