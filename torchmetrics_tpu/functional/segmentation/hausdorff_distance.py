"""Hausdorff distance for semantic segmentation
(reference ``functional/segmentation/hausdorff_distance.py``).

TPU design: fully vectorized over (batch, class) via masked static-shape edge sets —
the reference loops ``for b: for c:`` on host with dynamic coordinate gathers.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .utils import _segmentation_inputs_format, edge_surface_distance

Array = jax.Array


def _hausdorff_distance_validate_args(
    num_classes: int,
    include_background: bool,
    distance_metric: str = "euclidean",
    spacing: Optional[Union[Array, Sequence[float]]] = None,
    directed: bool = False,
    input_format: str = "one-hot",
) -> None:
    if num_classes <= 0:
        raise ValueError(f"Expected argument `num_classes` must be a positive integer, but got {num_classes}.")
    if not isinstance(include_background, bool):
        raise ValueError(f"Expected argument `include_background` must be a boolean, but got {include_background}.")
    if distance_metric not in ["euclidean", "chessboard", "taxicab"]:
        raise ValueError(
            f"Arg `distance_metric` must be one of 'euclidean', 'chessboard', 'taxicab', but got {distance_metric}."
        )
    if spacing is not None and not isinstance(spacing, (list, tuple)) and not hasattr(spacing, "shape"):
        raise ValueError(f"Arg `spacing` must be a list or tensor, but got {type(spacing)}.")
    if not isinstance(directed, bool):
        raise ValueError(f"Expected argument `directed` must be a boolean, but got {directed}.")
    if input_format not in ["one-hot", "index", "mixed"]:
        raise ValueError(
            f"Expected argument `input_format` to be one of 'one-hot', 'index', 'mixed', but got {input_format}."
        )


def hausdorff_distance(
    preds: Array,
    target: Array,
    num_classes: int,
    include_background: bool = False,
    distance_metric: str = "euclidean",
    spacing: Optional[Union[Array, Sequence[float]]] = None,
    directed: bool = False,
    input_format: str = "one-hot",
) -> Array:
    """Hausdorff distance per (sample, class): ``(N, C)`` (reference hausdorff_distance.py:50).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import hausdorff_distance
        >>> preds = jnp.asarray([[[0, 1, 1, 0], [1, 1, 0, 0], [2, 2, 1, 0], [2, 0, 0, 0]]])
        >>> target = jnp.asarray([[[0, 1, 1, 0], [1, 0, 0, 0], [2, 2, 0, 0], [2, 2, 0, 0]]])
        >>> hausdorff_distance(preds, target, num_classes=3, input_format='index')
        Array([[2., 1.]], dtype=float32)
    """
    _hausdorff_distance_validate_args(num_classes, include_background, distance_metric, spacing, directed, input_format)
    preds, target = _segmentation_inputs_format(preds, target, include_background, num_classes, input_format)
    if directed:
        return edge_surface_distance(preds, target, distance_metric, spacing, symmetric=False)
    d_pt, d_tp = edge_surface_distance(preds, target, distance_metric, spacing, symmetric=True)
    return jnp.maximum(d_pt, d_tp)
