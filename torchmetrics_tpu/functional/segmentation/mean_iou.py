"""Mean IoU for semantic segmentation (reference ``functional/segmentation/mean_iou.py``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.compute import _safe_divide
from .utils import _segmentation_inputs_format

Array = jax.Array


def _mean_iou_reshape_args(preds: Array, target: Array, input_format: str = "one-hot") -> Tuple[Array, Array]:
    """Promote 1D/2D index inputs to a leading batch axis (reference mean_iou.py:25)."""
    if input_format == "one-hot":
        return preds, target
    if preds.ndim == 1:
        preds = preds[None, None]
    elif preds.ndim == 2:
        preds = preds[None]
    if target.ndim == 1:
        target = target[None, None]
    elif target.ndim == 2:
        target = target[None]
    return preds, target


def _mean_iou_validate_args(
    num_classes: Optional[int],
    include_background: bool,
    per_class: bool,
    input_format: str = "one-hot",
) -> None:
    if input_format == "index" and num_classes is None:
        raise ValueError("Argument `num_classes` must be provided when `input_format` is 'index'.")
    if num_classes is not None and num_classes <= 0:
        raise ValueError(f"Expected argument `num_classes` must be `None` or a positive integer, but got {num_classes}.")
    if not isinstance(include_background, bool):
        raise ValueError(f"Expected argument `include_background` must be a boolean, but got {include_background}.")
    if not isinstance(per_class, bool):
        raise ValueError(f"Expected argument `per_class` must be a boolean, but got {per_class}.")
    if input_format not in ["one-hot", "index", "mixed"]:
        raise ValueError(
            f"Expected argument `input_format` to be one of 'one-hot', 'index', 'mixed', but got {input_format}."
        )


def _mean_iou_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    include_background: bool = False,
    input_format: str = "one-hot",
) -> Tuple[Array, Array]:
    """Per-sample-per-class intersection/union counts (reference mean_iou.py:69)."""
    preds, target = _mean_iou_reshape_args(jnp.asarray(preds), jnp.asarray(target), input_format)
    preds, target = _segmentation_inputs_format(preds, target, include_background, num_classes, input_format)
    reduce_axis = tuple(range(2, preds.ndim))
    predf = preds.astype(jnp.float32)
    targf = target.astype(jnp.float32)
    intersection = jnp.sum(predf * targf, axis=reduce_axis)
    union = jnp.sum(targf, axis=reduce_axis) + jnp.sum(predf, axis=reduce_axis) - intersection
    return intersection, union


def _mean_iou_compute(intersection: Array, union: Array, zero_division) -> Array:
    return _safe_divide(intersection, union, zero_division=zero_division)


def mean_iou(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    include_background: bool = True,
    per_class: bool = False,
    input_format: str = "one-hot",
) -> Array:
    """Mean Intersection over Union; absent classes score -1 per class, and are skipped
    in the averaged value (reference mean_iou.py:98).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import mean_iou
        >>> preds = jnp.asarray([[[0, 1, 1, 0], [1, 1, 0, 0], [2, 2, 1, 0], [2, 0, 0, 0]]])
        >>> target = jnp.asarray([[[0, 1, 1, 0], [1, 0, 0, 0], [2, 2, 0, 0], [2, 2, 0, 0]]])
        >>> mean_iou(preds, target, num_classes=3, input_format='index')
        Array([0.6833334], dtype=float32)
    """
    _mean_iou_validate_args(num_classes, include_background, per_class, input_format)
    intersection, union = _mean_iou_update(preds, target, num_classes, include_background, input_format)
    scores = _mean_iou_compute(intersection, union, zero_division=jnp.nan)
    valid_classes = union > 0
    if per_class:
        return jnp.nan_to_num(scores, nan=-1.0)
    return jnp.nansum(scores, axis=-1) / jnp.sum(valid_classes, axis=-1)
