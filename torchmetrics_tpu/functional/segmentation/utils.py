"""Segmentation input formatting + surface-distance kernels (TPU-first).

Parity: reference ``functional/segmentation/utils.py`` (_segmentation_inputs_format:52,
_ignore_background:27, binary_erosion:195, surface_distance:423, edge_surface_distance).

TPU design notes:
- one-hot conversion via ``jax.nn.one_hot`` (static C axis) instead of
  ``torch.nn.functional.one_hot``; logits/probabilities collapse through argmax.
- binary erosion is a ``lax.reduce_window`` min over the structuring-element window
  (masked-min formulation) — no conv weights, fuses on TPU.
- surface distances use a *masked pairwise* formulation on static pixel grids: the
  reference gathers edge coordinates dynamically (``x[mask]``), which XLA cannot jit;
  here non-edge pixels are masked to +/-inf so shapes stay static, and the pairwise
  distance matrix is processed in row chunks to bound memory.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from ...utilities.checks import _check_same_shape as _check_same_shape_host

Array = jax.Array

_INF = 1e30


def _ignore_background(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Drop class channel 0 (assumed background). Reference utils.py:27."""
    preds = preds[:, 1:] if preds.shape[1] > 1 else preds
    target = target[:, 1:] if target.shape[1] > 1 else target
    return preds, target




def _check_mixed_shape(preds, target) -> None:
    """Reference utils.py:34."""
    if preds.ndim == target.ndim + 1:
        if preds.shape[0] != target.shape[0] or preds.shape[2:] != target.shape[1:]:
            raise RuntimeError(
                f"Predictions and targets are expected to have the same shape, got {preds.shape} and {target.shape}."
            )
    elif preds.ndim + 1 == target.ndim:
        if preds.shape[0] != target.shape[0] or preds.shape[1:] != target.shape[2:]:
            raise RuntimeError(
                f"Predictions and targets are expected to have the same shape, got {preds.shape} and {target.shape}."
            )
    else:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, got {preds.shape} and {target.shape}."
        )


def _one_hot_channels(x: Array, num_classes: int) -> Array:
    """Integer labels ``(N, *spatial)`` -> one-hot ``(N, C, *spatial)`` (int32)."""
    return jnp.moveaxis(jax.nn.one_hot(x, num_classes, dtype=jnp.int32), -1, 1)


def _format_logits(x: Array, num_classes: int) -> Array:
    """Float logits/probabilities ``(N, C, *spatial)`` -> integer one-hot. Reference utils.py:97."""
    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        return _one_hot_channels(jnp.argmax(x, axis=1), num_classes)
    return x


def _get_num_classes(x) -> int:
    if x.ndim < 2:
        raise IndexError(f"Cannot determine `num_classes` from tensor with shape {x.shape}.")
    num_classes = x.shape[1]
    if num_classes == 0:
        raise ValueError(f"Expected argument `num_classes` to be a positive integer, but got {num_classes}.")
    return num_classes


def _segmentation_inputs_format(
    preds: Array,
    target: Array,
    include_background: bool,
    num_classes: Optional[int] = None,
    input_format: str = "one-hot",
) -> Tuple[Array, Array]:
    """Check and convert inputs to integer one-hot ``(N, C, *spatial)``. Reference utils.py:52."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if input_format == "mixed":
        _check_mixed_shape(preds, target)
    else:
        _check_same_shape_host(preds, target)

    if input_format == "index":
        if num_classes is None:
            raise ValueError("Argument `num_classes` must be provided when `input_format='index'`.")
        preds = _one_hot_channels(preds, num_classes)
        target = _one_hot_channels(target, num_classes)
    elif input_format == "one-hot":
        if num_classes is None:
            num_classes = _get_num_classes(preds)
        preds = _format_logits(preds, num_classes)
        target = _format_logits(target, num_classes)
    elif input_format == "mixed":
        if preds.ndim == target.ndim + 1:
            if num_classes is None:
                num_classes = _get_num_classes(preds)
            preds = _format_logits(preds, num_classes)
            target = _one_hot_channels(target, num_classes)
        elif preds.ndim + 1 == target.ndim:
            if num_classes is None:
                num_classes = _get_num_classes(target)
            target = _format_logits(target, num_classes)
            preds = _one_hot_channels(preds, num_classes)

    if preds.ndim < 3:
        raise ValueError(f"Expected both `preds` and `target` to have at least 3 dimensions, but got {preds.ndim}.")

    if not include_background:
        preds, target = _ignore_background(preds, target)
    return preds, target


def generate_binary_structure(rank: int, connectivity: int):
    """Structuring element a la scipy.ndimage (reference utils.py:152): True where the
    taxicab distance from the center is <= connectivity. Host-side (numpy) — it is
    static trace-time data, never a traced value."""
    import numpy as np

    if connectivity < 1:
        out = np.zeros((3,) * rank, dtype=bool)
        out[(1,) * rank] = True
        return out
    grids = np.meshgrid(*[np.abs(np.arange(-1, 2))] * rank, indexing="ij")
    return sum(grids) <= connectivity


def binary_erosion(image: Array, structure: Optional[Array] = None, border_value: int = 0) -> Array:
    """Binary erosion of an ``(N, C, *spatial)`` mask (reference utils.py:195).

    Masked-min formulation: a pixel survives iff the minimum of the image over the
    True positions of the structuring element (centered on it) is 1. Non-structure
    window positions are ignored by substituting 1 there.
    """
    import numpy as np

    image = jnp.asarray(image)
    spatial = image.shape[2:]
    rank = len(spatial)
    if structure is None:
        structure = generate_binary_structure(rank, 1)
    structure_np = np.asarray(structure).astype(bool)
    win = structure_np.shape
    pad = [(w // 2, w - 1 - w // 2) for w in win]
    padded = jnp.pad(
        image.astype(jnp.float32),
        [(0, 0), (0, 0)] + pad,
        constant_values=float(border_value),
    )
    # min over the structure's True offsets via explicit shifts (structure is tiny: 3^rank)
    out = jnp.ones(image.shape, jnp.float32)
    for offset in np.argwhere(structure_np):
        idx = tuple(slice(int(o), int(o) + s) for o, s in zip(offset, spatial))
        out = jnp.minimum(out, padded[(slice(None), slice(None), *idx)])
    return out.astype(image.dtype)


def _mask_edges(mask: Array) -> Array:
    """Edge pixels of a binary mask: mask & ~erosion(mask). Matches the reference's
    ``mask_edges`` (XOR with the eroded mask)."""
    eroded = binary_erosion(mask)
    return (mask.astype(bool)) & (~eroded.astype(bool))


def _pixel_coords(spatial: Sequence[int], spacing: Optional[Sequence[float]] = None) -> Array:
    """Static ``(prod(spatial), rank)`` float coordinate grid scaled by spacing."""
    grids = jnp.meshgrid(*[jnp.arange(s, dtype=jnp.float32) for s in spatial], indexing="ij")
    coords = jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    if spacing is not None:
        coords = coords * jnp.asarray(spacing, jnp.float32)
    return coords


def _chunk_pixel_distance(chunk_coords: Array, coords: Array, metric: str) -> Array:
    """``(K, P)`` distances from a row chunk of pixels to all pixels."""
    diff = jnp.abs(chunk_coords[:, None, :] - coords[None, :, :])
    if metric == "euclidean":
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1))
    if metric == "chessboard":
        return jnp.max(diff, axis=-1)
    if metric == "taxicab":
        return jnp.sum(diff, axis=-1)
    raise ValueError(f"Arg `distance_metric` must be one of 'euclidean', 'chessboard', 'taxicab', but got {metric}.")


_HAUSDORFF_CHUNK = 2048  # rows of the pairwise block processed at once (K*P floats live)


def _directed_hausdorff_from_masks(
    edge_a: Array, edge_b: Array, coords: Array, metric: str = "euclidean"
) -> Array:
    """max over edge pixels of A of (min distance to edge pixels of B).

    ``edge_a``/``edge_b``: flat boolean masks ``(..., P)``; ``coords``: ``(P, rank)``.
    The pairwise distance block is never materialized whole: rows are processed in
    chunks of ``_HAUSDORFF_CHUNK`` via ``lax.map``, keeping peak memory at
    ``K * P`` floats regardless of batch/class count (the reference instead gathers
    edge coordinates dynamically, which XLA cannot jit). Empty edge sets produce 0
    (the reference errors on empty sets)."""
    P = coords.shape[0]
    lead = edge_a.shape[:-1]
    chunk = min(_HAUSDORFF_CHUNK, P)
    n_chunks = -(-P // chunk)
    pad = n_chunks * chunk - P
    coords_pad = jnp.pad(coords, ((0, pad), (0, 0)))
    a_flat = jnp.pad(edge_a.reshape(-1, P), ((0, 0), (0, pad)))
    b_flat = edge_b.reshape(-1, P)

    def one_pair(ab):
        a_pad, b = ab  # (P+pad,), (P,)

        def body(ci):
            c = jax.lax.dynamic_slice_in_dim(coords_pad, ci * chunk, chunk, axis=0)
            a = jax.lax.dynamic_slice_in_dim(a_pad, ci * chunk, chunk, axis=0)
            d = _chunk_pixel_distance(c, coords, metric)  # (K, P)
            min_b = jnp.min(jnp.where(b[None, :], d, _INF), axis=-1)  # (K,)
            return jnp.max(jnp.where(a, min_b, -_INF))

        return jnp.max(jax.lax.map(body, jnp.arange(n_chunks)))

    max_a = jax.lax.map(one_pair, (a_flat, b_flat)).reshape(lead)
    any_a = jnp.any(edge_a, axis=-1)
    any_b = jnp.any(edge_b, axis=-1)
    return jnp.where(any_a & any_b, max_a, 0.0)


def edge_surface_distance(
    preds: Array,
    target: Array,
    distance_metric: str = "euclidean",
    spacing: Optional[Sequence[float]] = None,
    symmetric: bool = False,
):
    """Hausdorff-style edge surface distances for ``(N, C, *spatial)`` masks.

    Returns the directed Hausdorff value ``(N, C)`` (or a tuple of both directions when
    ``symmetric``). Vectorized over batch and class; the reference loops b, c on host
    (functional/segmentation/hausdorff_distance.py:124-135).
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    spatial = preds.shape[2:]
    edges_p = _mask_edges(preds).reshape(preds.shape[0], preds.shape[1], -1)
    edges_t = _mask_edges(target).reshape(target.shape[0], target.shape[1], -1)
    coords = _pixel_coords(spatial, spacing)
    d_pt = _directed_hausdorff_from_masks(edges_p, edges_t, coords, distance_metric)
    if not symmetric:
        return d_pt
    d_tp = _directed_hausdorff_from_masks(edges_t, edges_p, coords, distance_metric)
    return d_pt, d_tp
