"""Dice score for semantic segmentation (reference ``functional/segmentation/dice.py``).

Per-sample-per-class sufficient statistics (numerator/denominator/support) reduce over
static spatial axes in one fused pass; every averaging mode is a pure reduction over the
``(N, C)`` stat matrices.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.compute import _safe_divide
from .utils import _segmentation_inputs_format

Array = jax.Array


def _dice_score_validate_args(
    num_classes: int,
    include_background: bool,
    average: Optional[str] = "micro",
    input_format: str = "one-hot",
    aggregation_level: Optional[str] = "samplewise",
) -> None:
    if not isinstance(num_classes, int) or num_classes <= 0:
        raise ValueError(f"Expected argument `num_classes` must be a positive integer, but got {num_classes}.")
    if not isinstance(include_background, bool):
        raise ValueError(f"Expected argument `include_background` must be a boolean, but got {include_background}.")
    allowed_average = ["micro", "macro", "weighted", "none"]
    if average is not None and average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average} or None, but got {average}.")
    if input_format not in ["one-hot", "index", "mixed"]:
        raise ValueError(
            f"Expected argument `input_format` to be one of 'one-hot', 'index', 'mixed', but got {input_format}."
        )
    if aggregation_level not in ("samplewise", "global"):
        raise ValueError(
            f"Expected argument `aggregation_level` to be one of `samplewise`, `global`, but got {aggregation_level}"
        )


def _dice_score_update(
    preds: Array,
    target: Array,
    num_classes: int,
    include_background: bool,
    input_format: str = "one-hot",
) -> Tuple[Array, Array, Array]:
    """Per-sample-per-class 2*intersection / cardinality / support. Reference dice.py:50."""
    preds, target = _segmentation_inputs_format(preds, target, include_background, num_classes, input_format)
    reduce_axis = tuple(range(2, target.ndim))
    predf = preds.astype(jnp.float32)
    targf = target.astype(jnp.float32)
    intersection = jnp.sum(predf * targf, axis=reduce_axis)
    target_sum = jnp.sum(targf, axis=reduce_axis)
    pred_sum = jnp.sum(predf, axis=reduce_axis)
    return 2.0 * intersection, pred_sum + target_sum, target_sum


def _dice_score_compute(
    numerator: Array,
    denominator: Array,
    average: Optional[str] = "micro",
    aggregation_level: Optional[str] = "samplewise",
    support: Optional[Array] = None,
) -> Array:
    """Reference dice.py:71 — nan marks absent classes, which every averaging mode skips."""
    if aggregation_level == "global":
        numerator = jnp.sum(numerator, axis=0)[None]
        denominator = jnp.sum(denominator, axis=0)[None]
        support = jnp.sum(support, axis=0) if support is not None else None

    if average == "micro":
        return _safe_divide(jnp.sum(numerator, axis=-1), jnp.sum(denominator, axis=-1), zero_division=jnp.nan)

    dice = _safe_divide(numerator, denominator, zero_division=jnp.nan)
    if average == "macro":
        return jnp.nanmean(dice, axis=-1)
    if average == "weighted":
        if support is None:
            raise ValueError("Expected argument `support` to be provided for weighted averaging.")
        weights = _safe_divide(support, jnp.sum(support, axis=-1, keepdims=True), zero_division=jnp.nan)
        nan_mask = jnp.all(jnp.isnan(dice), axis=-1)
        out = jnp.nansum(dice * weights, axis=-1)
        return jnp.where(nan_mask, jnp.nan, out)
    if average in ("none", None):
        return dice
    raise ValueError(f"Invalid value for `average`: {average}.")


def dice_score(
    preds: Array,
    target: Array,
    num_classes: int,
    include_background: bool = True,
    average: Optional[str] = "macro",
    input_format: str = "one-hot",
    aggregation_level: Optional[str] = "samplewise",
) -> Array:
    """Compute the Dice score for semantic segmentation (reference dice.py:105).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import dice_score
        >>> preds = jnp.asarray([[[0, 1, 1, 0], [1, 1, 0, 0], [2, 2, 1, 0], [2, 0, 0, 0]]])
        >>> target = jnp.asarray([[[0, 1, 1, 0], [1, 0, 0, 0], [2, 2, 0, 0], [2, 2, 0, 0]]])
        >>> dice_score(preds, target, num_classes=3, input_format='index')
        Array([0.81022406], dtype=float32)
    """
    _dice_score_validate_args(num_classes, include_background, average, input_format, aggregation_level)
    numerator, denominator, support = _dice_score_update(preds, target, num_classes, include_background, input_format)
    return _dice_score_compute(numerator, denominator, average, aggregation_level=aggregation_level, support=support)
