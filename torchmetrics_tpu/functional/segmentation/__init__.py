"""Stateless segmentation kernels (reference ``functional/segmentation/``)."""

from .dice import dice_score
from .generalized_dice import generalized_dice_score
from .hausdorff_distance import hausdorff_distance
from .mean_iou import mean_iou

__all__ = ["dice_score", "generalized_dice_score", "hausdorff_distance", "mean_iou"]
