"""Generalized Dice score (reference ``functional/segmentation/generalized_dice.py``).

Deviation from the reference (documented): when a class is absent from the target the
reference replaces the infinite ``1/target_sum`` weight using a transposed-flatten
index dance (generalized_dice.py:75-81) that scrambles sample/class order unless
``N == C``; here the infinite weight is replaced by that class's maximum finite weight
across the batch — the intended semantics.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ...utilities.compute import _safe_divide
from .utils import _segmentation_inputs_format

Array = jax.Array


def _generalized_dice_validate_args(
    num_classes: int,
    include_background: bool,
    per_class: bool,
    weight_type: str,
    input_format: str,
) -> None:
    if not isinstance(num_classes, int) or num_classes <= 0:
        raise ValueError(f"Expected argument `num_classes` must be a positive integer, but got {num_classes}.")
    if not isinstance(include_background, bool):
        raise ValueError(f"Expected argument `include_background` must be a boolean, but got {include_background}.")
    if not isinstance(per_class, bool):
        raise ValueError(f"Expected argument `per_class` must be a boolean, but got {per_class}.")
    if weight_type not in ["square", "simple", "linear"]:
        raise ValueError(
            f"Expected argument `weight_type` to be one of 'square', 'simple', 'linear', but got {weight_type}."
        )
    if input_format not in ["one-hot", "index", "mixed"]:
        raise ValueError(
            f"Expected argument `input_format` to be one of 'one-hot', 'index', 'mixed', but got {input_format}."
        )


def _generalized_dice_update(
    preds: Array,
    target: Array,
    num_classes: int,
    include_background: bool,
    weight_type: str = "square",
    input_format: str = "one-hot",
) -> Tuple[Array, Array]:
    """Weighted per-sample-per-class numerator/denominator (reference generalized_dice.py:48)."""
    preds, target = _segmentation_inputs_format(preds, target, include_background, num_classes, input_format)
    reduce_axis = tuple(range(2, target.ndim))
    predf = preds.astype(jnp.float32)
    targf = target.astype(jnp.float32)
    intersection = jnp.sum(predf * targf, axis=reduce_axis)
    target_sum = jnp.sum(targf, axis=reduce_axis)
    pred_sum = jnp.sum(predf, axis=reduce_axis)
    cardinality = target_sum + pred_sum

    if weight_type == "simple":
        weights = 1.0 / target_sum
    elif weight_type == "linear":
        weights = jnp.ones_like(target_sum)
    else:  # square
        weights = 1.0 / (target_sum**2)

    infs = jnp.isinf(weights)
    finite = jnp.where(infs, 0.0, weights)
    class_max = jnp.max(finite, axis=0, keepdims=True)  # (1, C)
    weights = jnp.where(infs, jnp.broadcast_to(class_max, weights.shape), weights)

    return 2.0 * intersection * weights, cardinality * weights


def _generalized_dice_compute(numerator: Array, denominator: Array, per_class: bool = True) -> Array:
    if not per_class:
        numerator = jnp.sum(numerator, axis=1)
        denominator = jnp.sum(denominator, axis=1)
    return _safe_divide(numerator, denominator)


def generalized_dice_score(
    preds: Array,
    target: Array,
    num_classes: int,
    include_background: bool = True,
    per_class: bool = False,
    weight_type: str = "square",
    input_format: str = "one-hot",
) -> Array:
    """Generalized Dice Score (reference generalized_dice.py:96).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import generalized_dice_score
        >>> preds = jnp.asarray([[[0, 1, 1, 0], [1, 1, 0, 0], [2, 2, 1, 0], [2, 0, 0, 0]]])
        >>> target = jnp.asarray([[[0, 1, 1, 0], [1, 0, 0, 0], [2, 2, 0, 0], [2, 2, 0, 0]]])
        >>> generalized_dice_score(preds, target, num_classes=3, input_format='index')
        Array([0.7905575], dtype=float32)
    """
    _generalized_dice_validate_args(num_classes, include_background, per_class, weight_type, input_format)
    numerator, denominator = _generalized_dice_update(
        preds, target, num_classes, include_background, weight_type, input_format
    )
    return _generalized_dice_compute(numerator, denominator, per_class)
