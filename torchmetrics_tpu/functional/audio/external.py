"""Host-callback audio metrics backed by third-party native code: PESQ, STOI, SRMR,
DNSMOS, NISQA (reference ``functional/audio/{pesq,stoi,srmr,dnsmos,nisqa}.py``).

The reference itself runs these on CPU numpy via optional wheels (its PESQ moves
tensors to cpu and calls the ``pesq`` C extension — ``functional/audio/pesq.py:101-105``);
the same escape hatch applies here. When the wheel is absent the functions raise the
same clear ModuleNotFoundError the reference does.
"""

from __future__ import annotations


import jax.numpy as jnp
import numpy as np

from ...utilities.imports import _module_available

_PESQ_AVAILABLE = _module_available("pesq")
_PYSTOI_AVAILABLE = _module_available("pystoi")


def perceptual_evaluation_speech_quality(
    preds,
    target,
    fs: int,
    mode: str,
    keep_same_device: bool = False,
    n_processes: int = 1,
) -> jnp.ndarray:
    """PESQ via the ``pesq`` C extension on host numpy (ITU-T P.862)."""
    if not _PESQ_AVAILABLE:
        raise ModuleNotFoundError(
            "PESQ metric requires that pesq is installed."
            " Either install as `pip install torchmetrics[audio]` or `pip install pesq`."
        )
    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    import pesq as pesq_backend

    from ...utilities.checks import _check_same_shape

    preds_np = np.asarray(preds, np.float32)
    target_np = np.asarray(target, np.float32)
    _check_same_shape(preds_np, target_np)
    if preds_np.ndim == 1:
        scores = np.asarray(pesq_backend.pesq(fs, target_np, preds_np, mode))
    else:
        flat_p = preds_np.reshape(-1, preds_np.shape[-1])
        flat_t = target_np.reshape(-1, target_np.shape[-1])
        # flat 1-D batch of scores, like the reference (functional/audio/pesq.py)
        scores = np.asarray([pesq_backend.pesq(fs, t, p, mode) for p, t in zip(flat_p, flat_t)])
    return jnp.asarray(scores, jnp.float32)


def short_time_objective_intelligibility(preds, target, fs: int, extended: bool = False) -> jnp.ndarray:
    """STOI via ``pystoi`` on host numpy."""
    if not _PYSTOI_AVAILABLE:
        raise ModuleNotFoundError(
            "ShortTimeObjectiveIntelligibility metric requires that `pystoi` is installed."
            " Either install as `pip install torchmetrics[audio]` or `pip install pystoi`."
        )
    from pystoi import stoi as stoi_backend

    from ...utilities.checks import _check_same_shape

    preds_np = np.asarray(preds, np.float32)
    target_np = np.asarray(target, np.float32)
    _check_same_shape(preds_np, target_np)
    if preds_np.ndim == 1:
        scores = np.asarray(stoi_backend(target_np, preds_np, fs, extended))
    else:
        flat_p = preds_np.reshape(-1, preds_np.shape[-1])
        flat_t = target_np.reshape(-1, target_np.shape[-1])
        scores = np.asarray(
            [stoi_backend(t, p, fs, extended) for p, t in zip(flat_p, flat_t)]
        ).reshape(preds_np.shape[:-1])
    return jnp.asarray(scores, jnp.float32)


# SRMR and DNSMOS are real in-tree pipelines (./srmr.py, ./dnsmos.py) — unlike the
# reference, SRMR needs no wheels at all, and DNSMOS needs only onnxruntime + the
# model files (its librosa melspec is reimplemented in numpy).
from .dnsmos import deep_noise_suppression_mean_opinion_score  # noqa: F401,E402
from .srmr import speech_reverberation_modulation_energy_ratio  # noqa: F401,E402


# NISQA is a real in-tree pipeline (./nisqa.py) — melspec + CNN-self-attention model
# in jnp; unlike the reference it needs neither librosa nor requests, only the
# published nisqa.tar checkpoint.
from .nisqa import non_intrusive_speech_quality_assessment  # noqa: F401,E402
