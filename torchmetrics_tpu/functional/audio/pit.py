"""Permutation Invariant Training metric wrapper (reference
``functional/audio/pit.py``).

TPU-first: the speaker-wise metric matrix is built with ONE vmapped metric call over
all (target, pred) speaker pairs instead of the reference's spk^2 Python loop, and the
exhaustive permutation scoring is a single gather+mean. The Hungarian fallback for
many speakers uses scipy host-side (like the reference).
"""

from __future__ import annotations

from itertools import permutations
from typing import Any, Callable, Tuple

import jax.numpy as jnp
import numpy as np

_ps_cache: dict = {}


def _gen_permutations(spk_num: int) -> jnp.ndarray:
    if spk_num not in _ps_cache:
        _ps_cache[spk_num] = jnp.asarray(list(permutations(range(spk_num))), jnp.int32)
    return _ps_cache[spk_num]


def _find_best_perm_by_linear_sum_assignment(metric_mtx: jnp.ndarray, maximize: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    from scipy.optimize import linear_sum_assignment

    mmtx = np.asarray(metric_mtx)
    best_perm = jnp.asarray(np.stack([linear_sum_assignment(pwm, maximize)[1] for pwm in mmtx]))
    best_metric = jnp.take_along_axis(metric_mtx, best_perm[:, :, None], axis=2).mean(axis=(-1, -2))
    return best_metric, best_perm


def _find_best_perm_by_exhaustive_method(
    metric_mtx: jnp.ndarray, eval_func: str
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    batch_size, spk_num = metric_mtx.shape[:2]
    ps = _gen_permutations(spk_num)  # (perm_num, spk_num)
    perm_num = ps.shape[0]
    bps = jnp.broadcast_to(ps.T[None], (batch_size, spk_num, perm_num))
    metric_of_ps = jnp.take_along_axis(metric_mtx, bps, axis=2).mean(axis=1)  # (batch, perm)
    if eval_func == "max":
        best_indexes = jnp.argmax(metric_of_ps, axis=1)
        best_metric = jnp.max(metric_of_ps, axis=1)
    else:
        best_indexes = jnp.argmin(metric_of_ps, axis=1)
        best_metric = jnp.min(metric_of_ps, axis=1)
    return best_metric, ps[best_indexes]


def permutation_invariant_training(
    preds,
    target,
    metric_func: Callable,
    mode: str = "speaker-wise",
    eval_func: str = "max",
    **kwargs: Any,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Best metric value and speaker permutation per sample.

    ``metric_func(preds, target)`` must return per-sample values; ``mode`` decides
    whether it sees speaker pairs or whole permutations (reference semantics).


    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import permutation_invariant_training
        >>> from torchmetrics_tpu.functional import scale_invariant_signal_noise_ratio
        >>> preds = jnp.stack([jnp.sin(jnp.arange(100.0) / 9), jnp.cos(jnp.arange(100.0) / 7)])[None]
        >>> target = jnp.stack([jnp.cos(jnp.arange(100.0) / 8), jnp.sin(jnp.arange(100.0) / 10)])[None]
        >>> [round(float(x), 4) for x in permutation_invariant_training(preds, target, scale_invariant_signal_noise_ratio, eval_func='max')[0]]
        [-0.1867]
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape[0:2] != target.shape[0:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ["max", "min"]:
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if mode not in ["speaker-wise", "permutation-wise"]:
        raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    batch_size, spk_num = target.shape[0:2]

    if mode == "permutation-wise":
        perms = _gen_permutations(spk_num)  # (perm_num, spk_num)
        perm_num = perms.shape[0]
        ppreds = preds[:, perms.reshape(-1)].reshape(batch_size * perm_num, *preds.shape[1:])
        ptarget = jnp.repeat(target, perm_num, axis=0)
        metric_of_ps = metric_func(ppreds, ptarget, **kwargs)
        metric_of_ps = jnp.mean(metric_of_ps.reshape(batch_size, perm_num, -1), axis=-1)
        if eval_func == "max":
            best_indexes = jnp.argmax(metric_of_ps, axis=1)
            best_metric = jnp.max(metric_of_ps, axis=1)
        else:
            best_indexes = jnp.argmin(metric_of_ps, axis=1)
            best_metric = jnp.min(metric_of_ps, axis=1)
        return best_metric, perms[best_indexes]

    # speaker-wise: one batched metric call over all (target_idx, preds_idx) pairs
    ti, pi = jnp.meshgrid(jnp.arange(spk_num), jnp.arange(spk_num), indexing="ij")
    pair_preds = preds[:, pi.reshape(-1)].reshape(batch_size * spk_num * spk_num, *preds.shape[2:])
    pair_target = target[:, ti.reshape(-1)].reshape(batch_size * spk_num * spk_num, *target.shape[2:])
    vals = metric_func(pair_preds, pair_target, **kwargs)
    metric_mtx = jnp.asarray(vals).reshape(batch_size, spk_num, spk_num)

    if spk_num > 3:
        return _find_best_perm_by_linear_sum_assignment(metric_mtx, maximize=eval_func == "max")
    return _find_best_perm_by_exhaustive_method(metric_mtx, eval_func)


def pit_permutate(preds, perm) -> jnp.ndarray:
    """Reorder speaker dim of ``preds`` by the best permutation from PIT."""
    preds = jnp.asarray(preds)
    perm = jnp.asarray(perm)
    return jnp.take_along_axis(preds, perm.reshape(*perm.shape, *([1] * (preds.ndim - 2))), axis=1)
