"""DNSMOS — Deep Noise Suppression Mean Opinion Score.

Reference surface: ``functional/audio/dnsmos.py`` (melspec features + two ONNX
models + per-dimension polynomial calibration). The reference needs ``librosa``
for the mel spectrogram; here the whole feature pipeline (periodic-Hann centered
STFT, Slaney-norm mel filterbank, ``power_to_db`` with max-ref and 80 dB floor)
is self-contained numpy, so only ``onnxruntime`` + the Microsoft DNS-Challenge
model files remain external. Model files are looked up in the reference's cache
layout (``~/.torchmetrics/DNSMOS``); this environment has no egress so they are
never downloaded — place them there manually, or inject ``infer_fns`` (a test /
custom-runtime seam) to run the pipeline without onnxruntime.

Resampling note: the reference resamples through ``librosa.resample`` (soxr);
here it is ``scipy.signal.resample_poly`` (polyphase kaiser) — a documented
sub-1e-3 waveform difference for non-16 kHz inputs.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...utilities.imports import _module_available

_ONNXRUNTIME_AVAILABLE = _module_available("onnxruntime")

SAMPLING_RATE = 16000
INPUT_LENGTH = 9.01
DNSMOS_DIR = "~/.torchmetrics/DNSMOS"


# ---- librosa-equivalent mel spectrogram (numpy) ---------------------------------

def _hz_to_mel_slaney(f: np.ndarray) -> np.ndarray:
    f = np.asarray(f, np.float64)
    f_sp = 200.0 / 3
    mels = f / f_sp
    min_log_hz = 1000.0
    logstep = np.log(6.4) / 27.0
    log_region = f >= min_log_hz
    return np.where(log_region, min_log_hz / f_sp + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep, mels)


def _mel_to_hz_slaney(m: np.ndarray) -> np.ndarray:
    m = np.asarray(m, np.float64)
    f_sp = 200.0 / 3
    freqs = m * f_sp
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = np.log(6.4) / 27.0
    log_region = m >= min_log_mel
    return np.where(log_region, min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)


def mel_filterbank(sr: int, n_fft: int, n_mels: int, fmin: float = 0.0, fmax: Optional[float] = None) -> np.ndarray:
    """Slaney-style (librosa-default) triangular mel filterbank, slaney-normalized."""
    fmax = fmax or sr / 2.0
    # rfftfreq, not linspace(0, sr/2): for ODD n_fft (DNSMOS uses 321) the last
    # rfft bin sits at sr/2 * (1 - 1/n_fft), and linspace warps every bin center
    # by n_fft/(n_fft-1) relative to the librosa filterbank the reference feeds
    # the ONNX models
    fft_freqs = np.fft.rfftfreq(n_fft, 1.0 / sr)
    mel_pts = _mel_to_hz_slaney(np.linspace(_hz_to_mel_slaney(fmin), _hz_to_mel_slaney(fmax), n_mels + 2))
    fdiff = np.diff(mel_pts)
    ramps = mel_pts[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    enorm = 2.0 / (mel_pts[2 : n_mels + 2] - mel_pts[:n_mels])
    return weights * enorm[:, None]


def _stft_power(audio: np.ndarray, n_fft: int, hop_length: int) -> np.ndarray:
    """|STFT|^2 with librosa's defaults: periodic Hann of win_length=n_fft,
    center=True constant padding. audio: (B, T) -> (B, 1+n_fft//2, frames)."""
    window = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(n_fft) / n_fft)  # periodic hann
    pad = n_fft // 2
    x = np.pad(audio, ((0, 0), (pad, pad)))
    num_frames = 1 + (x.shape[-1] - n_fft) // hop_length
    idx = np.arange(num_frames)[:, None] * hop_length + np.arange(n_fft)[None, :]
    frames = x[:, idx] * window  # (B, F, n_fft)
    spec = np.fft.rfft(frames, axis=-1)
    return np.abs(spec.transpose(0, 2, 1)) ** 2


def _power_to_db(s: np.ndarray, amin: float = 1e-10, top_db: float = 80.0) -> np.ndarray:
    """librosa.power_to_db with ref=np.max (per-sample max ref)."""
    ref = np.maximum(s.max(axis=tuple(range(1, s.ndim)), keepdims=True), amin)
    log_spec = 10.0 * np.log10(np.maximum(amin, s)) - 10.0 * np.log10(ref)
    return np.maximum(log_spec, log_spec.max(axis=tuple(range(1, s.ndim)), keepdims=True) - top_db)


def _audio_melspec(
    audio: np.ndarray, n_mels: int = 120, frame_size: int = 320, hop_length: int = 160,
    sr: int = 16000, to_db: bool = True,
) -> np.ndarray:
    """Reference ``dnsmos.py:122-155``: mel power spectrogram (n_fft=frame_size+1),
    transposed to (..., frames, n_mels), optionally (power_to_db(ref=max)+40)/40."""
    shape = audio.shape
    x = audio.reshape(-1, shape[-1]).astype(np.float64)
    n_fft = frame_size + 1
    power = _stft_power(x, n_fft, hop_length)  # (B, bins, frames)
    mel = mel_filterbank(sr, n_fft, n_mels) @ power  # (n_mels, bins) @ (B, bins, F) -> (B, n_mels, F)
    mel = mel.transpose(0, 2, 1)  # (B, frames, n_mels)
    if to_db:
        mel = (_power_to_db(mel) + 40) / 40
    return mel.reshape(shape[:-1] + mel.shape[1:]).astype(np.float32)


# ---- ONNX sessions ---------------------------------------------------------------

_SESSION_CACHE: dict = {}


def _load_session(path: str, num_threads: Optional[int] = None, cache_session: bool = True):
    path = os.path.expanduser(path)
    key = (path, num_threads)
    if cache_session and key in _SESSION_CACHE:
        return _SESSION_CACHE[key]
    if not os.path.exists(path):
        raise ModuleNotFoundError(
            f"DNSMOS model file {path!r} not found and this environment has no network "
            "egress to download it. Fetch the DNS-Challenge ONNX models offline into "
            f"{DNSMOS_DIR}, or pass `infer_fns=(p808_fn, sig_bak_ovr_fn)`."
        )
    import onnxruntime as ort

    opts = ort.SessionOptions()
    if num_threads is not None:
        opts.inter_op_num_threads = num_threads
        opts.intra_op_num_threads = num_threads
    sess = ort.InferenceSession(path, providers=["CPUExecutionProvider"], sess_options=opts)
    run = lambda features: sess.run(None, {"input_1": features})[0]
    if cache_session:
        _SESSION_CACHE[key] = run
    return run


def _polyfit_val(mos: np.ndarray, personalized: bool) -> np.ndarray:
    """Raw model outputs -> calibrated MOS, published DNSMOS polynomial fits
    (reference ``dnsmos.py:158-181``)."""
    if personalized:
        p_ovr = np.polynomial.polynomial.Polynomial([-0.11236046, 1.18058466, 0.005101, -0.00533021])
        p_sig = np.polynomial.polynomial.Polynomial([-0.24348726, 1.19576786, 0.02751166, -0.01019296])
        p_bak = np.polynomial.polynomial.Polynomial([0.96883132, -0.1644611, 0.44276479, -0.04976499])
    else:
        p_ovr = np.polynomial.polynomial.Polynomial([0.04602535, 1.11546468, -0.06766283])
        p_sig = np.polynomial.polynomial.Polynomial([0.0052439, 1.22083953, -0.08397278])
        p_bak = np.polynomial.polynomial.Polynomial([-0.39604546, 1.60915514, -0.13166888])
    mos = mos.copy()
    mos[..., 1] = p_sig(mos[..., 1])
    mos[..., 2] = p_bak(mos[..., 2])
    mos[..., 3] = p_ovr(mos[..., 3])
    return mos


def deep_noise_suppression_mean_opinion_score(
    preds,
    fs: int,
    personalized: bool,
    device: Optional[str] = None,
    num_threads: Optional[int] = None,
    cache_session: bool = True,
    infer_fns: Optional[Tuple[Callable, Callable]] = None,
) -> jnp.ndarray:
    """DNSMOS values ``[..., 4]`` = [p808_mos, mos_sig, mos_bak, mos_ovr]
    (reference ``dnsmos.py:184-291``).

    ``infer_fns=(p808_fn, sig_bak_ovr_fn)`` bypasses onnxruntime: each callable
    maps the model's input features to its raw scores (p808: melspec
    ``(B, frames, 120)`` -> ``(B, 1)``; sig_bak_ovr: raw audio ``(B, T)`` ->
    ``(B, 3)``).
    """
    if infer_fns is not None:
        p808_run, sbo_run = infer_fns
    else:
        if not _ONNXRUNTIME_AVAILABLE:
            raise ModuleNotFoundError(
                "DNSMOS metric requires that onnxruntime is installed."
                " Install as `pip install onnxruntime`, or pass `infer_fns`."
            )
        sbo_run = _load_session(
            f"{DNSMOS_DIR}/{'p' if personalized else ''}DNSMOS/sig_bak_ovr.onnx", num_threads, cache_session
        )
        p808_run = _load_session(f"{DNSMOS_DIR}/DNSMOS/model_v8.onnx", num_threads, cache_session)

    audio = np.asarray(preds, np.float32)
    if fs != SAMPLING_RATE:
        from scipy.signal import resample_poly

        g = np.gcd(int(fs), SAMPLING_RATE)
        audio = resample_poly(audio.astype(np.float64), SAMPLING_RATE // g, int(fs) // g, axis=-1).astype(np.float32)
    len_samples = int(INPUT_LENGTH * SAMPLING_RATE)
    while audio.shape[-1] < len_samples:
        audio = np.concatenate([audio, audio], axis=-1)
    num_hops = int(np.floor(audio.shape[-1] / SAMPLING_RATE) - INPUT_LENGTH) + 1

    moss = []
    for idx in range(num_hops):
        seg = audio[..., int(idx * SAMPLING_RATE) : int((idx + INPUT_LENGTH) * SAMPLING_RATE)]
        if seg.shape[-1] < len_samples:
            continue
        shape = seg.shape
        seg = seg.reshape(-1, shape[-1])
        raw = np.asarray(p808_run(_audio_melspec(seg[..., :-160]).astype(np.float32)))
        sbo = np.asarray(sbo_run(seg.astype(np.float32)))
        mos = np.concatenate([raw, sbo], axis=-1).astype(np.float64)
        mos = _polyfit_val(mos, personalized)
        moss.append(mos.reshape(*shape[:-1], 4))
    return jnp.asarray(np.mean(np.stack(moss, axis=-1), axis=-1))
