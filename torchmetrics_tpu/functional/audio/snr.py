"""SNR family (reference ``functional/audio/snr.py``) — fully jittable."""

from __future__ import annotations

import jax.numpy as jnp

from ...utilities.checks import _check_same_shape
from .sdr import scale_invariant_signal_distortion_ratio


def signal_noise_ratio(preds, target, zero_mean: bool = False) -> jnp.ndarray:
    """SNR in dB: target power over residual power, per sample over the time axis.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import signal_noise_ratio
        >>> preds = jnp.asarray([2.8, -1.2, 0.06, 1.3])
        >>> target = jnp.asarray([3.0, -0.5, 0.1, 1.0])
        >>> signal_noise_ratio(preds, target)
        Array(12.176362, dtype=float32)
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    noise = target - preds
    snr_value = (jnp.sum(target**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_noise_ratio(preds, target) -> jnp.ndarray:
    """SI-SNR: SI-SDR with zero-mean normalization.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import scale_invariant_signal_noise_ratio
        >>> preds = jnp.asarray([2.8, -1.2, 0.06, 1.3])
        >>> target = jnp.asarray([3.0, -0.5, 0.1, 1.0])
        >>> scale_invariant_signal_noise_ratio(preds, target)
        Array(12.534761, dtype=float32)
    """
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)


def complex_scale_invariant_signal_noise_ratio(preds, target, zero_mean: bool = False) -> jnp.ndarray:
    """C-SI-SNR over complex STFT inputs ``(..., freq, time, 2)`` (or complex dtype)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.iscomplexobj(preds):
        preds = jnp.stack([preds.real, preds.imag], axis=-1)
    if jnp.iscomplexobj(target):
        target = jnp.stack([target.real, target.imag], axis=-1)
    if (preds.ndim < 3 or preds.shape[-1] != 2) or (target.ndim < 3 or target.shape[-1] != 2):
        raise RuntimeError(
            "Predictions and targets are expected to have the shape (..., frequency, time, 2),"
            f" but got {preds.shape} and {target.shape}."
        )
    preds = preds.reshape(*preds.shape[:-3], -1)
    target = target.reshape(*target.shape[:-3], -1)
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=zero_mean)
