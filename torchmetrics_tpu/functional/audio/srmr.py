"""Speech-to-Reverberation Modulation energy Ratio (SRMR).

Reference surface: ``functional/audio/srmr.py`` (itself a torch translation of
SRMRpy / SRMRToolbox). The reference *requires* the ``gammatone`` and
``torchaudio`` wheels; this implementation needs neither — the gammatone ERB
filterbank is built from Slaney's published filter design ("An Efficient
Implementation of the Patterson-Holdsworth Auditory Filter Bank", Apple TR #35,
1993: four cascaded biquads per channel + gain), and the 8-channel Q=2
modulation filterbank from its standard bandpass-biquad design.

The IIR cascades run on host in float64 via ``scipy.signal.lfilter``: recursive
filtering is inherently sequential over time (the reference also runs it on CPU
for any realistic batch), float64 matches SRMRpy/SRMRToolbox numerics, and no
eval pipeline is SRMR-bound. Everything around the recursion (Hilbert envelope,
framing, energies, score) is vectorized numpy.

Validated against the reference's own doctest golden value (seed-42
``randn(8000)`` at 8 kHz -> 0.3191, reference ``srmr.py:219-227``), which the
reference CI produced with the real gammatone wheel installed.
"""

from __future__ import annotations

from math import ceil, pi
from typing import Optional

import jax.numpy as jnp
import numpy as np

_EAR_Q = 9.26449  # Glasberg and Moore parameters
_MIN_BW = 24.7


def _centre_freqs(fs: int, num_freqs: int, cutoff: float) -> np.ndarray:
    """ERB-spaced centre frequencies from ``cutoff`` to fs/2, HIGHEST first
    (Slaney's ERBSpace)."""
    low, high = cutoff, fs / 2.0
    c = _EAR_Q * _MIN_BW
    return -c + np.exp(
        np.arange(1, num_freqs + 1) * (-np.log(high + c) + np.log(low + c)) / num_freqs
    ) * (high + c)


def _erb_bandwidths(cfs: np.ndarray, order: float = 1.0) -> np.ndarray:
    return ((cfs / _EAR_Q) ** order + _MIN_BW**order) ** (1.0 / order)


def _make_erb_filters(fs: int, cfs: np.ndarray) -> np.ndarray:
    """Slaney's 4th-order gammatone as four cascaded biquads.

    Returns (N, 10): [A0, A11, A12, A13, A14, A2, B0, B1, B2, gain] — numerators
    (A0, A1i, A2) per stage over the shared denominator (B0, B1, B2).
    """
    t = 1.0 / fs
    b = 1.019 * 2 * pi * _erb_bandwidths(cfs)
    arg = 2 * cfs * pi * t
    vec = np.exp(2j * arg)

    a0 = t
    a2 = 0.0
    b0 = 1.0
    b1 = -2 * np.cos(arg) / np.exp(b * t)
    b2 = np.exp(-2 * b * t)

    rt_pos = np.sqrt(3 + 2**1.5)
    rt_neg = np.sqrt(3 - 2**1.5)

    common = -t * np.exp(-(b * t))
    k11 = np.cos(arg) + rt_pos * np.sin(arg)
    k12 = np.cos(arg) - rt_pos * np.sin(arg)
    k13 = np.cos(arg) + rt_neg * np.sin(arg)
    k14 = np.cos(arg) - rt_neg * np.sin(arg)
    a11, a12, a13, a14 = common * k11, common * k12, common * k13, common * k14

    gain_arg = np.exp(1j * arg - b * t)
    gain = np.abs(
        (vec * t - gain_arg * t * k12)
        * (vec * t - gain_arg * t * k11)
        * (vec * t - gain_arg * t * k14)
        * (vec * t - gain_arg * t * k13)
        / (-2 / np.exp(2 * b * t) - 2 * vec + 2 * (1 + vec) / np.exp(b * t)) ** 4
    )
    n = cfs.shape[0]
    return np.column_stack([
        np.full(n, a0), a11, a12, a13, a14, np.full(n, a2),
        np.full(n, b0), b1, b2, gain,
    ])


def _erb_filterbank(wave: np.ndarray, coefs: np.ndarray) -> np.ndarray:
    """(B, T) x (N, 10) -> (B, N, T): four cascaded biquads per channel."""
    from scipy.signal import lfilter

    out = np.empty((wave.shape[0], coefs.shape[0], wave.shape[1]), np.float64)
    for ch in range(coefs.shape[0]):
        a0, a11, a12, a13, a14, a2, b0, b1, b2, gain = coefs[ch]
        den = [b0, b1, b2]
        y = lfilter([a0, a11, a2], den, wave, axis=-1)
        y = lfilter([a0, a12, a2], den, y, axis=-1)
        y = lfilter([a0, a13, a2], den, y, axis=-1)
        y = lfilter([a0, a14, a2], den, y, axis=-1)
        out[:, ch] = y / gain
    return out


def _hilbert_envelope(x: np.ndarray) -> np.ndarray:
    """|analytic signal|, FFT length rounded up to a multiple of 16 (reference
    ``srmr.py:93-115`` — the rounding changes values slightly and is kept)."""
    t = x.shape[-1]
    n = ceil(t / 16) * 16 if t % 16 else t
    x_fft = np.fft.fft(x, n=n, axis=-1)
    h = np.zeros(n)
    if n % 2 == 0:
        h[0] = h[n // 2] = 1
        h[1 : n // 2] = 2
    else:
        h[0] = 1
        h[1 : (n + 1) // 2] = 2
    return np.abs(np.fft.ifft(x_fft * h, axis=-1)[..., :t])


def _modulation_filterbank(min_cf: float, max_cf: float, n: int, fs: float, q: float):
    """Geometric centre frequencies + 2nd-order bandpass biquads (b, a) and the
    lower 3 dB cutoffs (SRMRToolbox design)."""
    spacing = (max_cf / min_cf) ** (1.0 / (n - 1))
    cfs = min_cf * spacing ** np.arange(n)
    w0 = 2 * pi * cfs / fs
    w = np.tan(w0 / 2)
    b0 = w / q
    bs = np.stack([b0, np.zeros(n), -b0], axis=1)
    aas = np.stack([1 + b0 + w**2, 2 * w**2 - 2, 1 - b0 + w**2], axis=1)
    low_cut = cfs - b0 * fs / (2 * pi)
    return cfs, bs, aas, low_cut


def _frame_energy(x: np.ndarray, w_length: int, w_inc: int, num_frames: int) -> np.ndarray:
    """Hamming-windowed squared frame energies over the last axis."""
    t = x.shape[-1]
    pad = max(ceil(t / w_inc) * w_inc - t, w_length - t)
    if pad > 0:
        x = np.concatenate([x, np.zeros((*x.shape[:-1], pad), x.dtype)], axis=-1)
    window = np.hamming(w_length + 1)[:-1]  # periodic hamming
    starts = np.arange(num_frames) * w_inc
    frames = x[..., starts[:, None] + np.arange(w_length)[None, :]]  # (..., F, w)
    return ((frames * window) ** 2).sum(-1)


def _normalize_energy(energy: np.ndarray, drange: float = 30.0) -> np.ndarray:
    """Clamp into a 30 dB dynamic range below the peak mean-over-filters energy."""
    peak = energy.mean(axis=1, keepdims=True).max(axis=(2, 3), keepdims=True)
    floor = peak * 10.0 ** (-drange / 10.0)
    return np.clip(energy, floor, peak)


def _srmr_arg_validate(
    fs: int, n_cochlear_filters: int, low_freq: float, min_cf: float,
    max_cf: Optional[float], norm: bool, fast: bool,
) -> None:
    if not (isinstance(fs, int) and fs > 0):
        raise ValueError(f"Expected argument `fs` to be a positive int, but got {fs}")
    if not (isinstance(n_cochlear_filters, int) and n_cochlear_filters > 0):
        raise ValueError(
            f"Expected argument `n_cochlear_filters` to be a positive int, but got {n_cochlear_filters}"
        )
    if not ((isinstance(low_freq, (float, int))) and low_freq > 0):
        raise ValueError(f"Expected argument `low_freq` to be a positive float, but got {low_freq}")
    if not ((isinstance(min_cf, (float, int))) and min_cf > 0):
        raise ValueError(f"Expected argument `min_cf` to be a positive float, but got {min_cf}")
    if max_cf is not None and not ((isinstance(max_cf, (float, int))) and max_cf > 0):
        raise ValueError(f"Expected argument `max_cf` to be a positive float, but got {max_cf}")
    if not isinstance(norm, bool):
        raise ValueError("Expected argument `norm` to be a bool value")
    if not isinstance(fast, bool):
        raise ValueError("Expected argument `fast` to be a bool value")


def speech_reverberation_modulation_energy_ratio(
    preds,
    fs: int,
    n_cochlear_filters: int = 23,
    low_freq: float = 125,
    min_cf: float = 4,
    max_cf: Optional[float] = None,
    norm: bool = False,
    fast: bool = False,
) -> jnp.ndarray:
    """SRMR: ratio of low (<~20 Hz) to high modulation-band energy of the
    gammatone envelope — higher means less reverberant/degraded speech.

    Matches the reference's slow path (``fast=False``); ``fast=True`` (the
    gammatonegram shortcut) is not implemented because its own docs flag it as
    inconsistent with the SRMRToolbox and slower on accelerators.
    """
    _srmr_arg_validate(fs, n_cochlear_filters, low_freq, min_cf, max_cf, norm, fast)
    if fast:
        raise NotImplementedError(
            "`fast=True` (the gammatonegram approximation) is not implemented; the "
            "reference itself marks it inconsistent with SRMRToolbox. Use fast=False."
        )
    arr = np.asarray(preds)
    shape = arr.shape
    x = arr.reshape(1, -1) if arr.ndim == 1 else arr.reshape(-1, shape[-1])
    if np.issubdtype(x.dtype, np.integer):
        x = x.astype(np.float64) / np.iinfo(arr.dtype).max
    x = x.astype(np.float64)
    # normalize into [-1, 1] like the reference (lfilter range requirement there)
    max_vals = np.abs(x).max(axis=-1, keepdims=True)
    x = x / np.where(max_vals > 1, max_vals, 1.0)
    num_batch, t = x.shape

    cfs = _centre_freqs(fs, n_cochlear_filters, low_freq)
    coefs = _make_erb_filters(fs, cfs)
    gt_env = _hilbert_envelope(_erb_filterbank(x, coefs))  # (B, N, T)
    mfs = float(fs)

    w_length = ceil(0.256 * mfs)
    w_inc = ceil(0.064 * mfs)
    if max_cf is None:
        max_cf = 30 if norm else 128
    _, mod_b, mod_a, cutoffs = _modulation_filterbank(min_cf, float(max_cf), 8, mfs, q=2)

    from scipy.signal import lfilter

    num_frames = int(1 + (t - w_length) // w_inc)
    mod_out = np.stack(
        [lfilter(mod_b[k], mod_a[k], gt_env, axis=-1) for k in range(8)], axis=2
    )  # (B, N, 8, T)
    energy = _frame_energy(mod_out, w_length, w_inc, num_frames)  # (B, N, 8, F)
    if norm:
        energy = _normalize_energy(energy)

    erbs = _erb_bandwidths(cfs)[::-1]  # ascending-cf order
    avg_energy = energy.mean(-1)  # (B, N, 8)
    total_energy = avg_energy.reshape(num_batch, -1).sum(-1)
    ac_energy = avg_energy.sum(2)  # (B, N)
    ac_perc = ac_energy * 100 / total_energy[:, None]
    ac_perc_cumsum = ac_perc[:, ::-1].cumsum(-1)
    k90_idx = ((ac_perc_cumsum > 90).cumsum(-1) == 1).argmax(-1)  # first idx past 90%
    bw = erbs[k90_idx]  # (B,)

    scores = np.empty(num_batch)
    for bi in range(num_batch):
        if cutoffs[4] <= bw[bi] < cutoffs[5]:
            kstar = 5
        elif cutoffs[5] <= bw[bi] < cutoffs[6]:
            kstar = 6
        elif cutoffs[6] <= bw[bi] < cutoffs[7]:
            kstar = 7
        elif cutoffs[7] <= bw[bi]:
            kstar = 8
        else:
            raise ValueError("Something wrong with the cutoffs compared to bw values.")
        scores[bi] = avg_energy[bi, :, :4].sum() / avg_energy[bi, :, 4:kstar].sum()

    out = scores.reshape(shape[:-1]) if arr.ndim > 1 else scores
    return jnp.asarray(out)
