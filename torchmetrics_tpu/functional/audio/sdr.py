"""SDR family (reference ``functional/audio/sdr.py``).

SI-SDR and SA-SDR are one fused jnp expression each. Full SDR solves a per-sample
Toeplitz system for the optimal 512-tap distortion filter — the reference runs this in
float64, which TPUs emulate slowly, so the solve runs host-side: FFT correlations in
numpy f64 + scipy's Levinson ``solve_toeplitz`` (O(L^2) instead of the reference's
dense O(L^3) solve).
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ...utilities.checks import _check_same_shape


def signal_distortion_ratio(
    preds,
    target,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> jnp.ndarray:
    """SDR in dB via the optimal linear distortion filter (fast-bss-eval semantics).
    ``use_cg_iter`` is accepted for API parity; the Levinson solve is always direct.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import signal_distortion_ratio
        >>> preds = jnp.sin(jnp.arange(800, dtype=jnp.float32) / 20)
        >>> target = jnp.sin(jnp.arange(800, dtype=jnp.float32) / 20 + 0.1)
        >>> signal_distortion_ratio(preds, target, filter_length=16)
        Array(31.780607, dtype=float32)
    """
    preds = np.asarray(preds, np.float64)
    target = np.asarray(target, np.float64)
    _check_same_shape(preds, target)
    if zero_mean:
        preds = preds - preds.mean(axis=-1, keepdims=True)
        target = target - target.mean(axis=-1, keepdims=True)
    target = target / np.clip(np.linalg.norm(target, axis=-1, keepdims=True), 1e-6, None)
    preds = preds / np.clip(np.linalg.norm(preds, axis=-1, keepdims=True), 1e-6, None)

    n_fft = 2 ** math.ceil(math.log2(preds.shape[-1] + target.shape[-1] - 1))
    t_fft = np.fft.rfft(target, n=n_fft, axis=-1)
    r_0 = np.fft.irfft(t_fft.real**2 + t_fft.imag**2, n=n_fft)[..., :filter_length]
    p_fft = np.fft.rfft(preds, n=n_fft, axis=-1)
    b = np.fft.irfft(np.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :filter_length]
    if load_diag is not None:
        r_0 = r_0.copy()
        r_0[..., 0] += load_diag

    from scipy.linalg import solve_toeplitz

    flat_r = r_0.reshape(-1, filter_length)
    flat_b = b.reshape(-1, filter_length)
    sol = np.stack([solve_toeplitz(flat_r[i], flat_b[i]) for i in range(flat_r.shape[0])])
    coh = np.einsum("bl,bl->b", flat_b, sol).reshape(r_0.shape[:-1])
    ratio = coh / (1 - coh)
    return jnp.asarray(10.0 * np.log10(ratio), jnp.float32)


def scale_invariant_signal_distortion_ratio(preds, target, zero_mean: bool = False) -> jnp.ndarray:
    """SI-SDR in dB (scale-invariant projection residual).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import scale_invariant_signal_distortion_ratio
        >>> preds = jnp.asarray([2.8, -1.2, 0.06, 1.3])
        >>> target = jnp.asarray([3.0, -0.5, 0.1, 1.0])
        >>> scale_invariant_signal_distortion_ratio(preds, target)
        Array(12.216658, dtype=float32)
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(val)


def source_aggregated_signal_distortion_ratio(
    preds, target, scale_invariant: bool = True, zero_mean: bool = False
) -> jnp.ndarray:
    """SA-SDR over ``(..., spk, time)``: one dB ratio over all speakers jointly.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import source_aggregated_signal_distortion_ratio
        >>> preds = jnp.stack([jnp.sin(jnp.arange(100.0) / 9), jnp.cos(jnp.arange(100.0) / 7)])[None]
        >>> target = jnp.stack([jnp.sin(jnp.arange(100.0) / 10), jnp.cos(jnp.arange(100.0) / 8)])[None]
        >>> source_aggregated_signal_distortion_ratio(preds, target)
        Array([-0.427748], dtype=float32)
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    if preds.ndim < 2:
        raise RuntimeError(f"The preds and target should have the shape (..., spk, time), but {preds.shape} found")
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    if scale_invariant:
        alpha = ((preds * target).sum(axis=-1, keepdims=True).sum(axis=-2, keepdims=True) + eps) / (
            (target**2).sum(axis=-1, keepdims=True).sum(axis=-2, keepdims=True) + eps
        )
        target = alpha * target
    distortion = target - preds
    val = ((target**2).sum(axis=-1).sum(axis=-1) + eps) / ((distortion**2).sum(axis=-1).sum(axis=-1) + eps)
    return 10 * jnp.log10(val)
