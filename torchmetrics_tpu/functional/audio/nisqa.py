"""NISQA v2.0 — Non-Intrusive Speech Quality Assessment.

Reference surface: ``functional/audio/nisqa.py`` (a torch port of the published
NISQA model). The full inference pipeline is in-tree jnp:

- amplitude mel spectrogram (librosa-semantics: centered reflect-pad STFT with a
  ``win_length``-sample Hann window zero-padded to ``n_fft``, Slaney mel
  filterbank, per-sample ``amplitude_to_db`` with an 80 dB floor) — no librosa
  needed, unlike the reference;
- overlapping spectrogram segments -> per-window adaptive CNN (framewise), a
  self-attention encoder over windows, and five attention-pooling heads
  predicting [MOS, noisiness, discontinuity, coloration, loudness];
- a converter from the published checkpoint layout (``nisqa.tar``: ``args`` +
  ``model_state_dict``) to the jnp parameter pytree.

Only the trained checkpoint is external: it is read from the reference's cache
location (``~/.torchmetrics/NISQA/nisqa.tar``) or an explicit
``checkpoint_path``; without it the call gates with a clear error. Architecture
parity is tested against the reference's own torch model driven with shared
random weights (``tests/test_nisqa.py``).
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NISQA_DIR = "~/.torchmetrics/NISQA"


# ---------------------------------------------------------------- features -----

def _melspec_amplitude(y: np.ndarray, sr: int, args: Dict[str, Any]) -> np.ndarray:
    """(B, T) -> (B, n_mels, frames) amplitude mel spectrogram, librosa semantics
    (reference ``nisqa.py:322-361``): power=1.0, hann(win_length) centered in
    n_fft, reflect padding, Slaney mel + norm, fmax cap, per-sample
    ``amplitude_to_db(ref=1.0, amin=1e-4, top_db=80)``."""
    from .dnsmos import mel_filterbank

    n_fft = int(args["ms_n_fft"])
    hop = int(sr * args["ms_hop_length"])
    win = int(sr * args["ms_win_length"])
    window = np.zeros(n_fft)
    start = (n_fft - win) // 2
    window[start : start + win] = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(win) / win)
    pad = n_fft // 2
    x = np.pad(y.astype(np.float64), ((0, 0), (pad, pad)), mode="reflect")
    num_frames = 1 + (x.shape[-1] - n_fft) // hop
    idx = np.arange(num_frames)[:, None] * hop + np.arange(n_fft)[None, :]
    frames = x[:, idx] * window
    mag = np.abs(np.fft.rfft(frames, axis=-1)).transpose(0, 2, 1)  # (B, bins, F)
    fb = mel_filterbank(sr, n_fft, int(args["ms_n_mels"]), fmin=0.0, fmax=args["ms_fmax"])
    mel = fb @ mag  # amplitude (power=1.0)
    # amplitude_to_db per sample (top_db relative to each sample's max)
    db = 20.0 * np.log10(np.maximum(1e-4, mel))
    floor = db.max(axis=(1, 2), keepdims=True) - 80.0
    return np.maximum(db, floor).astype(np.float32)


def _segment_specs(spec: np.ndarray, args: Dict[str, Any]) -> Tuple[np.ndarray, int]:
    """(B, n_mels, frames) -> (B, max_segments, n_mels, seg_length) overlapping
    windows (reference ``nisqa.py:363-392``)."""
    seg_length = int(args["ms_seg_length"])
    seg_hop = int(args["ms_seg_hop_length"])
    max_length = int(args["ms_max_segments"])
    n_wins = spec.shape[2] - (seg_length - 1)
    if n_wins < 1:
        raise RuntimeError("Input signal is too short.")
    starts = np.arange(0, n_wins, seg_hop)
    windows = spec[:, :, starts[:, None] + np.arange(seg_length)[None, :]]  # (B, M, W, S)
    windows = windows.transpose(0, 2, 1, 3)  # (B, W, n_mels, seg)
    n_wins = math.ceil(n_wins / seg_hop)
    if max_length < n_wins:
        raise RuntimeError("Maximum number of mel spectrogram windows exceeded. Use shorter audio.")
    out = np.zeros((spec.shape[0], max_length, spec.shape[1], seg_length), np.float32)
    out[:, :n_wins] = windows
    return out, n_wins


# ------------------------------------------------------------------- model -----

def _adaptive_max_pool(x: jnp.ndarray, out_hw) -> jnp.ndarray:
    """torch ``adaptive_max_pool2d`` semantics: region i = [floor(iN/o), ceil((i+1)N/o))."""
    n, c, h, w = x.shape
    oh, ow = int(out_hw[0]), int(out_hw[1])
    rows = []
    for i in range(oh):
        h0, h1 = (i * h) // oh, -(-((i + 1) * h) // oh)
        cols = []
        for j in range(ow):
            w0, w1 = (j * w) // ow, -(-((j + 1) * w) // ow)
            cols.append(x[:, :, h0:h1, w0:w1].max(axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)  # (N, C, oh, ow)


def _conv_bn_relu(x: jnp.ndarray, p: Dict[str, jnp.ndarray], pad) -> jnp.ndarray:
    from jax import lax

    out = lax.conv_general_dilated(
        x, p["w"], (1, 1), [(pad[0], pad[0]), (pad[1], pad[1])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    ) + p["b"][None, :, None, None]
    inv = p["bn_w"] / jnp.sqrt(p["bn_var"] + 1e-5)
    out = out * inv[None, :, None, None] + (p["bn_b"] - p["bn_mean"] * inv)[None, :, None, None]
    return jnp.maximum(out, 0)


def _adapt_cnn(params: Dict[str, Any], args: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
    """(N, 1, n_mels, seg) -> (N, c_out_3 * pool_3[0]) framewise features
    (reference ``_AdaptCNN``, ``nisqa.py:188-229``)."""
    pad = (1, 0) if tuple(args["cnn_kernel_size"])[0] == 1 else (1, 1)
    x = _conv_bn_relu(x, params["conv1"], pad)
    x = _adaptive_max_pool(x, args["cnn_pool_1"])
    x = _conv_bn_relu(x, params["conv2"], pad)
    x = _adaptive_max_pool(x, args["cnn_pool_2"])
    x = _conv_bn_relu(x, params["conv3"], pad)
    x = _conv_bn_relu(x, params["conv4"], pad)
    x = _adaptive_max_pool(x, args["cnn_pool_3"])
    x = _conv_bn_relu(x, params["conv5"], pad)
    x = _conv_bn_relu(x, params["conv6"], (1, 0))  # kernel (k, pool_3[1]) collapses width
    return x.reshape(x.shape[0], -1)


def _layer_norm(x: jnp.ndarray, p: Dict[str, jnp.ndarray], eps: float = 1e-5) -> jnp.ndarray:
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * p["w"] + p["b"]


def _mha(p: Dict[str, jnp.ndarray], nhead: int, x: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Multi-head self-attention over (B, L, E) with a key validity mask (B, L)
    (torch ``nn.MultiheadAttention`` packed in_proj layout)."""
    b, length, e = x.shape
    head = e // nhead
    qkv = x @ p["in_w"].T + p["in_b"]  # (B, L, 3E)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    reshape = lambda t: t.reshape(b, length, nhead, head).transpose(0, 2, 1, 3)
    q, k, v = reshape(q), reshape(k), reshape(v)
    scores = q @ k.transpose(0, 1, 3, 2) / jnp.sqrt(head)
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    attn = jax.nn.softmax(scores, axis=-1)
    out = (attn @ v).transpose(0, 2, 1, 3).reshape(b, length, e)
    return out @ p["out_w"].T + p["out_b"]


def _self_attention(params: Dict[str, Any], args: Dict[str, Any], x: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """(B, L, F) -> (B, L, d_model) encoder (reference ``_SelfAttention``/
    ``_SelfAttentionLayer``, ``nisqa.py:242-289``); dropout is inference no-op."""
    x = x @ params["linear"]["w"].T + params["linear"]["b"]
    x = _layer_norm(x, params["norm1"])
    for layer in params["layers"]:
        att = _mha(layer["self_attn"], int(args["td_sa_nhead"]), x, valid)
        x = _layer_norm(x + att, layer["norm1"])
        ff = jnp.maximum(x @ layer["linear1"]["w"].T + layer["linear1"]["b"], 0)
        ff = ff @ layer["linear2"]["w"].T + layer["linear2"]["b"]
        x = _layer_norm(x + ff, layer["norm2"])
    return x


def _pool_att_ff(p: Dict[str, Any], x: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Attention pooling head -> (B, 1) (reference ``_PoolAttFF``, ``nisqa.py:301-319``)."""
    att = jnp.maximum(x @ p["linear1"]["w"].T + p["linear1"]["b"], 0)
    att = (att @ p["linear2"]["w"].T + p["linear2"]["b"])[..., 0]  # (B, L)
    att = jnp.where(valid, att, -jnp.inf)
    att = jax.nn.softmax(att, axis=-1)
    pooled = jnp.einsum("bl,ble->be", att, x)
    return pooled @ p["linear3"]["w"].T + p["linear3"]["b"]


def nisqa_forward(params: Dict[str, Any], segments: jnp.ndarray, n_wins, *, args: Dict[str, Any]) -> jnp.ndarray:
    """(B, L, n_mels, seg) padded segments -> (B, 5) [mos, noi, dis, col, loud]."""
    b, length = segments.shape[:2]
    valid = jnp.arange(length)[None, :] < n_wins  # (1, L) -> broadcast over batch
    valid = jnp.broadcast_to(valid, (b, length))
    # framewise CNN on the valid windows only would be a dynamic shape; run all
    # windows and zero the padding outputs (packed-sequence equivalence)
    flat = segments.reshape(b * length, 1, *segments.shape[2:])
    feats = _adapt_cnn(params["cnn"], args, flat).reshape(b, length, -1)
    feats = jnp.where(valid[:, :, None], feats, 0.0)
    enc = _self_attention(params["td"], args, feats, valid)
    heads = [_pool_att_ff(p, enc, valid) for p in params["pool"]]
    return jnp.concatenate(heads, axis=1)


# --------------------------------------------------------------- converter -----

def convert_nisqa_state_dict(sd: Dict[str, Any], args: Dict[str, Any]) -> Dict[str, Any]:
    """torch ``model_state_dict`` of the published checkpoint -> jnp pytree."""
    a = {k: np.asarray(v) for k, v in sd.items()}

    def conv(i):
        pre = f"cnn.model.conv{i}"
        return {
            "w": jnp.asarray(a[f"{pre}.weight"]),
            "b": jnp.asarray(a[f"{pre}.bias"]),
            "bn_w": jnp.asarray(a[f"cnn.model.bn{i}.weight"]),
            "bn_b": jnp.asarray(a[f"cnn.model.bn{i}.bias"]),
            "bn_mean": jnp.asarray(a[f"cnn.model.bn{i}.running_mean"]),
            "bn_var": jnp.asarray(a[f"cnn.model.bn{i}.running_var"]),
        }

    def lin(pre):
        return {"w": jnp.asarray(a[f"{pre}.weight"]), "b": jnp.asarray(a[f"{pre}.bias"])}

    def norm(pre):
        return {"w": jnp.asarray(a[f"{pre}.weight"]), "b": jnp.asarray(a[f"{pre}.bias"])}

    layers = []
    for i in range(int(args["td_sa_num_layers"])):
        pre = f"time_dependency.model.layers.{i}"
        layers.append({
            "self_attn": {
                "in_w": jnp.asarray(a[f"{pre}.self_attn.in_proj_weight"]),
                "in_b": jnp.asarray(a[f"{pre}.self_attn.in_proj_bias"]),
                "out_w": jnp.asarray(a[f"{pre}.self_attn.out_proj.weight"]),
                "out_b": jnp.asarray(a[f"{pre}.self_attn.out_proj.bias"]),
            },
            "linear1": lin(f"{pre}.linear1"),
            "linear2": lin(f"{pre}.linear2"),
            "norm1": norm(f"{pre}.norm1"),
            "norm2": norm(f"{pre}.norm2"),
        })
    return {
        "cnn": {f"conv{i}": conv(i) for i in range(1, 7)},
        "td": {
            "linear": lin("time_dependency.model.linear"),
            "norm1": norm("time_dependency.model.norm1"),
            "layers": layers,
        },
        "pool": [
            {
                "linear1": lin(f"pool_layers.{i}.model.linear1"),
                "linear2": lin(f"pool_layers.{i}.model.linear2"),
                "linear3": lin(f"pool_layers.{i}.model.linear3"),
            }
            for i in range(5)
        ],
    }


_MODEL_CACHE: Dict[str, Tuple[Dict, Dict, Any]] = {}


def resolve_checkpoint_path(checkpoint_path: Optional[str]) -> str:
    """Single source of truth for where the nisqa.tar checkpoint lives."""
    return os.path.expanduser(checkpoint_path or os.path.join(NISQA_DIR, "nisqa.tar"))


def ensure_checkpoint_exists(checkpoint_path: Optional[str]) -> str:
    """Shared construction/load-time gate (one copy of the error text)."""
    path = resolve_checkpoint_path(checkpoint_path)
    if not os.path.exists(path):
        raise ModuleNotFoundError(
            f"NISQA checkpoint {path!r} not found and this environment has no network "
            "egress to download it. Fetch the published nisqa.tar offline into "
            f"{NISQA_DIR} or pass `checkpoint_path=`."
        )
    return path


def _load_nisqa_checkpoint(checkpoint_path: Optional[str]) -> Tuple[Dict, Dict, Any]:
    path = ensure_checkpoint_exists(checkpoint_path)
    if path in _MODEL_CACHE:
        return _MODEL_CACHE[path]
    import functools

    import torch

    ckpt = torch.load(path, map_location="cpu", weights_only=True)
    args = dict(ckpt["args"])
    params = convert_nisqa_state_dict(ckpt["model_state_dict"], args)
    # args drive Python-level structure (pool sizes, layer count) -> close over them
    # and jit per checkpoint; segments shape is static (max_segments padding) and
    # n_wins traces as a scalar, so repeated updates hit the compile cache
    jitted = jax.jit(functools.partial(nisqa_forward, args=args))
    _MODEL_CACHE[path] = (params, args, jitted)
    return _MODEL_CACHE[path]


def non_intrusive_speech_quality_assessment(
    preds, fs: int, checkpoint_path: Optional[str] = None
) -> jnp.ndarray:
    """NISQA scores ``(..., 5)`` = [MOS, noisiness, discontinuity, coloration,
    loudness] (reference ``nisqa.py:66-122``). ``checkpoint_path`` extends the
    reference surface to load the published ``nisqa.tar`` from a custom location."""
    if not isinstance(fs, int) or fs <= 0:
        raise ValueError(f"Argument `fs` expected to be a positive integer, but got {fs}")
    params, args, jitted_forward = _load_nisqa_checkpoint(checkpoint_path)
    arr = np.asarray(preds, np.float32)
    x = arr.reshape(-1, arr.shape[-1])
    spec = _melspec_amplitude(x, fs, args)
    segments, n_wins = _segment_specs(spec, args)
    out = jitted_forward(params, jnp.asarray(segments), jnp.asarray(n_wins))
    return out.reshape((*arr.shape[:-1], 5))
