"""F-beta / F1. Parity: reference ``functional/classification/f_beta.py:44-1158``."""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax

from ...utilities.compute import _adjust_weights_safe_divide, _safe_divide
from ._family import make_binary, make_multiclass, make_multilabel, make_task_dispatch
from ...utilities.enums import ClassificationTask

Array = jax.Array


def _fbeta_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
    zero_division: float = 0,
) -> Array:
    beta2 = beta**2
    if average == "binary":
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp, zero_division)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp_s, fn_s, fp_s = tp.sum(axis), fn.sum(axis), fp.sum(axis)
        return _safe_divide((1 + beta2) * tp_s, (1 + beta2) * tp_s + beta2 * fn_s + fp_s, zero_division)
    fbeta_score = _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp, zero_division)
    return _adjust_weights_safe_divide(fbeta_score, average, multilabel, tp, fp, fn, top_k)


def _make_fbeta_entry(maker, name: str, beta_arg: bool):
    """Entry points for fbeta carry an extra leading ``beta`` argument."""

    def reduce_with_beta(beta):
        return lambda tp, fp, tn, fn, average, mda="global", ml=False, top_k=1, zd=0: _fbeta_reduce(
            tp, fp, tn, fn, beta, average, mda, ml, top_k, zd
        )

    if not beta_arg:  # f1: beta fixed at 1.0
        return maker(reduce_with_beta(1.0), name)

    base_factory = maker

    def fn(preds, target, beta: float = 1.0, *args, **kwargs):
        if not (isinstance(beta, float) and beta > 0):
            raise ValueError(f"Expected argument `beta` to be a positive float, but got {beta}.")
        inner = base_factory(reduce_with_beta(beta), name)
        return inner(preds, target, *args, **kwargs)

    fn.__name__ = name
    fn.__qualname__ = name
    return fn


binary_fbeta_score = _make_fbeta_entry(make_binary, "binary_fbeta_score", beta_arg=True)
multiclass_fbeta_score = _make_fbeta_entry(make_multiclass, "multiclass_fbeta_score", beta_arg=True)
multilabel_fbeta_score = _make_fbeta_entry(make_multilabel, "multilabel_fbeta_score", beta_arg=True)

binary_f1_score = _make_fbeta_entry(make_binary, "binary_f1_score", beta_arg=False)
multiclass_f1_score = _make_fbeta_entry(make_multiclass, "multiclass_f1_score", beta_arg=False)
multilabel_f1_score = _make_fbeta_entry(make_multilabel, "multilabel_f1_score", beta_arg=False)

f1_score = make_task_dispatch(binary_f1_score, multiclass_f1_score, multilabel_f1_score, "f1_score")


def fbeta_score(
    preds,
    target,
    task: str,
    beta: float = 1.0,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
    zero_division: float = 0,
) -> Array:
    """Task facade with explicit beta (reference f_beta.py, bottom)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_fbeta_score(preds, target, beta, threshold, multidim_average, ignore_index, validate_args, zero_division)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_fbeta_score(
            preds, target, beta, num_classes, average, top_k, multidim_average, ignore_index, validate_args, zero_division
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_fbeta_score(
            preds, target, beta, num_labels, threshold, average, multidim_average, ignore_index, validate_args, zero_division
        )
    raise ValueError(f"Not handled value: {task}")
