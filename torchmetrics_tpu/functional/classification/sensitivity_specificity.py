"""Max sensitivity (TPR) at a specificity floor (reference
``functional/classification/sensitivity_specificity.py``)."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ._operating_point import _apply_over_classes
from .precision_recall_curve import (
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from .recall_fixed_precision import _validate_min
from .roc import _binary_roc_compute, _multiclass_roc_compute, _multilabel_roc_compute

Array = jax.Array


def _constrained_first_argmax(objective, constraint, thresholds, min_constraint: float):
    """First argmax of ``objective`` where ``constraint >= floor``; fallback (0, 1e6).

    Mirrors the reference's boolean-index + ``torch.argmax`` (first occurrence)
    semantics (sensitivity_specificity.py:47-70) with a static-shape mask.
    """
    n = min(objective.shape[0], constraint.shape[0], thresholds.shape[0])
    obj, con, thr = objective[:n], constraint[:n], thresholds[:n]
    mask = con >= min_constraint
    obj_m = jnp.where(mask, obj, -jnp.inf)
    idx = jnp.argmax(obj_m)
    feasible = mask.any()
    best = jnp.where(feasible, obj[idx], 0.0)
    best_thr = jnp.where(feasible, thr[idx], 1e6)
    return best, best_thr


def _sensitivity_at_specificity(fpr, tpr, thresholds, min_specificity: float):
    return _constrained_first_argmax(tpr, 1 - fpr, thresholds, min_specificity)


def _binary_sensitivity_at_specificity_compute(state, thresholds, min_specificity: float):
    fpr, tpr, thres = _binary_roc_compute(state, thresholds)
    return _sensitivity_at_specificity(fpr, tpr, thres, min_specificity)


def binary_sensitivity_at_specificity(
    preds, target, min_specificity: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    if validate_args:
        _validate_min("min_specificity", min_specificity)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    return _binary_sensitivity_at_specificity_compute(state, thresholds, min_specificity)


def _apply_roc_operating_point(reduce_fn, fpr, tpr, thres, floor):
    return _apply_over_classes(partial(reduce_fn, **floor), fpr, tpr, thres)


def _multiclass_sensitivity_at_specificity_compute(state, num_classes: int, thresholds, min_specificity: float):
    fpr, tpr, thres = _multiclass_roc_compute(state, num_classes, thresholds)
    return _apply_over_classes(
        partial(_sensitivity_at_specificity, min_specificity=min_specificity), fpr, tpr, thres
    )


def multiclass_sensitivity_at_specificity(
    preds, target, num_classes: int, min_specificity: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    if validate_args:
        _validate_min("min_specificity", min_specificity)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w)
    return _multiclass_sensitivity_at_specificity_compute(state, num_classes, thresholds, min_specificity)


def _multilabel_sensitivity_at_specificity_compute(state, num_labels: int, thresholds, ignore_index, min_specificity: float):
    fpr, tpr, thres = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    return _apply_over_classes(
        partial(_sensitivity_at_specificity, min_specificity=min_specificity), fpr, tpr, thres
    )


def multilabel_sensitivity_at_specificity(
    preds, target, num_labels: int, min_specificity: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    if validate_args:
        _validate_min("min_specificity", min_specificity)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    return _multilabel_sensitivity_at_specificity_compute(state, num_labels, thresholds, ignore_index, min_specificity)
