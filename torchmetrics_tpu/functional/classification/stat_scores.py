"""True/false positive/negative sufficient statistics — the kernel under the whole
accuracy / precision / recall / F-beta / specificity / NPV / hamming family.

Parity: reference ``functional/classification/stat_scores.py`` (binary:26-137,
multiclass:220-483, multilabel:~500+). TPU-native notes:

- ``ignore_index`` is expressed as a zero *weight* per element instead of the
  reference's negative-label masking + boolean indexing — static shapes, jit-safe.
- Multiclass stats are one-hot elementwise products reduced over samples (O(M·C)
  vector ops that XLA fuses into a single pass; no scatter in the hot loop).
- Everything here is pure jnp and trace-safe; host-side value validation lives in the
  ``*_tensor_validation`` functions, gated by ``validate_args``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape, _is_traced
from ...utilities.compute import _safe_divide, normalize_logits_if_needed
from ...utilities.data import select_topk
from ...utilities.enums import ClassificationTask

Array = jax.Array


# --------------------------------------------------------------------- binary


def _binary_stat_scores_arg_validation(
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    zero_division: float = 0,
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if zero_division not in (0, 0.0, 1, 1.0):
        raise ValueError(f"Expected argument `zero_division` to be 0 or 1, but got {zero_division}.")


def _binary_stat_scores_tensor_validation(
    preds, target, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if multidim_average != "global" and preds.ndim < 2:
        raise ValueError("Expected input to be at least 2D when multidim_average is set to `samplewise`")
    if _is_traced(preds, target):
        return
    import numpy as np

    t = np.asarray(target)
    ok = (t == 0) | (t == 1)
    if ignore_index is not None:
        ok |= t == ignore_index
    if not ok.all():
        raise RuntimeError(
            f"Detected the following values in `target`: {np.unique(t)} but expected only"
            f" the following values {[0, 1] if ignore_index is None else [ignore_index]}."
        )
    p = np.asarray(preds)
    if not np.issubdtype(p.dtype, np.floating) and not (((p == 0) | (p == 1)).all()):
        raise RuntimeError(
            f"Detected the following values in `preds`: {np.unique(p)} but expected only"
            " the following values [0,1] since `preds` is a label tensor."
        )


def _binary_stat_scores_format(
    preds, target, threshold: float = 0.5, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """→ (preds01, target01, weights) all shaped ``(N, S)``; ignored points get w=0."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = normalize_logits_if_needed(preds, "sigmoid")
        preds = preds > threshold
    preds = preds.reshape(preds.shape[0], -1).astype(jnp.int32)
    target = target.reshape(target.shape[0], -1)
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.int32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.int32)
    return preds, target.astype(jnp.int32), w


def _binary_stat_scores_update(
    preds: Array, target: Array, weights: Array, multidim_average: str = "global"
) -> Tuple[Array, Array, Array, Array]:
    axis = (0, 1) if multidim_average == "global" else (1,)
    tp = (weights * preds * target).sum(axis)
    fp = (weights * preds * (1 - target)).sum(axis)
    fn = (weights * (1 - preds) * target).sum(axis)
    tn = (weights * (1 - preds) * (1 - target)).sum(axis)
    return tp, fp, tn, fn


def _binary_stat_scores_compute(tp, fp, tn, fn, multidim_average: str = "global") -> Array:
    stats = [tp, fp, tn, fn, tp + fn]
    return jnp.stack([jnp.asarray(s) for s in stats], axis=-1).squeeze()


def binary_stat_scores(
    preds,
    target,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn/support for binary tasks. Reference: stat_scores.py:140-216.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_stat_scores
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_stat_scores(preds, target)
        Array([3, 0, 3, 0, 3], dtype=int32)
    """
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, w = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, w, multidim_average)
    return _binary_stat_scores_compute(tp, fp, tn, fn, multidim_average)


# ------------------------------------------------------------------ multiclass


def _multiclass_stat_scores_arg_validation(
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    zero_division: float = 0,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if not isinstance(top_k, int) or top_k < 1:
        raise ValueError(f"Expected argument `top_k` to be an integer larger than or equal to 1, but got {top_k}")
    if top_k > num_classes:
        raise ValueError(
            f"Expected argument `top_k` to be smaller or equal to `num_classes` but got {top_k} and {num_classes}"
        )
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average}, but got {average}")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if zero_division not in (0, 0.0, 1, 1.0):
        raise ValueError(f"Expected argument `zero_division` to be 0 or 1, but got {zero_division}.")


def _multiclass_stat_scores_tensor_validation(
    preds, target, num_classes: int, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> None:
    if preds.ndim == target.ndim + 1:
        if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[1] != num_classes:
            raise ValueError("If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                             " equal to number of classes.")
        if preds.shape[0] != target.shape[0] or preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
    elif preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError("The `preds` and `target` should have the same shape,"
                             f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}.")
        if multidim_average != "global" and preds.ndim < 2:
            raise ValueError("when `preds` and `target` have the same shape, they should be at least 2D when"
                             " `multidim_average` is set to `samplewise`")
    else:
        raise ValueError("Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be"
                         " (N, ...) and `preds` should be (N, C, ...).")
    if _is_traced(preds, target):
        return
    import numpy as np

    t = np.asarray(target)
    num_unique = t[t != ignore_index] if ignore_index is not None else t
    if num_unique.size and (num_unique.min() < 0 or num_unique.max() >= num_classes):
        raise RuntimeError(f"Detected more unique values in `target` than expected: values outside"
                           f" [0, {num_classes - 1}] found.")
    if preds.ndim == target.ndim and not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        p = np.asarray(preds)
        if p.size and (p.min() < 0 or p.max() >= num_classes):
            raise RuntimeError("Detected more unique values in `preds` than expected.")


def _multiclass_stat_scores_format(
    preds, target, num_classes: int, top_k: int = 1, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """→ (preds_onehot, target_labels, weights).

    ``preds_onehot``: ``(N, S, C)`` 0/1 top-k membership mask (k=1 ⇒ one-hot argmax).
    ``target_labels``: ``(N, S)`` int labels with ignored points remapped to 0.
    ``weights``: ``(N, S)`` 0/1 validity.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    n = target.shape[0]
    target2 = target.reshape(n, -1)
    if ignore_index is not None:
        w = (target2 != ignore_index).astype(jnp.int32)
        target2 = jnp.where(w == 1, target2, 0)
    else:
        w = jnp.ones(target2.shape, jnp.int32)
    # clip stray labels (validated host-side when validate_args) so one_hot stays total
    target2 = jnp.clip(target2, 0, num_classes - 1).astype(jnp.int32)
    if preds.ndim == target.ndim + 1:  # (N, C, ...) float scores
        c = preds.shape[1]
        scores = jnp.moveaxis(preds.reshape(n, c, -1), 1, -1)  # (N, S, C)
        if top_k > 1:
            # reference refinement (_refine_preds_oh, stat_scores.py:347): each sample
            # predicts exactly ONE class — the target when it sits in the top-k, else
            # the top-1 — rather than counting all k columns (which would inflate fp/tn)
            topk_oh = select_topk(scores, top_k, dim=-1)
            in_topk = jnp.take_along_axis(topk_oh, target2[..., None], axis=-1)[..., 0] > 0
            refined = jnp.where(in_topk, target2, jnp.argmax(scores, axis=-1))
            oh = jax.nn.one_hot(refined, num_classes, dtype=jnp.int32)
        else:
            oh = select_topk(scores, 1, dim=-1)
    else:  # (N, ...) int labels
        labels = preds.reshape(n, -1)
        oh = jax.nn.one_hot(labels, num_classes, dtype=jnp.int32)
    return oh.astype(jnp.int32), target2, w


def _multiclass_stat_scores_update(
    preds_oh: Array, target: Array, weights: Array, num_classes: int, multidim_average: str = "global"
) -> Tuple[Array, Array, Array, Array]:
    """Per-class stats via one-hot elementwise products (single fused XLA pass).

    Shapes: global → ``(C,)``; samplewise → ``(N, C)``.
    """
    t_oh = jax.nn.one_hot(target, num_classes, dtype=jnp.int32)  # (N, S, C)
    w = weights[..., None]
    axis = (0, 1) if multidim_average == "global" else (1,)
    tp = (w * preds_oh * t_oh).sum(axis)
    fp = (w * preds_oh * (1 - t_oh)).sum(axis)
    fn = (w * (1 - preds_oh) * t_oh).sum(axis)
    tn = (w * (1 - preds_oh) * (1 - t_oh)).sum(axis)
    return tp, fp, tn, fn


def _multiclass_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, average: Optional[str] = "macro", multidim_average: str = "global"
) -> Array:
    """Average-strategy aggregation over the class axis (reference
    stat_scores.py:454-480): micro sums, macro means in float, weighted uses
    support weights (per-sample-normalized on the samplewise path), none keeps
    the (..., C, 5) table."""
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    sum_dim = 0 if multidim_average == "global" else 1
    if average == "micro":
        return res.sum(sum_dim) if res.ndim > 1 else res
    if average == "macro":
        return res.astype(jnp.float32).mean(sum_dim)
    if average == "weighted":
        weight = tp + fn
        if multidim_average == "global":
            norm = weight / weight.sum()
        else:
            norm = weight / weight.sum(-1, keepdims=True)
        return (res * norm.reshape(*weight.shape, 1)).sum(sum_dim)
    return res


def multiclass_stat_scores(
    preds,
    target,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn/support for multiclass tasks. Reference: stat_scores.py:486-581.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_stat_scores
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_stat_scores(preds, target, num_classes=3)
        Array([1.3333334, 0.       , 2.6666667, 0.       , 1.3333334], dtype=float32)
    """
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    preds_oh, target, w = _multiclass_stat_scores_format(preds, target, num_classes, top_k, ignore_index)
    tp, fp, tn, fn = _multiclass_stat_scores_update(preds_oh, target, w, num_classes, multidim_average)
    return _multiclass_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# ------------------------------------------------------------------ multilabel


def _multilabel_stat_scores_arg_validation(
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    zero_division: float = 0,
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average}, but got {average}")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if zero_division not in (0, 0.0, 1, 1.0):
        raise ValueError(f"Expected argument `zero_division` to be 0 or 1, but got {zero_division}.")


def _multilabel_stat_scores_tensor_validation(
    preds, target, num_labels: int, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(f"Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
                         f" but got {preds.shape[1]} and expected {num_labels}")
    if multidim_average != "global" and preds.ndim < 3:
        raise ValueError("Expected input to be at least 3D when multidim_average is set to `samplewise`")
    if _is_traced(preds, target):
        return
    import numpy as np

    t = np.asarray(target)
    ok = (t == 0) | (t == 1)
    if ignore_index is not None:
        ok |= t == ignore_index
    if not ok.all():
        raise RuntimeError(f"Detected the following values in `target`: {np.unique(t)} but expected only"
                           f" the following values {[0, 1] if ignore_index is None else [ignore_index]}.")
    p = np.asarray(preds)
    if not np.issubdtype(p.dtype, np.floating) and not (((p == 0) | (p == 1)).all()):
        raise RuntimeError("Detected non 0/1 values in `preds` but `preds` is a label tensor.")


def _multilabel_stat_scores_format(
    preds, target, num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """→ (preds01, target01, weights), all ``(N, C, S)``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = normalize_logits_if_needed(preds, "sigmoid")
        preds = preds > threshold
    n, c = preds.shape[0], preds.shape[1]
    preds = preds.reshape(n, c, -1).astype(jnp.int32)
    target = target.reshape(n, c, -1)
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.int32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.int32)
    return preds, target.astype(jnp.int32), w


def _multilabel_stat_scores_update(
    preds: Array, target: Array, weights: Array, multidim_average: str = "global"
) -> Tuple[Array, Array, Array, Array]:
    axis = (0, 2) if multidim_average == "global" else (2,)
    tp = (weights * preds * target).sum(axis)
    fp = (weights * preds * (1 - target)).sum(axis)
    fn = (weights * (1 - preds) * target).sum(axis)
    tn = (weights * (1 - preds) * (1 - target)).sum(axis)
    return tp, fp, tn, fn


def _multilabel_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, average: Optional[str] = "macro", multidim_average: str = "global"
) -> Array:
    """Multilabel variant (reference stat_scores.py:719-744): like multiclass,
    except `weighted` normalizes by the GLOBAL support sum even on the
    samplewise path — a deliberate reference asymmetry kept for parity."""
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    sum_dim = 0 if multidim_average == "global" else 1
    if average == "micro":
        return res.sum(sum_dim)
    if average == "macro":
        return res.astype(jnp.float32).mean(sum_dim)
    if average == "weighted":
        weight = tp + fn
        return (res * (weight / weight.sum()).reshape(*weight.shape, 1)).sum(sum_dim)
    return res


def multilabel_stat_scores(
    preds,
    target,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn/support for multilabel tasks.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_stat_scores
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_stat_scores(preds, target, num_labels=3)
        Array([1.        , 0.33333334, 1.3333334 , 0.33333334, 1.3333334 ],      dtype=float32)
    """
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, w = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, w, multidim_average)
    return _multilabel_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# ------------------------------------------------------------------- dispatch


def stat_scores(
    preds,
    target,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatch facade (reference stat_scores.py, bottom)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_stat_scores(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_stat_scores(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_stat_scores(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
