"""Max specificity (TNR) at a sensitivity floor (reference
``functional/classification/specificity_sensitivity.py``)."""

from __future__ import annotations

from functools import partial

import jax

from ._operating_point import _apply_over_classes
from .precision_recall_curve import (
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from .recall_fixed_precision import _validate_min
from .roc import _binary_roc_compute, _multiclass_roc_compute, _multilabel_roc_compute
from .sensitivity_specificity import _constrained_first_argmax

Array = jax.Array


def _specificity_at_sensitivity(fpr, tpr, thresholds, min_sensitivity: float):
    return _constrained_first_argmax(1 - fpr, tpr, thresholds, min_sensitivity)


def _binary_specificity_at_sensitivity_compute(state, thresholds, min_sensitivity: float):
    fpr, tpr, thres = _binary_roc_compute(state, thresholds)
    return _specificity_at_sensitivity(fpr, tpr, thres, min_sensitivity)


def binary_specificity_at_sensitivity(
    preds, target, min_sensitivity: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    if validate_args:
        _validate_min("min_sensitivity", min_sensitivity)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    return _binary_specificity_at_sensitivity_compute(state, thresholds, min_sensitivity)


def _multiclass_specificity_at_sensitivity_compute(state, num_classes: int, thresholds, min_sensitivity: float):
    fpr, tpr, thres = _multiclass_roc_compute(state, num_classes, thresholds)
    return _apply_over_classes(
        partial(_specificity_at_sensitivity, min_sensitivity=min_sensitivity), fpr, tpr, thres
    )


def multiclass_specificity_at_sensitivity(
    preds, target, num_classes: int, min_sensitivity: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    if validate_args:
        _validate_min("min_sensitivity", min_sensitivity)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w)
    return _multiclass_specificity_at_sensitivity_compute(state, num_classes, thresholds, min_sensitivity)


def _multilabel_specificity_at_sensitivity_compute(state, num_labels: int, thresholds, ignore_index, min_sensitivity: float):
    fpr, tpr, thres = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    return _apply_over_classes(
        partial(_specificity_at_sensitivity, min_sensitivity=min_sensitivity), fpr, tpr, thres
    )


def multilabel_specificity_at_sensitivity(
    preds, target, num_labels: int, min_sensitivity: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    if validate_args:
        _validate_min("min_sensitivity", min_sensitivity)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    return _multilabel_specificity_at_sensitivity_compute(state, num_labels, thresholds, ignore_index, min_sensitivity)
