"""Equal Error Rate (reference ``functional/classification/eer.py``).

EER is the operating point where FPR equals FNR; computed as the midpoint
``(FPR + FNR) / 2`` at the threshold minimizing ``|FPR - FNR|``.
"""

from __future__ import annotations

from typing import List, Union

import jax
import jax.numpy as jnp

from .precision_recall_curve import (
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from .roc import _binary_roc_compute, _multiclass_roc_compute, _multilabel_roc_compute

Array = jax.Array


def _binary_eer_compute(fpr: Array, tpr: Array) -> Array:
    """Midpoint of FPR/FNR at the |FPR - FNR|-minimizing threshold (ref eer.py:28)."""
    fnr = 1 - tpr
    idx = jnp.argmin(jnp.abs(fpr - fnr))
    return (fpr[idx] + fnr[idx]) / 2


def _eer_compute(fpr: Union[Array, List[Array]], tpr: Union[Array, List[Array]]) -> Array:
    if not isinstance(fpr, list) and fpr.ndim == 1:
        return _binary_eer_compute(fpr, tpr)
    return jnp.stack([_binary_eer_compute(f, t) for f, t in zip(fpr, tpr)])


def binary_eer(preds, target, thresholds=None, ignore_index=None, validate_args: bool = True) -> Array:
    """Binary eer.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_eer
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_eer(preds, target)
        Array(0., dtype=float32)
    """
    if validate_args:
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    return _eer_compute(*_binary_roc_compute(state, thresholds)[:2])


def multiclass_eer(
    preds, target, num_classes: int, thresholds=None, average=None, ignore_index=None, validate_args: bool = True
) -> Array:
    """Multiclass eer.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_eer
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_eer(preds, target, num_classes=3)
        Array([0., 0., 0.], dtype=float32)
    """
    if validate_args:
        if average not in ("micro", "macro", None):
            raise ValueError(f"Expected argument `average` to be one of ('micro', 'macro', None), but got {average}")
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index, average
    )
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w, average)
    # micro (one-hot flattened binary) and macro (interpolated mean curve) both collapse
    # to a single curve inside _multiclass_roc_compute (reference eer.py:162)
    fpr, tpr, _ = _multiclass_roc_compute(state, num_classes, thresholds, average)
    return _eer_compute(fpr, tpr)


def multilabel_eer(
    preds, target, num_labels: int, thresholds=None, ignore_index=None, validate_args: bool = True
) -> Array:
    """Multilabel eer.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_eer
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_eer(preds, target, num_labels=3)
        Array([0.  , 0.75, 0.  ], dtype=float32)
    """
    if validate_args:
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    fpr, tpr, _ = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    return _eer_compute(fpr, tpr)


def eer(preds, target, task: str, thresholds=None, num_classes=None, num_labels=None, average=None, ignore_index=None, validate_args: bool = True):
    """Task dispatch (reference eer.py:225-282 facade, incl. ``average``)."""
    from ...utilities.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_eer(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_eer(preds, target, num_classes, thresholds, average, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_eer(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
