"""ROC curves. Parity: reference ``functional/classification/roc.py``
(_binary_roc_compute:40-80, multiclass/multilabel below)."""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...utilities.compute import _safe_divide
from ...utilities.prints import rank_zero_warn
from .precision_recall_curve import (
    _binary_clf_curve,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)

Array = jax.Array


def _binary_roc_compute(
    state, thresholds: Optional[Array], pos_label: int = 1
) -> Tuple[Array, Array, Array]:
    if not isinstance(state, tuple) and thresholds is not None:
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        tns = state[:, 0, 0]
        tpr = _safe_divide(tps, tps + fns)[::-1]
        fpr = _safe_divide(fps, fps + tns)[::-1]
        # homogeneous jax output tuple (thresholds are host numpy until compute)
        return fpr, tpr, jnp.asarray(thresholds)[::-1]
    fps, tps, thres = _binary_clf_curve(preds=state[0], target=state[1], pos_label=pos_label)
    # extra threshold so the curve starts at (0, 0)
    tps = jnp.concatenate([jnp.zeros(1, tps.dtype), tps])
    fps = jnp.concatenate([jnp.zeros(1, fps.dtype), fps])
    thres = jnp.concatenate([jnp.ones(1, thres.dtype), thres])
    if float(fps[-1]) <= 0:
        rank_zero_warn("No negative samples in targets, false positive value should be meaningless.", UserWarning)
        fpr = jnp.zeros_like(thres)
    else:
        fpr = fps / fps[-1]
    if float(tps[-1]) <= 0:
        rank_zero_warn("No positive samples in targets, true positive value should be meaningless.", UserWarning)
        tpr = jnp.zeros_like(thres)
    else:
        tpr = tps / tps[-1]
    return fpr, tpr, thres


def binary_roc(preds, target, thresholds=None, ignore_index: Optional[int] = None, validate_args: bool = True):
    """Binary roc.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_roc
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_roc(preds, target, thresholds=5)
        (Array([0.        , 0.        , 0.        , 0.33333334, 1.        ],      dtype=float32), Array([0.       , 0.6666667, 1.       , 1.       , 1.       ], dtype=float32), Array([1.  , 0.75, 0.5 , 0.25, 0.  ], dtype=float32))
    """
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    return _binary_roc_compute(state, thresholds)


def _multiclass_roc_compute(
    state, num_classes: int, thresholds: Optional[Array], average: Optional[str] = None
):
    if average == "micro":
        return _binary_roc_compute(state, thresholds)
    if not isinstance(state, tuple) and thresholds is not None:
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        tns = state[:, :, 0, 0]
        tpr = _safe_divide(tps, tps + fns)[::-1].T
        fpr = _safe_divide(fps, fps + tns)[::-1].T
        if average == "macro":
            return _macro_interpolate_curves(fpr, tpr, jnp.tile(thresholds[::-1], num_classes), num_classes)
        return fpr, tpr, jnp.asarray(thresholds)[::-1]
    fpr_list, tpr_list, thres_list = [], [], []
    for i in range(num_classes):
        f, t, th = _binary_roc_compute((state[0][:, i], state[1]), None, pos_label=i)
        fpr_list.append(f)
        tpr_list.append(t)
        thres_list.append(th)
    if average == "macro":
        return _macro_interpolate_curves(fpr_list, tpr_list, jnp.concatenate(thres_list), num_classes)
    return fpr_list, tpr_list, thres_list


def _macro_interpolate_curves(fpr, tpr, thres: Array, num_classes: int):
    """Macro curve aggregation (reference roc.py:187-198): interpolate every classwise
    curve onto the union of FPR support points and average the TPRs."""
    from ...utilities.compute import interp

    thres = -jnp.sort(-thres)
    mean_fpr = jnp.sort(jnp.concatenate([jnp.ravel(f) for f in fpr]) if isinstance(fpr, list) else fpr.ravel())
    mean_tpr = jnp.zeros_like(mean_fpr)
    for i in range(num_classes):
        mean_tpr = mean_tpr + interp(mean_fpr, fpr[i], tpr[i])
    return mean_fpr, mean_tpr / num_classes, thres


def multiclass_roc(
    preds,
    target,
    num_classes: int,
    thresholds=None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Multiclass roc.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_roc
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_roc(preds, target, num_classes=3, thresholds=5)
        (Array([[0.        , 0.        , 0.        , 0.33333334, 1.        ],
               [0.        , 0.        , 0.        , 0.5       , 1.        ],
               [0.        , 0.        , 0.        , 0.33333334, 1.        ]],      dtype=float32), Array([[0. , 1. , 1. , 1. , 1. ],
               [0. , 0.5, 0.5, 1. , 1. ],
               [0. , 0. , 1. , 1. , 1. ]], dtype=float32), Array([1.  , 0.75, 0.5 , 0.25, 0.  ], dtype=float32))
    """
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index, average)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index, average
    )
    if thresholds is None and ignore_index is not None:
        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w, average)
    return _multiclass_roc_compute(state, num_classes, thresholds, average)


def _multilabel_roc_compute(
    state, num_labels: int, thresholds: Optional[Array], ignore_index: Optional[int] = None
):
    if not isinstance(state, tuple) and thresholds is not None:
        return _multiclass_roc_compute(state, num_labels, thresholds, None)
    fpr_list, tpr_list, thres_list = [], [], []
    for i in range(num_labels):
        preds_i = np.asarray(state[0][:, i])
        target_i = np.asarray(state[1][:, i])
        if ignore_index is not None:
            keep = target_i != ignore_index
            preds_i, target_i = preds_i[keep], target_i[keep]
        f, t, th = _binary_roc_compute((jnp.asarray(preds_i), jnp.asarray(target_i)), None)
        fpr_list.append(f)
        tpr_list.append(t)
        thres_list.append(th)
    return fpr_list, tpr_list, thres_list


def multilabel_roc(
    preds, target, num_labels: int, thresholds=None, ignore_index: Optional[int] = None, validate_args: bool = True
):
    """Multilabel roc.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_roc
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_roc(preds, target, num_labels=3, thresholds=5)
        (Array([[0. , 0. , 0. , 0.5, 1. ],
               [0. , 0.5, 0.5, 0.5, 1. ],
               [0. , 0. , 0. , 0. , 1. ]], dtype=float32), Array([[0. , 1. , 1. , 1. , 1. ],
               [0. , 0. , 1. , 1. , 1. ],
               [0. , 0.5, 0.5, 1. , 1. ]], dtype=float32), Array([1.  , 0.75, 0.5 , 0.25, 0.  ], dtype=float32))
    """
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    return _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)


def roc(
    preds,
    target,
    task: str,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task facade."""
    from ...utilities.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_roc(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_roc(preds, target, num_classes, thresholds, average, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_roc(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
