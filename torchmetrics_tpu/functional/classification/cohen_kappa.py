"""Cohen's kappa. Parity: reference ``functional/classification/cohen_kappa.py``
(_cohen_kappa_reduce:33-54)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...utilities.enums import ClassificationTaskNoMultilabel
from .confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
)

Array = jax.Array


def _cohen_kappa_reduce(confmat: Array, weights: Optional[str] = None) -> Array:
    """Un-normalized (C,C) confusion matrix → kappa score."""
    confmat = confmat.astype(jnp.float32)
    num_classes = confmat.shape[0]
    sum0 = confmat.sum(axis=0, keepdims=True)
    sum1 = confmat.sum(axis=1, keepdims=True)
    expected = sum1 @ sum0 / sum0.sum()

    if weights is None or weights == "none":
        w_mat = 1 - jnp.eye(num_classes, dtype=confmat.dtype)
    elif weights in ("linear", "quadratic"):
        idx = jnp.arange(num_classes, dtype=confmat.dtype)
        diff = jnp.abs(idx[None, :] - idx[:, None])
        w_mat = diff if weights == "linear" else diff**2
    else:
        raise ValueError(f"Received {weights} for argument ``weights`` but should be either None, 'linear' or 'quadratic'")
    k = jnp.sum(w_mat * confmat) / jnp.sum(w_mat * expected)
    return 1 - k


def _binary_cohen_kappa_arg_validation(
    threshold: float = 0.5, ignore_index: Optional[int] = None, weights: Optional[str] = None
) -> None:
    _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize=None)
    if weights not in ("linear", "quadratic", "none", None):
        raise ValueError(f"Expected argument `weight` to be one of ('linear', 'quadratic', 'none', None), but got {weights}.")


def binary_cohen_kappa(
    preds, target, threshold: float = 0.5, weights: Optional[str] = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Binary cohen kappa.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_cohen_kappa
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_cohen_kappa(preds, target)
        Array(1., dtype=float32)
    """
    if validate_args:
        _binary_cohen_kappa_arg_validation(threshold, ignore_index, weights)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target, w = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target, w)
    return _cohen_kappa_reduce(confmat, weights)


def _multiclass_cohen_kappa_arg_validation(
    num_classes: int, ignore_index: Optional[int] = None, weights: Optional[str] = None
) -> None:
    _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize=None)
    if weights not in ("linear", "quadratic", "none", None):
        raise ValueError(f"Expected argument `weight` to be one of ('linear', 'quadratic', 'none', None), but got {weights}.")


def multiclass_cohen_kappa(
    preds, target, num_classes: int, weights: Optional[str] = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Multiclass cohen kappa.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_cohen_kappa
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_cohen_kappa(preds, target, num_classes=3)
        Array(1., dtype=float32)
    """
    if validate_args:
        _multiclass_cohen_kappa_arg_validation(num_classes, ignore_index, weights)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, w = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, w, num_classes)
    return _cohen_kappa_reduce(confmat, weights)


def cohen_kappa(
    preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
    weights: Optional[str] = None, ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Task facade (binary/multiclass only)."""
    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_cohen_kappa(preds, target, threshold, weights, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_cohen_kappa(preds, target, num_classes, weights, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
