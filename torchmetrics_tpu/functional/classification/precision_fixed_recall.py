"""Max precision at a recall floor (reference
``functional/classification/precision_fixed_recall.py``)."""

from __future__ import annotations

from functools import partial

import jax

from ._operating_point import _apply_over_classes, _masked_lex_best
from .precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from .recall_fixed_precision import _validate_min

Array = jax.Array


def _precision_at_recall(precision, recall, thresholds, min_recall: float):
    """Best (precision, threshold) with recall >= floor (ref precision_fixed_recall.py:42)."""
    return _masked_lex_best(precision, recall, thresholds, min_recall)


def _binary_precision_at_fixed_recall_arg_validation(min_recall, thresholds=None, ignore_index=None) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    _validate_min("min_recall", min_recall)


def _multiclass_precision_at_fixed_recall_arg_validation(num_classes, min_recall, thresholds=None, ignore_index=None) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    _validate_min("min_recall", min_recall)


def _multilabel_precision_at_fixed_recall_arg_validation(num_labels, min_recall, thresholds=None, ignore_index=None) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    _validate_min("min_recall", min_recall)


def _binary_precision_at_fixed_recall_compute(state, thresholds, min_recall: float):
    precision, recall, thres = _binary_precision_recall_curve_compute(state, thresholds)
    return _precision_at_recall(precision, recall, thres, min_recall)


def binary_precision_at_fixed_recall(
    preds, target, min_recall: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    if validate_args:
        _validate_min("min_recall", min_recall)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    return _binary_precision_at_fixed_recall_compute(state, thresholds, min_recall)


def _multiclass_precision_at_fixed_recall_compute(state, num_classes: int, thresholds, min_recall: float):
    precision, recall, thres = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    return _apply_over_classes(partial(_precision_at_recall, min_recall=min_recall), precision, recall, thres)


def multiclass_precision_at_fixed_recall(
    preds, target, num_classes: int, min_recall: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    if validate_args:
        _multiclass_precision_at_fixed_recall_arg_validation(num_classes, min_recall, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w)
    return _multiclass_precision_at_fixed_recall_compute(state, num_classes, thresholds, min_recall)


def _multilabel_precision_at_fixed_recall_compute(state, num_labels: int, thresholds, ignore_index, min_recall: float):
    precision, recall, thres = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    return _apply_over_classes(partial(_precision_at_recall, min_recall=min_recall), precision, recall, thres)


def multilabel_precision_at_fixed_recall(
    preds, target, num_labels: int, min_recall: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    if validate_args:
        _multilabel_precision_at_fixed_recall_arg_validation(num_labels, min_recall, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    return _multilabel_precision_at_fixed_recall_compute(state, num_labels, thresholds, ignore_index, min_recall)
