"""Hinge loss. Parity: reference ``functional/classification/hinge.py``
(_binary_hinge_loss_update:51-68, _multiclass_hinge_loss_update:151-175)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape, _is_traced
from ...utilities.compute import normalize_logits_if_needed
from ...utilities.enums import ClassificationTaskNoMultilabel

Array = jax.Array


def _hinge_loss_compute(measure: Array, total: Array) -> Array:
    return measure / total


def _binary_hinge_loss_arg_validation(squared: bool, ignore_index: Optional[int] = None) -> None:
    if not isinstance(squared, bool):
        raise ValueError(f"Expected argument `squared` to be an bool but got {squared}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_hinge_loss_tensor_validation(preds, target, ignore_index: Optional[int] = None) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError("Expected argument `preds` to be floating tensor with probabilities/logits"
                         f" but got tensor with dtype {jnp.asarray(preds).dtype}")


def _binary_hinge_loss_format(preds, target, ignore_index: Optional[int] = None):
    preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
    target = jnp.asarray(target).reshape(-1)
    preds = normalize_logits_if_needed(preds, "sigmoid")
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.float32)
    return preds, target.astype(jnp.int32), w


def _binary_hinge_loss_update(preds: Array, target: Array, squared: bool, weights: Optional[Array] = None) -> Tuple[Array, Array]:
    w = jnp.ones(target.shape, jnp.float32) if weights is None else weights
    margin = jnp.where(target == 1, preds, -preds)
    measures = jnp.clip(1 - margin, min=0)
    if squared:
        measures = measures**2
    return (w * measures).sum(), w.sum()


def binary_hinge_loss(
    preds, target, squared: bool = False, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """Binary hinge loss.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_hinge_loss
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_hinge_loss(preds, target)
        Array(0.695, dtype=float32)
    """
    if validate_args:
        _binary_hinge_loss_arg_validation(squared, ignore_index)
        _binary_hinge_loss_tensor_validation(preds, target, ignore_index)
    preds, target, w = _binary_hinge_loss_format(preds, target, ignore_index)
    measure, total = _binary_hinge_loss_update(preds, target, squared, w)
    return _hinge_loss_compute(measure, total)


def _multiclass_hinge_loss_arg_validation(
    num_classes: int, squared: bool = False, multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_hinge_loss_arg_validation(squared, ignore_index)
    if multiclass_mode not in ("crammer-singer", "one-vs-all"):
        raise ValueError(
            f"Expected argument `multiclass_mode` to be one of ('crammer-singer', 'one-vs-all') but got {multiclass_mode}"
        )


def _multiclass_hinge_loss_format(preds, target, num_classes: int, ignore_index: Optional[int] = None):
    preds = jnp.asarray(preds).astype(jnp.float32)
    target = jnp.asarray(target).reshape(-1)
    preds = normalize_logits_if_needed(preds, "softmax")
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.float32)
    return preds, jnp.clip(target, 0, num_classes - 1).astype(jnp.int32), w


def _multiclass_hinge_loss_update(
    preds: Array, target: Array, squared: bool, multiclass_mode: str = "crammer-singer",
    weights: Optional[Array] = None,
) -> Tuple[Array, Array]:
    w = jnp.ones(target.shape, jnp.float32) if weights is None else weights
    num_classes = preds.shape[1]
    t_oh = jax.nn.one_hot(target, num_classes, dtype=jnp.bool_)
    if multiclass_mode == "crammer-singer":
        true_score = jnp.take_along_axis(preds, target[:, None], axis=1)[:, 0]
        other_max = jnp.max(jnp.where(t_oh, -jnp.inf, preds), axis=1)
        measures = jnp.clip(1 - (true_score - other_max), min=0)
        if squared:
            measures = measures**2
        return (w * measures).sum(), w.sum()
    margin = jnp.where(t_oh, preds, -preds)
    measures = jnp.clip(1 - margin, min=0)
    if squared:
        measures = measures**2
    return (w[:, None] * measures).sum(0), w.sum()


def multiclass_hinge_loss(
    preds, target, num_classes: int, squared: bool = False, multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Multiclass hinge loss.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_hinge_loss
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_hinge_loss(preds, target, num_classes=3)
        Array(0.625, dtype=float32)
    """
    if validate_args:
        _multiclass_hinge_loss_arg_validation(num_classes, squared, multiclass_mode, ignore_index)
        from .stat_scores import _multiclass_stat_scores_tensor_validation

        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, "global", ignore_index)
    preds, target, w = _multiclass_hinge_loss_format(preds, target, num_classes, ignore_index)
    measure, total = _multiclass_hinge_loss_update(preds, target, squared, multiclass_mode, w)
    return _hinge_loss_compute(measure, total)


def hinge_loss(
    preds, target, task: str, num_classes: Optional[int] = None, squared: bool = False,
    multiclass_mode: str = "crammer-singer", ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Task facade (binary/multiclass)."""
    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_hinge_loss(preds, target, squared, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_hinge_loss(preds, target, num_classes, squared, multiclass_mode, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
