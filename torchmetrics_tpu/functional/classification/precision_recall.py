"""Precision / Recall. Parity: reference ``functional/classification/precision_recall.py``
(_precision_recall_reduce:44, entry points :41-959)."""

from __future__ import annotations

from typing import Optional

import jax

from ...utilities.compute import _adjust_weights_safe_divide, _safe_divide
from ._family import make_binary, make_multiclass, make_multilabel, make_task_dispatch

Array = jax.Array


def _precision_recall_reduce(
    stat: str,
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
    zero_division: float = 0,
) -> Array:
    different_stat = fp if stat == "precision" else fn  # this is what differs between the two scores
    if average == "binary":
        return _safe_divide(tp, tp + different_stat, zero_division)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp = tp.sum(axis)
        different_stat = different_stat.sum(axis)
        return _safe_divide(tp, tp + different_stat, zero_division)
    score = _safe_divide(tp, tp + different_stat, zero_division)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn, top_k)


def _precision_reduce(tp, fp, tn, fn, average, multidim_average="global", multilabel=False, top_k=1, zero_division=0):
    return _precision_recall_reduce("precision", tp, fp, tn, fn, average, multidim_average, multilabel, top_k, zero_division)


def _recall_reduce(tp, fp, tn, fn, average, multidim_average="global", multilabel=False, top_k=1, zero_division=0):
    return _precision_recall_reduce("recall", tp, fp, tn, fn, average, multidim_average, multilabel, top_k, zero_division)


binary_precision = make_binary(_precision_reduce, "binary_precision")
multiclass_precision = make_multiclass(_precision_reduce, "multiclass_precision")
multilabel_precision = make_multilabel(_precision_reduce, "multilabel_precision")
precision = make_task_dispatch(binary_precision, multiclass_precision, multilabel_precision, "precision")

binary_recall = make_binary(_recall_reduce, "binary_recall")
multiclass_recall = make_multiclass(_recall_reduce, "multiclass_recall")
multilabel_recall = make_multilabel(_recall_reduce, "multilabel_recall")
recall = make_task_dispatch(binary_recall, multiclass_recall, multilabel_recall, "recall")
