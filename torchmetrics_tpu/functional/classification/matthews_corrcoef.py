"""Matthews correlation coefficient. Parity: reference
``functional/classification/matthews_corrcoef.py`` (_matthews_corrcoef_reduce:37-89
including the zero-denominator edge cases)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...utilities.enums import ClassificationTask
from .confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
    _multilabel_confusion_matrix_arg_validation,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_tensor_validation,
    _multilabel_confusion_matrix_update,
)

Array = jax.Array


def _matthews_corrcoef_reduce(confmat: Array) -> Array:
    """Un-normalized confusion matrix → MCC (host-side edge-case handling; runs at
    compute time on concrete values)."""
    cm = np.asarray(confmat)
    if cm.ndim == 3:  # multilabel → binary fold
        cm = cm.sum(0)

    if cm.size == 4:
        tn, fp, fn, tp = cm.reshape(-1).astype(np.float64)
        if tp + tn != 0 and fp + fn == 0:
            return jnp.asarray(1.0, jnp.float32)
        if tp + tn == 0 and fp + fn != 0:
            return jnp.asarray(-1.0, jnp.float32)

    cmf = cm.astype(np.float64)
    tk = cmf.sum(-1)
    pk = cmf.sum(-2)
    c = np.trace(cmf)
    s = cmf.sum()
    cov_ytyp = c * s - (tk * pk).sum()
    cov_ypyp = s**2 - (pk * pk).sum()
    cov_ytyt = s**2 - (tk * tk).sum()
    numerator = cov_ytyp
    denom = cov_ypyp * cov_ytyt

    if denom == 0 and cm.size == 4:
        eps = np.finfo(np.float32).eps
        if fn == 0 and tn == 0:
            numerator = np.sqrt(eps) * (tp - fp)
        elif fp == 0 and tn == 0:
            numerator = np.sqrt(eps) * (tp - fn)
        elif tp == 0 and fn == 0:
            numerator = np.sqrt(eps) * (tn - fp)
        elif tp == 0 and fp == 0:
            numerator = np.sqrt(eps) * (tn - fn)
        elif tp == 0:
            numerator = tn - fp * fn
        elif tn == 0:
            numerator = tp - fp * fn
        elif fp == 0 or fn == 0:
            numerator = tp * tn
        else:
            return jnp.asarray(0.0, jnp.float32)
        denom = (tp + fp + eps) * (tp + fn + eps) * (tn + fp + eps) * (tn + fn + eps)
    elif denom == 0:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.asarray(numerator / np.sqrt(denom), jnp.float32)


def binary_matthews_corrcoef(
    preds, target, threshold: float = 0.5, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """Binary matthews corrcoef.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_matthews_corrcoef
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_matthews_corrcoef(preds, target)
        Array(1., dtype=float32)
    """
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize=None)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target, w = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target, w)
    return _matthews_corrcoef_reduce(confmat)


def multiclass_matthews_corrcoef(
    preds, target, num_classes: int, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """Multiclass matthews corrcoef.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_matthews_corrcoef
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_matthews_corrcoef(preds, target, num_classes=3)
        Array(1., dtype=float32)
    """
    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize=None)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, w = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, w, num_classes)
    return _matthews_corrcoef_reduce(confmat)


def multilabel_matthews_corrcoef(
    preds, target, num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel matthews corrcoef.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_matthews_corrcoef
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_matthews_corrcoef(preds, target, num_labels=3)
        Array(0.55, dtype=float32)
    """
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize=None)
        _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, w = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, w, num_labels)
    return _matthews_corrcoef_reduce(confmat)


def matthews_corrcoef(
    preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
    num_labels: Optional[int] = None, ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Task facade."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_matthews_corrcoef(preds, target, threshold, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_matthews_corrcoef(preds, target, num_classes, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_matthews_corrcoef(preds, target, num_labels, threshold, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
