"""Multilabel ranking metrics. Parity: reference
``functional/classification/ranking.py`` (_rank_data:27-33, coverage:48-55, LRAP:112-128,
ranking loss:185+).

TPU-native: the reference loops per-sample with ``torch.unique``; here everything is a
vectorized pairwise ``(N, C, C)`` comparison (C is small for multilabel problems), one
fused XLA kernel, no host loop. Tie handling matches ``_rank_data`` (max-rank).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.compute import normalize_logits_if_needed
from .stat_scores import _multilabel_stat_scores_tensor_validation

Array = jax.Array


def _ranking_reduce(score: Array, num_elements: Array) -> Array:
    return score / num_elements


def _multilabel_ranking_tensor_validation(preds, target, num_labels: int, ignore_index: Optional[int] = None) -> None:
    _multilabel_stat_scores_tensor_validation(preds, target, num_labels, "global", ignore_index)
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError(f"Expected preds tensor to be floating point, but received input with dtype {jnp.asarray(preds).dtype}")


def _multilabel_ranking_format(preds, target, num_labels: int, ignore_index: Optional[int] = None):
    preds = jnp.asarray(preds).reshape(-1, num_labels).astype(jnp.float32)
    target = jnp.asarray(target).reshape(-1, num_labels)
    preds = normalize_logits_if_needed(preds, "sigmoid")
    if ignore_index is not None:
        # reference semantics: ignored positions behave as negatives
        target = jnp.where(target == ignore_index, 0, target)
    return preds, target.astype(jnp.int32)


def _multilabel_coverage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """How deep in the ranking to cover all true labels (reference :48-55)."""
    big = jnp.abs(preds.min()) + 10
    preds_mod = jnp.where(target == 0, preds + big, preds)
    preds_min = preds_mod.min(axis=1)
    coverage = (preds >= preds_min[:, None]).sum(axis=1).astype(jnp.float32)
    return coverage.sum(), jnp.asarray(coverage.shape[0], jnp.float32)


def multilabel_coverage_error(
    preds, target, num_labels: int, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """Multilabel coverage error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_coverage_error
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_coverage_error(preds, target, num_labels=3)
        Array(1.3333334, dtype=float32)
    """
    if validate_args:
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_ranking_format(preds, target, num_labels, ignore_index)
    score, total = _multilabel_coverage_error_update(preds, target)
    return _ranking_reduce(score, total)


def _multilabel_ranking_average_precision_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    n, c = preds.shape
    rel = target == 1
    # descending-rank of label j: #{k: s_ik >= s_ij} (max-rank under ties, matching the
    # reference's cumulative-count _rank_data on negated scores)
    ge = preds[:, :, None] <= preds[:, None, :]  # ge[i, j, k] = s_ik >= s_ij
    rank_all = ge.sum(-1).astype(jnp.float32)  # (N, C)
    rank_rel = (ge & rel[:, None, :]).sum(-1).astype(jnp.float32)  # rank within relevant
    k = rel.sum(-1)  # number of relevant labels per sample
    frac = jnp.where(rel, rank_rel / jnp.maximum(rank_all, 1.0), 0.0)
    score_i = jnp.where((k > 0) & (k < c), frac.sum(-1) / jnp.maximum(k, 1), 1.0)
    return score_i.sum(), jnp.asarray(n, jnp.float32)


def multilabel_ranking_average_precision(
    preds, target, num_labels: int, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """Multilabel ranking average precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_ranking_average_precision
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_ranking_average_precision(preds, target, num_labels=3)
        Array(1., dtype=float32)
    """
    if validate_args:
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_ranking_format(preds, target, num_labels, ignore_index)
    score, total = _multilabel_ranking_average_precision_update(preds, target)
    return _ranking_reduce(score, total)


def _multilabel_ranking_loss_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Fraction of incorrectly ordered (relevant, irrelevant) label pairs."""
    n, c = preds.shape
    rel = (target == 1).astype(jnp.float32)
    irr = 1.0 - rel
    # pair (r, i): wrong when s_i >= s_r (irrelevant ranked at least as high)
    ge = (preds[:, None, :] >= preds[:, :, None]).astype(jnp.float32)  # ge[b, r, i] = s_i >= s_r
    wrong = jnp.einsum("br,bri,bi->b", rel, ge, irr)
    k = rel.sum(-1)
    denom = k * (c - k)
    loss_i = jnp.where(denom > 0, wrong / jnp.maximum(denom, 1.0), 0.0)
    return loss_i.sum(), jnp.asarray(n, jnp.float32)


def multilabel_ranking_loss(
    preds, target, num_labels: int, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """Multilabel ranking loss.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_ranking_loss
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_ranking_loss(preds, target, num_labels=3)
        Array(0., dtype=float32)
    """
    if validate_args:
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_ranking_format(preds, target, num_labels, ignore_index)
    score, total = _multilabel_ranking_loss_update(preds, target)
    return _ranking_reduce(score, total)
