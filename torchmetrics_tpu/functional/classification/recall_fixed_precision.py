"""Max recall at a precision floor (reference
``functional/classification/recall_fixed_precision.py``)."""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Union

import jax

from ._operating_point import _apply_over_classes, _masked_lex_best
from .precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)

Array = jax.Array


def _recall_at_precision(precision, recall, thresholds, min_precision: float):
    """Best (recall, threshold) with precision >= floor (ref recall_fixed_precision.py:58)."""
    return _masked_lex_best(recall, precision, thresholds, min_precision)


def _validate_min(name: str, value: float) -> None:
    if not isinstance(value, float) or not (0 <= value <= 1):
        raise ValueError(f"Expected argument `{name}` to be an float in the [0,1] range, but got {value}")


def _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds=None, ignore_index=None) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    _validate_min("min_precision", min_precision)


def _binary_recall_at_fixed_precision_compute(state, thresholds, min_precision: float, reduce_fn=_recall_at_precision):
    precision, recall, thres = _binary_precision_recall_curve_compute(state, thresholds)
    return reduce_fn(precision, recall, thres, min_precision)


def binary_recall_at_fixed_precision(
    preds, target, min_precision: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    """Binary recall at fixed precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_recall_at_fixed_precision
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_recall_at_fixed_precision(preds, target, min_precision=0.5)
        (Array(1., dtype=float32), Array(0.73, dtype=float32))
    """
    if validate_args:
        _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    return _binary_recall_at_fixed_precision_compute(state, thresholds, min_precision)


def _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_precision, thresholds=None, ignore_index=None) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    _validate_min("min_precision", min_precision)


def _multiclass_recall_at_fixed_precision_compute(
    state, num_classes: int, thresholds, min_precision: float, reduce_fn=_recall_at_precision
):
    precision, recall, thres = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    return _apply_over_classes(partial(reduce_fn, min_precision=min_precision), precision, recall, thres)


def multiclass_recall_at_fixed_precision(
    preds, target, num_classes: int, min_precision: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    """Multiclass recall at fixed precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_recall_at_fixed_precision
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_recall_at_fixed_precision(preds, target, num_classes=3, min_precision=0.5)
        (Array([1., 1., 1.], dtype=float32), Array([0.75, 0.4 , 0.5 ], dtype=float32))
    """
    if validate_args:
        _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_precision, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w)
    return _multiclass_recall_at_fixed_precision_compute(state, num_classes, thresholds, min_precision)


def _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_precision, thresholds=None, ignore_index=None) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    _validate_min("min_precision", min_precision)


def _multilabel_recall_at_fixed_precision_compute(
    state, num_labels: int, thresholds, ignore_index, min_precision: float, reduce_fn=_recall_at_precision
):
    precision, recall, thres = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    return _apply_over_classes(partial(reduce_fn, min_precision=min_precision), precision, recall, thres)


def multilabel_recall_at_fixed_precision(
    preds, target, num_labels: int, min_precision: float, thresholds=None, ignore_index=None, validate_args: bool = True
):
    """Multilabel recall at fixed precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_recall_at_fixed_precision
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_recall_at_fixed_precision(preds, target, num_labels=3, min_precision=0.5)
        (Array([1., 1., 1.], dtype=float32), Array([0.75, 0.65, 0.35], dtype=float32))
    """
    if validate_args:
        _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_precision, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    return _multilabel_recall_at_fixed_precision_compute(state, num_labels, thresholds, ignore_index, min_precision)
