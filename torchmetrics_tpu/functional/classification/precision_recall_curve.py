"""Precision-recall curves (the curve-family kernel).

Parity: reference ``functional/classification/precision_recall_curve.py``
(_binary_clf_curve:30-82, _adjust_threshold_arg:85, binned updates:192-252 with the 50k
vectorized/loop crossover, computes:255-291, multiclass:489-570, multilabel below).

TPU-native notes:
- The binned path is the hot path: one fused ``(N,T)`` threshold-mask einsum per batch
  (rides the MXU as a matmul), producing a static ``(T,2,2)``/(T,C,2,2)`` confusion
  state — no scatter, no 50k crossover heuristic needed.
- ``ignore_index`` flows through as zero sample weights (static shapes under jit).
- The exact path (``thresholds=None``) sorts at compute time on host (numpy): its
  output length is data-dependent (unique scores), which XLA cannot express — same
  reason the reference keeps cat-list states for it.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...utilities.checks import _check_same_shape, _is_traced
from ...utilities.compute import _safe_divide, normalize_logits_if_needed
from ...utilities.prints import rank_zero_warn

Array = jax.Array


def _binary_clf_curve(
    preds, target, sample_weights=None, pos_label: int = 1
) -> Tuple[Array, Array, Array]:
    """fps/tps at every distinct threshold (host-side, sklearn-style sort+cumsum)."""
    preds = np.asarray(preds)
    target = np.asarray(target)
    if preds.ndim > target.ndim:
        preds = preds[:, 0]
    order = np.argsort(-preds, kind="stable")
    preds_s = preds[order]
    target_s = (target[order] == pos_label).astype(np.float64)
    weight = np.asarray(sample_weights, dtype=np.float64)[order] if sample_weights is not None else 1.0

    distinct = np.nonzero(np.diff(preds_s))[0]
    threshold_idxs = np.concatenate([distinct, [target_s.size - 1]])
    tps = np.cumsum(target_s * weight)[threshold_idxs]
    if sample_weights is not None:
        fps = np.cumsum((1 - target_s) * weight)[threshold_idxs]
    else:
        fps = 1 + threshold_idxs - tps
    return jnp.asarray(fps), jnp.asarray(tps), jnp.asarray(preds_s[threshold_idxs])


# Counts from float32 matmuls are only exact below 2^24; chunking the sample axis keeps
# every partial product exactly representable while still riding the MXU, with the
# running total held in int32 (exact to 2^31 accumulated samples; the reference uses
# int64, which default-config JAX does not expose — documented limit).
_EXACT_F32_CHUNK = 1 << 22


def _exact_count_matmul(vec: Array, mat: Array) -> Array:
    """``vec @ mat`` with integer-exact counts: f32 MXU matmul per ≤2^22-row chunk,
    accumulated in int32. ``vec`` is a 0/1(/masked) weight row, ``mat`` a 0/1 mask."""
    n = vec.shape[0]
    if n <= _EXACT_F32_CHUNK:
        return (vec @ mat).astype(jnp.int32)
    acc = jnp.zeros(mat.shape[1:], jnp.int32)
    for i in range(0, n, _EXACT_F32_CHUNK):
        acc = acc + (vec[i : i + _EXACT_F32_CHUNK] @ mat[i : i + _EXACT_F32_CHUNK]).astype(jnp.int32)
    return acc


def _exact_count_einsum(spec: str, a: Array, b: Array) -> Array:
    """Chunked einsum over the leading (sample) axis with int32-exact accumulation."""
    n = a.shape[0]
    if n <= _EXACT_F32_CHUNK:
        return jnp.einsum(spec, a, b).astype(jnp.int32)
    acc = None
    for i in range(0, n, _EXACT_F32_CHUNK):
        part = jnp.einsum(spec, a[i : i + _EXACT_F32_CHUNK], b[i : i + _EXACT_F32_CHUNK]).astype(jnp.int32)
        acc = part if acc is None else acc + part
    return acc


def _exact_count_sum(x: Array, axis=None) -> Array:
    """Integer-exact sum of a 0/1 float mask along ``axis`` (chunked over axis 0)."""
    n = x.shape[0]
    if n <= _EXACT_F32_CHUNK:
        return jnp.sum(x, axis=axis).astype(jnp.int32)
    acc = None
    for i in range(0, n, _EXACT_F32_CHUNK):
        part = jnp.sum(x[i : i + _EXACT_F32_CHUNK], axis=axis).astype(jnp.int32)
        acc = part if acc is None else acc + part
    return acc


def _adjust_threshold_arg(thresholds=None):
    # Host (numpy) on purpose: thresholds are closure-captured by jitted updates, and a
    # captured *device* constant forces a D2H readback at lowering, which flips
    # tunneled TPU runtimes into synchronous dispatch for the whole process. Numpy
    # constants embed from host bytes for free.
    if isinstance(thresholds, int):
        return np.linspace(0, 1, thresholds, dtype=np.float32)
    if isinstance(thresholds, list):
        return np.asarray(thresholds, np.float32)
    if thresholds is None:
        return None
    if isinstance(thresholds, jax.Array):
        return thresholds  # user-supplied device array: keep (documented slow path)
    return np.asarray(thresholds, np.float32)  # numpy array, tuple, or other sequence


# --------------------------------------------------------------------- binary


def _binary_precision_recall_curve_arg_validation(
    thresholds=None, ignore_index: Optional[int] = None
) -> None:
    if thresholds is not None and not isinstance(thresholds, (list, int)) and not hasattr(thresholds, "shape"):
        raise ValueError(
            "Expected argument `thresholds` to either be an integer, list of floats or tensor of floats,"
            f" but got {thresholds}"
        )
    if isinstance(thresholds, int) and thresholds < 2:
        raise ValueError(f"If argument `thresholds` is an integer, expected it to be larger than 1, but got {thresholds}")
    if isinstance(thresholds, list) and not all(isinstance(t, float) and 0 <= t <= 1 for t in thresholds):
        raise ValueError(f"If argument `thresholds` is a list, expected all elements to be floats in the [0,1] range,"
                         f" but got {thresholds}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index: Optional[int] = None) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError("Expected argument `preds` to be an floating tensor with probability/logit scores,"
                         f" but got tensor with dtype {jnp.asarray(preds).dtype}")
    if _is_traced(preds, target):
        return
    t = np.asarray(target)
    ok = (t == 0) | (t == 1)
    if ignore_index is not None:
        ok |= t == ignore_index
    if not ok.all():
        raise RuntimeError(f"Detected the following values in `target`: {np.unique(t)} but expected only"
                           f" the following values {[0, 1] if ignore_index is None else [ignore_index]}.")


def _binary_precision_recall_curve_format(
    preds, target, thresholds=None, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Optional[Array], Array]:
    """→ (preds, target, thresholds, weights); preds sigmoid-normalized, flattened."""
    preds = jnp.asarray(preds).reshape(-1)
    target = jnp.asarray(target).reshape(-1)
    preds = normalize_logits_if_needed(preds, "sigmoid")
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.float32)
    return preds, target.astype(jnp.int32), _adjust_threshold_arg(thresholds), w


def _binary_precision_recall_curve_update(
    preds: Array, target: Array, thresholds: Optional[Array], weights: Optional[Array] = None
):
    """Binned multi-threshold confusion: one fused einsum pass → ``(T, 2, 2)``."""
    if thresholds is None:
        return preds, target
    w = jnp.ones(preds.shape, jnp.float32) if weights is None else weights
    preds_t = (preds[:, None] >= thresholds[None, :]).astype(jnp.float32)  # (N, T)
    pos = (w * target).astype(jnp.float32)
    neg = (w * (1 - target)).astype(jnp.float32)
    tp = _exact_count_matmul(pos, preds_t)  # (T,)
    fp = _exact_count_matmul(neg, preds_t)
    fn = _exact_count_sum(pos) - tp
    tn = _exact_count_sum(neg) - fp
    return jnp.stack([jnp.stack([tn, fp], -1), jnp.stack([fn, tp], -1)], -2)  # (T,2,2) int32


def _binary_precision_recall_curve_compute(
    state, thresholds: Optional[Array], pos_label: int = 1
) -> Tuple[Array, Array, Array]:
    if not isinstance(state, tuple) and thresholds is not None:
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        precision = _safe_divide(tps, tps + fps, zero_division=jnp.nan)
        recall = _safe_divide(tps, tps + fns, zero_division=jnp.nan)
        precision = jnp.concatenate([precision, jnp.ones(1, precision.dtype)])
        recall = jnp.concatenate([recall, jnp.zeros(1, recall.dtype)])
        # thresholds live as numpy until here (closure-captured by jitted updates);
        # the OUTPUT tuple is homogeneous jax Arrays like the reference's device
        # tensors (ADVICE round 5)
        return precision, recall, jnp.asarray(thresholds)
    fps, tps, thres = _binary_clf_curve(state[0], state[1], pos_label=pos_label)
    precision = tps / (tps + fps)
    recall = tps / tps[-1]
    # reference quirk preserved (precision_recall_curve.py:?): the all-negative
    # fallback tests `(target == 0).all()` LITERALLY — so for one-vs-rest class
    # curves (pos_label != 0) a zero-positive class keeps NaN recall, which is
    # what lets average-precision mark absent classes NaN and skip them in
    # macro averaging
    if bool((np.asarray(state[1]) == 0).all()):
        rank_zero_warn(
            "No positive samples found in target, recall is undefined. Setting recall to one for all thresholds.",
            UserWarning,
        )
        recall = jnp.ones_like(recall)
    precision = jnp.concatenate([precision[::-1], jnp.ones(1, precision.dtype)])
    recall = jnp.concatenate([recall[::-1], jnp.zeros(1, recall.dtype)])
    return precision, recall, thres[::-1]


def binary_precision_recall_curve(
    preds, target, thresholds=None, ignore_index: Optional[int] = None, validate_args: bool = True
):
    """Binary precision recall curve.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_precision_recall_curve
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_precision_recall_curve(preds, target, thresholds=5)
        (Array([0.5 , 0.75, 1.  , 1.  ,  nan, 1.  ], dtype=float32), Array([1.       , 1.       , 1.       , 0.6666667, 0.       , 0.       ],      dtype=float32), Array([0.  , 0.25, 0.5 , 0.75, 1.  ], dtype=float32))
    """
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    return _binary_precision_recall_curve_compute(state, thresholds)


# ------------------------------------------------------------------ multiclass


def _multiclass_precision_recall_curve_arg_validation(
    num_classes: int, thresholds=None, ignore_index: Optional[int] = None, average: Optional[str] = None
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if average not in (None, "micro", "macro"):
        raise ValueError(f"Expected argument `average` to be one of None, 'micro' or 'macro', but got {average}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _multiclass_precision_recall_curve_tensor_validation(
    preds, target, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim != target.ndim + 1:
        raise ValueError("Expected `preds` to have one more dimension than `target` but got"
                         f" {preds.ndim} and {target.ndim}")
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError("Expected `preds` to be a float tensor, but got"
                         f" {jnp.asarray(preds).dtype}")
    if preds.shape[1] != num_classes:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to be equal to the number of classes"
                         f" {num_classes}")
    if preds.shape[0] != target.shape[0] or preds.shape[2:] != target.shape[1:]:
        raise ValueError("Expected the shape of `preds` should be (N, C, ...) and the shape of `target` should be"
                         " (N, ...).")
    if _is_traced(preds, target):
        return
    t = np.asarray(target)
    t = t[t != ignore_index] if ignore_index is not None else t
    if t.size and (t.min() < 0 or t.max() >= num_classes):
        raise RuntimeError("Detected more unique values in `target` than expected.")


def _multiclass_precision_recall_curve_format(
    preds, target, num_classes: int, thresholds=None, ignore_index: Optional[int] = None, average: Optional[str] = None
):
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    n, c = preds.shape[0], preds.shape[1]
    preds = jnp.moveaxis(preds.reshape(n, c, -1), 1, -1).reshape(-1, c)  # (M, C)
    target = target.reshape(-1)
    preds = normalize_logits_if_needed(preds, "softmax")
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.float32)
    target = jnp.clip(target, 0, num_classes - 1).astype(jnp.int32)
    if average == "micro":
        # one-vs-rest flatten to a single binary problem (reference :~480)
        t_oh = jax.nn.one_hot(target, num_classes, dtype=jnp.int32)
        preds = preds.reshape(-1)
        target = t_oh.reshape(-1)
        w = jnp.broadcast_to(w[:, None], t_oh.shape).reshape(-1)
    return preds, target, _adjust_threshold_arg(thresholds), w


def _multiclass_precision_recall_curve_update(
    preds: Array, target: Array, num_classes: int, thresholds: Optional[Array], weights: Optional[Array] = None,
    average: Optional[str] = None,
):
    if thresholds is None:
        return preds, target
    if average == "micro":
        return _binary_precision_recall_curve_update(preds, target, thresholds, weights)
    w = jnp.ones(target.shape, jnp.float32) if weights is None else weights
    preds_t = (preds[:, :, None] >= thresholds[None, None, :]).astype(jnp.float32)  # (M, C, T)
    t_oh = jax.nn.one_hot(target, num_classes, dtype=jnp.float32) * w[:, None]  # (M, C)
    n_oh = (1 - jax.nn.one_hot(target, num_classes, dtype=jnp.float32)) * w[:, None]
    tp = _exact_count_einsum("mc,mct->tc", t_oh, preds_t)
    fp = _exact_count_einsum("mc,mct->tc", n_oh, preds_t)
    fn = _exact_count_sum(t_oh, axis=0)[None, :] - tp
    tn = _exact_count_sum(n_oh, axis=0)[None, :] - fp
    return jnp.stack([jnp.stack([tn, fp], -1), jnp.stack([fn, tp], -1)], -2)  # (T,C,2,2) int32


def _multiclass_precision_recall_curve_compute(
    state, num_classes: int, thresholds: Optional[Array], average: Optional[str] = None
):
    if average == "micro":
        return _binary_precision_recall_curve_compute(state, thresholds)
    if not isinstance(state, tuple) and thresholds is not None:
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        precision = _safe_divide(tps, tps + fps, zero_division=jnp.nan)
        recall = _safe_divide(tps, tps + fns, zero_division=jnp.nan)
        precision = jnp.concatenate([precision, jnp.ones((1, num_classes), precision.dtype)], axis=0).T
        recall = jnp.concatenate([recall, jnp.zeros((1, num_classes), recall.dtype)], axis=0).T
        return precision, recall, jnp.asarray(thresholds)  # homogeneous jax output tuple
    precision_list, recall_list, thres_list = [], [], []
    for i in range(num_classes):
        p, r, t = _binary_precision_recall_curve_compute((state[0][:, i], state[1]), None, pos_label=i)
        precision_list.append(p)
        recall_list.append(r)
        thres_list.append(t)
    return precision_list, recall_list, thres_list


def multiclass_precision_recall_curve(
    preds,
    target,
    num_classes: int,
    thresholds=None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Multiclass precision recall curve.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_precision_recall_curve
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_precision_recall_curve(preds, target, num_classes=3, thresholds=5)
        (Array([[0.25     , 0.5      , 1.       , 1.       ,       nan, 1.       ],
               [0.5      , 0.6666667, 1.       , 1.       ,       nan, 1.       ],
               [0.25     , 0.5      , 1.       ,       nan,       nan, 1.       ]],      dtype=float32), Array([[1. , 1. , 1. , 1. , 0. , 0. ],
               [1. , 1. , 0.5, 0.5, 0. , 0. ],
               [1. , 1. , 1. , 0. , 0. , 0. ]], dtype=float32), Array([0.  , 0.25, 0.5 , 0.75, 1.  ], dtype=float32))
    """
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index, average)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index, average
    )
    if thresholds is None and ignore_index is not None:
        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w, average)
    return _multiclass_precision_recall_curve_compute(state, num_classes, thresholds, average)


# ------------------------------------------------------------------ multilabel


def _multilabel_precision_recall_curve_arg_validation(
    num_labels: int, thresholds=None, ignore_index: Optional[int] = None
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _multilabel_precision_recall_curve_tensor_validation(
    preds, target, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError("Expected `preds` to be a float tensor")
    if preds.shape[1] != num_labels:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to equal `num_labels={num_labels}`")


def _multilabel_precision_recall_curve_format(
    preds, target, num_labels: int, thresholds=None, ignore_index: Optional[int] = None
):
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    n, c = preds.shape[0], preds.shape[1]
    preds = jnp.moveaxis(preds.reshape(n, c, -1), 1, -1).reshape(-1, c)
    target = jnp.moveaxis(target.reshape(n, c, -1), 1, -1).reshape(-1, c)
    preds = normalize_logits_if_needed(preds, "sigmoid")
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.float32)
        # binned path: ignored points become zero-weight negatives; exact path: keep the
        # raw ignore_index markers so compute-time per-label filtering works
        # (reference precision_recall_curve.py:767 remaps only when thresholds given)
        if thresholds is not None:
            target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.float32)
    return preds, target.astype(jnp.int32), _adjust_threshold_arg(thresholds), w


def _multilabel_precision_recall_curve_update(
    preds: Array, target: Array, num_labels: int, thresholds: Optional[Array], weights: Optional[Array] = None
):
    if thresholds is None:
        return preds, target
    w = jnp.ones(target.shape, jnp.float32) if weights is None else weights
    preds_t = (preds[:, :, None] >= thresholds[None, None, :]).astype(jnp.float32)  # (M, C, T)
    pos = (w * target).astype(jnp.float32)
    neg = (w * (1 - target)).astype(jnp.float32)
    tp = _exact_count_einsum("mc,mct->tc", pos, preds_t)
    fp = _exact_count_einsum("mc,mct->tc", neg, preds_t)
    fn = _exact_count_sum(pos, axis=0)[None, :] - tp
    tn = _exact_count_sum(neg, axis=0)[None, :] - fp
    return jnp.stack([jnp.stack([tn, fp], -1), jnp.stack([fn, tp], -1)], -2)


def _multilabel_precision_recall_curve_compute(
    state, num_labels: int, thresholds: Optional[Array], ignore_index: Optional[int] = None
):
    if not isinstance(state, tuple) and thresholds is not None:
        return _multiclass_precision_recall_curve_compute(state, num_labels, thresholds, None)
    precision_list, recall_list, thres_list = [], [], []
    for i in range(num_labels):
        preds_i = np.asarray(state[0][:, i])
        target_i = np.asarray(state[1][:, i])
        if ignore_index is not None:
            keep = target_i != ignore_index
            preds_i, target_i = preds_i[keep], target_i[keep]
        p, r, t = _binary_precision_recall_curve_compute((jnp.asarray(preds_i), jnp.asarray(target_i)), None)
        precision_list.append(p)
        recall_list.append(r)
        thres_list.append(t)
    return precision_list, recall_list, thres_list


def multilabel_precision_recall_curve(
    preds, target, num_labels: int, thresholds=None, ignore_index: Optional[int] = None, validate_args: bool = True
):
    """Multilabel precision recall curve.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_precision_recall_curve
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_precision_recall_curve(preds, target, num_labels=3, thresholds=5)
        (Array([[0.33333334, 0.5       , 1.        , 1.        ,        nan,
                1.        ],
               [0.33333334, 0.5       , 0.5       , 0.        ,        nan,
                1.        ],
               [0.6666667 , 1.        , 1.        , 1.        ,        nan,
                1.        ]], dtype=float32), Array([[1. , 1. , 1. , 1. , 0. , 0. ],
               [1. , 1. , 1. , 0. , 0. , 0. ],
               [1. , 1. , 0.5, 0.5, 0. , 0. ]], dtype=float32), Array([0.  , 0.25, 0.5 , 0.75, 1.  ], dtype=float32))
    """
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    return _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)


def precision_recall_curve(
    preds,
    target,
    task: str,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task facade."""
    from ...utilities.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_precision_recall_curve(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_precision_recall_curve(
            preds, target, num_classes, thresholds, average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_precision_recall_curve(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
