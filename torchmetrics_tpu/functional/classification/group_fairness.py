"""Group fairness metrics (reference ``functional/classification/group_fairness.py``).

TPU-native design: per-group tp/fp/tn/fn via ``jax.ops.segment_sum`` with static
``num_segments`` — one fused pass, static shapes, fully jittable — replacing the
reference's sort → ``_flexible_bincount`` → host ``split`` pipeline
(group_fairness.py:52-83).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.checks import _is_traced
from ...utilities.compute import _safe_divide
from ...utilities.prints import rank_zero_warn
from .stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
)

Array = jax.Array


def _groups_validation(groups: Array, num_groups: int) -> None:
    if not jnp.issubdtype(jnp.asarray(groups).dtype, jnp.integer):
        raise ValueError(f"Expected dtype of argument groups to be integer, not {jnp.asarray(groups).dtype}.")
    if _is_traced(groups):
        # under jit the values are abstract — the range check would concretize
        # (ConcretizationTypeError); it runs eagerly in _prepare_inputs instead
        return
    if int(jnp.max(groups)) >= num_groups:
        raise ValueError(
            f"The largest number in the groups tensor is {int(jnp.max(groups))}, which is out of range for the "
            f"specified number of groups {num_groups}. The group identifiers should be ``0, 1, ..., (num_groups - 1)``."
        )


def _binary_groups_stat_scores(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array, Array]:
    """Per-group (tp, fp, tn, fn), each shaped ``(num_groups,)`` — one segment-sum pass."""
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)
        _groups_validation(groups, num_groups)
    preds, target, w = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    preds, target, w = preds.reshape(-1), target.reshape(-1), w.reshape(-1)
    g = jnp.asarray(groups).reshape(-1)
    seg = lambda vals: jax.ops.segment_sum(vals * w, g, num_segments=num_groups)
    tp = seg(preds * target)
    fp = seg(preds * (1 - target))
    tn = seg((1 - preds) * (1 - target))
    fn = seg((1 - preds) * target)
    return tp, fp, tn, fn


def binary_groups_stat_rates(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Rates dict ``{group_g: [tp, fp, tn, fn] / n_g}`` (reference group_fairness.py:105)."""
    tp, fp, tn, fn = _binary_groups_stat_scores(
        preds, target, groups, num_groups, threshold, ignore_index, validate_args
    )
    stats = jnp.stack([tp, fp, tn, fn], axis=-1)
    rates = _safe_divide(stats, stats.sum(axis=-1, keepdims=True))
    return {f"group_{g}": rates[g] for g in range(num_groups)}


def _compute_binary_demographic_parity(tp, fp, tn, fn) -> Dict[str, Array]:
    """Min/max positive-rate ratio (reference group_fairness.py:164)."""
    pos_rates = _safe_divide(tp + fp, tp + fp + tn + fn)
    lo = int(jnp.argmin(pos_rates))
    hi = int(jnp.argmax(pos_rates))
    return {f"DP_{lo}_{hi}": _safe_divide(pos_rates[lo], pos_rates[hi])}


def _compute_binary_equal_opportunity(tp, fp, tn, fn) -> Dict[str, Array]:
    """Min/max true-positive-rate ratio (reference group_fairness.py:243)."""
    tpr = _safe_divide(tp, tp + fn)
    lo = int(jnp.argmin(tpr))
    hi = int(jnp.argmax(tpr))
    return {f"EO_{lo}_{hi}": _safe_divide(tpr[lo], tpr[hi])}


def demographic_parity(
    preds: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Positive-rate parity across groups; no target needed (reference :177)."""
    target = jnp.zeros(jnp.asarray(preds).shape, jnp.int32)
    num_groups = int(jnp.unique(jnp.asarray(groups)).shape[0])
    stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    return _compute_binary_demographic_parity(*stats)


def equal_opportunity(
    preds: Array,
    target: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """True-positive-rate parity across groups (reference :258)."""
    num_groups = int(jnp.unique(jnp.asarray(groups)).shape[0])
    stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    return _compute_binary_equal_opportunity(*stats)


def binary_fairness(
    preds: Array,
    target: Optional[Array],
    groups: Array,
    task: str = "all",
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Demographic parity and/or equal opportunity (reference :326)."""
    if task not in ["demographic_parity", "equal_opportunity", "all"]:
        raise ValueError(
            f"Expected argument `task` to either be ``demographic_parity``,"
            f"``equal_opportunity`` or ``all`` but got {task}."
        )
    if task == "demographic_parity":
        if target is not None:
            rank_zero_warn("The task demographic_parity does not require a target.", UserWarning)
        target = jnp.zeros(jnp.asarray(preds).shape, jnp.int32)
    num_groups = int(jnp.unique(jnp.asarray(groups)).shape[0])
    stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    if task == "demographic_parity":
        return _compute_binary_demographic_parity(*stats)
    if task == "equal_opportunity":
        return _compute_binary_equal_opportunity(*stats)
    return {
        **_compute_binary_demographic_parity(*stats),
        **_compute_binary_equal_opportunity(*stats),
    }
