"""Specificity (TNR). Parity: reference ``functional/classification/specificity.py``."""

from __future__ import annotations

from typing import Optional

import jax

from ...utilities.compute import _adjust_weights_safe_divide, _safe_divide
from ._family import make_binary, make_multiclass, make_multilabel, make_task_dispatch

Array = jax.Array


def _specificity_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
    zero_division: float = 0,
) -> Array:
    if average == "binary":
        return _safe_divide(tn, tn + fp, zero_division)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tn_s, fp_s = tn.sum(axis), fp.sum(axis)
        return _safe_divide(tn_s, tn_s + fp_s, zero_division)
    specificity_score = _safe_divide(tn, tn + fp, zero_division)
    return _adjust_weights_safe_divide(specificity_score, average, multilabel, tp, fp, fn, top_k)


binary_specificity = make_binary(_specificity_reduce, "binary_specificity")
multiclass_specificity = make_multiclass(_specificity_reduce, "multiclass_specificity")
multilabel_specificity = make_multilabel(_specificity_reduce, "multilabel_specificity")
specificity = make_task_dispatch(binary_specificity, multiclass_specificity, multilabel_specificity, "specificity")
