from .accuracy import accuracy, binary_accuracy, multiclass_accuracy, multilabel_accuracy
from .auroc import auroc, binary_auroc, multiclass_auroc, multilabel_auroc
from .average_precision import (
    average_precision,
    binary_average_precision,
    multiclass_average_precision,
    multilabel_average_precision,
)
from .calibration_error import binary_calibration_error, calibration_error, multiclass_calibration_error
from .cohen_kappa import binary_cohen_kappa, cohen_kappa, multiclass_cohen_kappa
from .confusion_matrix import (
    binary_confusion_matrix,
    confusion_matrix,
    multiclass_confusion_matrix,
    multilabel_confusion_matrix,
)
from .exact_match import exact_match, multiclass_exact_match, multilabel_exact_match
from .f_beta import (
    binary_f1_score,
    binary_fbeta_score,
    f1_score,
    fbeta_score,
    multiclass_f1_score,
    multiclass_fbeta_score,
    multilabel_f1_score,
    multilabel_fbeta_score,
)
from .hamming import (
    binary_hamming_distance,
    hamming_distance,
    multiclass_hamming_distance,
    multilabel_hamming_distance,
)
from .hinge import binary_hinge_loss, hinge_loss, multiclass_hinge_loss
from .jaccard import binary_jaccard_index, jaccard_index, multiclass_jaccard_index, multilabel_jaccard_index
from .matthews_corrcoef import (
    binary_matthews_corrcoef,
    matthews_corrcoef,
    multiclass_matthews_corrcoef,
    multilabel_matthews_corrcoef,
)
from .negative_predictive_value import (
    binary_negative_predictive_value,
    multiclass_negative_predictive_value,
    multilabel_negative_predictive_value,
    negative_predictive_value,
)
from .precision_recall import (
    binary_precision,
    binary_recall,
    multiclass_precision,
    multiclass_recall,
    multilabel_precision,
    multilabel_recall,
    precision,
    recall,
)
from .specificity import (
    binary_specificity,
    multiclass_specificity,
    multilabel_specificity,
    specificity,
)
from .precision_recall_curve import (
    binary_precision_recall_curve,
    multiclass_precision_recall_curve,
    multilabel_precision_recall_curve,
    precision_recall_curve,
)
from .ranking import (
    multilabel_coverage_error,
    multilabel_ranking_average_precision,
    multilabel_ranking_loss,
)
from .eer import binary_eer, eer, multiclass_eer, multilabel_eer
from .group_fairness import (
    binary_fairness,
    binary_groups_stat_rates,
    demographic_parity,
    equal_opportunity,
)
from .logauc import binary_logauc, logauc, multiclass_logauc, multilabel_logauc
from ._operating_point_facades import (
    precision_at_fixed_recall,
    recall_at_fixed_precision,
    sensitivity_at_specificity,
    specificity_at_sensitivity,
)
from .precision_fixed_recall import (
    binary_precision_at_fixed_recall,
    multiclass_precision_at_fixed_recall,
    multilabel_precision_at_fixed_recall,
)
from .recall_fixed_precision import (
    binary_recall_at_fixed_precision,
    multiclass_recall_at_fixed_precision,
    multilabel_recall_at_fixed_precision,
)
from .roc import binary_roc, multiclass_roc, multilabel_roc, roc
from .sensitivity_specificity import (
    binary_sensitivity_at_specificity,
    multiclass_sensitivity_at_specificity,
    multilabel_sensitivity_at_specificity,
)
from .specificity_sensitivity import (
    binary_specificity_at_sensitivity,
    multiclass_specificity_at_sensitivity,
    multilabel_specificity_at_sensitivity,
)
from .stat_scores import (
    binary_stat_scores,
    multiclass_stat_scores,
    multilabel_stat_scores,
    stat_scores,
)

# public surface = every imported kernel (modules filtered out); aggregated by
# torchmetrics_tpu.functional.__init__
__all__ = sorted(n for n, v in list(globals().items()) if not n.startswith("_") and callable(v))
