from .accuracy import accuracy, binary_accuracy, multiclass_accuracy, multilabel_accuracy
from .confusion_matrix import (
    binary_confusion_matrix,
    confusion_matrix,
    multiclass_confusion_matrix,
    multilabel_confusion_matrix,
)
from .f_beta import (
    binary_f1_score,
    binary_fbeta_score,
    f1_score,
    fbeta_score,
    multiclass_f1_score,
    multiclass_fbeta_score,
    multilabel_f1_score,
    multilabel_fbeta_score,
)
from .hamming import (
    binary_hamming_distance,
    hamming_distance,
    multiclass_hamming_distance,
    multilabel_hamming_distance,
)
from .negative_predictive_value import (
    binary_negative_predictive_value,
    multiclass_negative_predictive_value,
    multilabel_negative_predictive_value,
    negative_predictive_value,
)
from .precision_recall import (
    binary_precision,
    binary_recall,
    multiclass_precision,
    multiclass_recall,
    multilabel_precision,
    multilabel_recall,
    precision,
    recall,
)
from .specificity import (
    binary_specificity,
    multiclass_specificity,
    multilabel_specificity,
    specificity,
)
from .stat_scores import (
    binary_stat_scores,
    multiclass_stat_scores,
    multilabel_stat_scores,
    stat_scores,
)
