"""Hamming distance. Parity: reference ``functional/classification/hamming.py``
(_hamming_distance_reduce:37-83)."""

from __future__ import annotations

from typing import Optional

import jax

from ...utilities.compute import _adjust_weights_safe_divide, _safe_divide
from ._family import make_binary, make_multiclass, make_multilabel, make_task_dispatch

Array = jax.Array


def _hamming_distance_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
    zero_division: float = 0,
) -> Array:
    if average == "binary":
        return 1 - _safe_divide(tp + tn, tp + fp + tn + fn)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp_s, fn_s = tp.sum(axis), fn.sum(axis)
        if multilabel:
            fp_s, tn_s = fp.sum(axis), tn.sum(axis)
            return 1 - _safe_divide(tp_s + tn_s, tp_s + tn_s + fp_s + fn_s)
        return 1 - _safe_divide(tp_s, tp_s + fn_s)
    score = 1 - _safe_divide(tp + tn, tp + tn + fp + fn) if multilabel else 1 - _safe_divide(tp, tp + fn)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn)


binary_hamming_distance = make_binary(_hamming_distance_reduce, "binary_hamming_distance")
multiclass_hamming_distance = make_multiclass(_hamming_distance_reduce, "multiclass_hamming_distance")
multilabel_hamming_distance = make_multilabel(_hamming_distance_reduce, "multilabel_hamming_distance")
hamming_distance = make_task_dispatch(
    binary_hamming_distance, multiclass_hamming_distance, multilabel_hamming_distance, "hamming_distance"
)
