"""Factory for stat-scores-family entry points.

The reference repeats ~60 lines of validate/format/update boilerplate per metric per
task (precision_recall.py:41-959, f_beta.py:44-1158, …). Here one factory generates the
``binary_*``/``multiclass_*``/``multilabel_*`` functions from a reduce callback — same
public signatures, single code path to test.
"""

from __future__ import annotations

from typing import Callable, Optional

from ...utilities.enums import ClassificationTask
from .stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multiclass_stat_scores_update,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)

# reduce signature: (tp, fp, tn, fn, average, multidim_average, multilabel, top_k, zero_division) -> Array


def make_binary(reduce: Callable, name: str, support_zero_division: bool = True) -> Callable:
    def fn(
        preds,
        target,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        zero_division: float = 0,
    ):
        if validate_args:
            _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index, zero_division)
            _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
        preds, target, w = _binary_stat_scores_format(preds, target, threshold, ignore_index)
        tp, fp, tn, fn_ = _binary_stat_scores_update(preds, target, w, multidim_average)
        return reduce(tp, fp, tn, fn_, "binary", multidim_average, False, 1, zero_division)

    fn.__name__ = name
    fn.__qualname__ = name
    return fn


def make_multiclass(reduce: Callable, name: str, default_average: str = "macro") -> Callable:
    def fn(
        preds,
        target,
        num_classes: int,
        average: Optional[str] = default_average,
        top_k: int = 1,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        zero_division: float = 0,
    ):
        if validate_args:
            _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index, zero_division)
            _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
        preds_oh, target, w = _multiclass_stat_scores_format(preds, target, num_classes, top_k, ignore_index)
        tp, fp, tn, fn_ = _multiclass_stat_scores_update(preds_oh, target, w, num_classes, multidim_average)
        return reduce(tp, fp, tn, fn_, average, multidim_average, False, top_k, zero_division)

    fn.__name__ = name
    fn.__qualname__ = name
    return fn


def make_multilabel(reduce: Callable, name: str, default_average: str = "macro") -> Callable:
    def fn(
        preds,
        target,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = default_average,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        zero_division: float = 0,
    ):
        if validate_args:
            _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index, zero_division)
            _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
        preds, target, w = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
        tp, fp, tn, fn_ = _multilabel_stat_scores_update(preds, target, w, multidim_average)
        return reduce(tp, fp, tn, fn_, average, multidim_average, True, 1, zero_division)

    fn.__name__ = name
    fn.__qualname__ = name
    return fn


def make_task_dispatch(binary_fn: Callable, multiclass_fn: Callable, multilabel_fn: Callable, name: str) -> Callable:
    def fn(
        preds,
        target,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: Optional[str] = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        zero_division: float = 0,
    ):
        task = ClassificationTask.from_str(task)
        if task == ClassificationTask.BINARY:
            return binary_fn(preds, target, threshold, multidim_average, ignore_index, validate_args, zero_division)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return multiclass_fn(
                preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args, zero_division
            )
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return multilabel_fn(
                preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args, zero_division
            )
        raise ValueError(f"Not handled value: {task}")

    fn.__name__ = name
    fn.__qualname__ = name
    return fn
