"""Calibration error (ECE). Parity: reference
``functional/classification/calibration_error.py`` (_binning_bucketize:30-60,
_ce_compute:63-107, updates:137+).

TPU-native: states are the per-bin sufficient statistics (conf sum / acc sum / count
per bin, static ``(n_bins+1,)`` shapes, sum-reduced) instead of the reference's
unbounded confidence lists — identical ECE values since the reference bins with the
same uniform boundaries at compute time anyway.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.compute import _safe_divide, normalize_logits_if_needed
from ...utilities.enums import ClassificationTaskNoMultilabel

Array = jax.Array


def _binned_stats_update(
    confidences: Array, accuracies: Array, n_bins: int, weights: Optional[Array] = None
) -> Tuple[Array, Array, Array]:
    """Per-bin sufficient statistics (the static-shape metric state)."""
    bin_boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
    n = bin_boundaries.shape[0]
    w = jnp.ones(confidences.shape, jnp.float32) if weights is None else weights
    indices = jnp.clip(jnp.searchsorted(bin_boundaries, confidences, side="right") - 1, 0, n - 1)
    count_bin = jax.ops.segment_sum(w, indices, num_segments=n)
    conf_bin = jax.ops.segment_sum(w * confidences, indices, num_segments=n)
    acc_bin = jax.ops.segment_sum(w * accuracies.astype(jnp.float32), indices, num_segments=n)
    return conf_bin, acc_bin, count_bin


def _ce_compute_from_bins(conf_bin: Array, acc_bin: Array, count_bin: Array, norm: str = "l1") -> Array:
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Argument `norm` is expected to be one of 'l1', 'l2', 'max' but got {norm}")
    acc_rate = _safe_divide(acc_bin, count_bin)
    conf_rate = _safe_divide(conf_bin, count_bin)
    prop_bin = _safe_divide(count_bin, count_bin.sum())
    if norm == "l1":
        return jnp.sum(jnp.abs(acc_rate - conf_rate) * prop_bin)
    if norm == "max":
        ce = jnp.max(jnp.abs(acc_rate - conf_rate) * (prop_bin > 0))
        return ce
    ce = jnp.sum(jnp.square(acc_rate - conf_rate) * prop_bin)
    return jnp.where(ce > 0, jnp.sqrt(ce), ce)


def _binary_calibration_error_arg_validation(
    n_bins: int, norm: str = "l1", ignore_index: Optional[int] = None
) -> None:
    if not isinstance(n_bins, int) or n_bins < 1:
        raise ValueError(f"Expected argument `n_bins` to be an integer larger than 0, but got {n_bins}")
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Expected argument `norm` to be one of 'l1', 'l2' or 'max' but got {norm}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_calibration_error_tensor_validation(preds, target, ignore_index: Optional[int] = None) -> None:
    from .precision_recall_curve import _binary_precision_recall_curve_tensor_validation

    _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)


def _binary_calibration_error_format(preds, target, ignore_index: Optional[int] = None):
    preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
    target = jnp.asarray(target).reshape(-1)
    preds = normalize_logits_if_needed(preds, "sigmoid")
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.float32)
    return preds, target.astype(jnp.int32), w


def _binary_calibration_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    return preds, target  # confidences, accuracies (reference :137-139)


def binary_calibration_error(
    preds, target, n_bins: int = 15, norm: str = "l1", ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Binary calibration error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_calibration_error
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_calibration_error(preds, target, n_bins=3)
        Array(0.195, dtype=float32)
    """
    if validate_args:
        _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        _binary_calibration_error_tensor_validation(preds, target, ignore_index)
    preds, target, w = _binary_calibration_error_format(preds, target, ignore_index)
    conf_bin, acc_bin, count_bin = _binned_stats_update(preds, target, n_bins, w)
    return _ce_compute_from_bins(conf_bin, acc_bin, count_bin, norm)


def _multiclass_calibration_error_arg_validation(
    num_classes: int, n_bins: int, norm: str = "l1", ignore_index: Optional[int] = None
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)


def _multiclass_calibration_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Top-label confidence + correctness."""
    confidences = jnp.max(preds, axis=1)
    accuracies = (jnp.argmax(preds, axis=1) == target).astype(jnp.int32)
    return confidences, accuracies


def multiclass_calibration_error(
    preds, target, num_classes: int, n_bins: int = 15, norm: str = "l1",
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Multiclass calibration error.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_calibration_error
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_calibration_error(preds, target, num_classes=3, n_bins=3)
        Array(0.38750002, dtype=float32)
    """
    if validate_args:
        _multiclass_calibration_error_arg_validation(num_classes, n_bins, norm, ignore_index)
        from .stat_scores import _multiclass_stat_scores_tensor_validation

        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, "global", ignore_index)
    preds = jnp.asarray(preds).astype(jnp.float32)
    target = jnp.asarray(target).reshape(-1)
    preds = normalize_logits_if_needed(preds, "softmax")
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.float32)
    confidences, accuracies = _multiclass_calibration_error_update(preds, jnp.clip(target, 0, num_classes - 1))
    conf_bin, acc_bin, count_bin = _binned_stats_update(confidences, accuracies, n_bins, w)
    return _ce_compute_from_bins(conf_bin, acc_bin, count_bin, norm)


def calibration_error(
    preds, target, task: str, n_bins: int = 15, norm: str = "l1", num_classes: Optional[int] = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Task facade (binary/multiclass)."""
    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_calibration_error(preds, target, n_bins, norm, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_calibration_error(preds, target, num_classes, n_bins, norm, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
