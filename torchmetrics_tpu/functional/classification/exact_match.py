"""Exact match (subset accuracy). Parity: reference
``functional/classification/exact_match.py`` (multiclass:45-216 class-side)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.compute import _safe_divide
from ...utilities.enums import ClassificationTaskNoBinary
from .stat_scores import (
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)

Array = jax.Array


def _exact_match_reduce(correct: Array, total: Array) -> Array:
    return _safe_divide(correct, total)


def _multiclass_exact_match_update(
    preds, target, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> Tuple[Array, Array]:
    """Sample is correct when ALL its (multidim) positions are correct; ignored
    positions count as correct (reference semantics)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim + 1:
        preds = jnp.argmax(preds, axis=1)
    n = target.shape[0]
    preds = preds.reshape(n, -1)
    target = target.reshape(n, -1)
    ok = preds == target
    if ignore_index is not None:
        ok = ok | (target == ignore_index)
    correct = ok.all(axis=1).astype(jnp.int32)
    if multidim_average == "global":
        return correct.sum(), jnp.asarray(n, jnp.int32)
    return correct, jnp.ones((n,), jnp.int32)


def multiclass_exact_match(
    preds, target, num_classes: int, multidim_average: str = "global",
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Multiclass exact match.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_exact_match
        >>> preds = jnp.asarray([[0, 1, 2], [1, 1, 2]])
        >>> target = jnp.asarray([[0, 1, 2], [2, 1, 2]])
        >>> multiclass_exact_match(preds, target, num_classes=3)
        Array(0.5, dtype=float32)
    """
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, 1, None, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    correct, total = _multiclass_exact_match_update(preds, target, multidim_average, ignore_index)
    return _exact_match_reduce(correct, total)


def _multilabel_exact_match_update(
    preds, target, num_labels: int, threshold: float = 0.5, multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array]:
    p, t, w = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)  # (N, C, S)
    ok = (p == t) | (w == 0)
    correct = ok.all(axis=1).astype(jnp.int32)  # (N, S)
    if multidim_average == "global":
        return correct.sum(), jnp.asarray(correct.size, jnp.int32)
    return correct.sum(axis=1), jnp.full((correct.shape[0],), correct.shape[1], jnp.int32)


def multilabel_exact_match(
    preds, target, num_labels: int, threshold: float = 0.5, multidim_average: str = "global",
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Multilabel exact match.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_exact_match
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_exact_match(preds, target, num_labels=3)
        Array(0.33333334, dtype=float32)
    """
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, None, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    correct, total = _multilabel_exact_match_update(preds, target, num_labels, threshold, multidim_average, ignore_index)
    return _exact_match_reduce(correct, total)


def exact_match(
    preds, target, task: str, num_classes: Optional[int] = None, num_labels: Optional[int] = None,
    threshold: float = 0.5, multidim_average: str = "global", ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task facade (multiclass/multilabel only)."""
    task = ClassificationTaskNoBinary.from_str(task)
    if task == ClassificationTaskNoBinary.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_exact_match(preds, target, num_classes, multidim_average, ignore_index, validate_args)
    if task == ClassificationTaskNoBinary.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_exact_match(
            preds, target, num_labels, threshold, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
