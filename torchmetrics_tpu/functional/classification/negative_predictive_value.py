"""Negative predictive value. Parity: reference
``functional/classification/negative_predictive_value.py``."""

from __future__ import annotations

from typing import Optional

import jax

from ...utilities.compute import _adjust_weights_safe_divide, _safe_divide
from ._family import make_binary, make_multiclass, make_multilabel, make_task_dispatch

Array = jax.Array


def _negative_predictive_value_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
    zero_division: float = 0,
) -> Array:
    if average == "binary":
        return _safe_divide(tn, tn + fn, zero_division)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tn_s, fn_s = tn.sum(axis), fn.sum(axis)
        return _safe_divide(tn_s, tn_s + fn_s, zero_division)
    score = _safe_divide(tn, tn + fn, zero_division)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn, top_k)


binary_negative_predictive_value = make_binary(_negative_predictive_value_reduce, "binary_negative_predictive_value")
multiclass_negative_predictive_value = make_multiclass(_negative_predictive_value_reduce, "multiclass_negative_predictive_value")
multilabel_negative_predictive_value = make_multilabel(_negative_predictive_value_reduce, "multilabel_negative_predictive_value")
negative_predictive_value = make_task_dispatch(
    binary_negative_predictive_value,
    multiclass_negative_predictive_value,
    multilabel_negative_predictive_value,
    "negative_predictive_value",
)
