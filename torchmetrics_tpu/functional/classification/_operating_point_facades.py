"""Task-dispatch facades for the curve operating-point metrics (reference
``functional/classification/{precision_fixed_recall,recall_fixed_precision,
sensitivity_specificity,specificity_sensitivity}.py`` facade tails).

One shared dispatcher covers all four — the facades differ only in the floor-argument
name and the underlying binary/multiclass/multilabel triple.
"""

from __future__ import annotations

from typing import Optional

from ...utilities.enums import ClassificationTask
from .precision_fixed_recall import (
    binary_precision_at_fixed_recall,
    multiclass_precision_at_fixed_recall,
    multilabel_precision_at_fixed_recall,
)
from .recall_fixed_precision import (
    binary_recall_at_fixed_precision,
    multiclass_recall_at_fixed_precision,
    multilabel_recall_at_fixed_precision,
)
from .sensitivity_specificity import (
    binary_sensitivity_at_specificity,
    multiclass_sensitivity_at_specificity,
    multilabel_sensitivity_at_specificity,
)
from .specificity_sensitivity import (
    binary_specificity_at_sensitivity,
    multiclass_specificity_at_sensitivity,
    multilabel_specificity_at_sensitivity,
)


def _dispatch(
    triple,
    preds,
    target,
    task: str,
    floor: float,
    thresholds,
    num_classes: Optional[int],
    num_labels: Optional[int],
    ignore_index: Optional[int],
    validate_args: bool,
):
    binary_fn, multiclass_fn, multilabel_fn = triple
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_fn(preds, target, floor, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_fn(preds, target, num_classes, floor, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_fn(preds, target, num_labels, floor, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")


def precision_at_fixed_recall(
    preds,
    target,
    task: str,
    min_recall: float,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Highest precision (and its threshold) with recall >= ``min_recall``."""
    return _dispatch(
        (binary_precision_at_fixed_recall, multiclass_precision_at_fixed_recall, multilabel_precision_at_fixed_recall),
        preds, target, task, min_recall, thresholds, num_classes, num_labels, ignore_index, validate_args,
    )


def recall_at_fixed_precision(
    preds,
    target,
    task: str,
    min_precision: float,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Highest recall (and its threshold) with precision >= ``min_precision``."""
    return _dispatch(
        (binary_recall_at_fixed_precision, multiclass_recall_at_fixed_precision, multilabel_recall_at_fixed_precision),
        preds, target, task, min_precision, thresholds, num_classes, num_labels, ignore_index, validate_args,
    )


def sensitivity_at_specificity(
    preds,
    target,
    task: str,
    min_specificity: float,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Highest sensitivity (and its threshold) with specificity >= ``min_specificity``."""
    return _dispatch(
        (binary_sensitivity_at_specificity, multiclass_sensitivity_at_specificity, multilabel_sensitivity_at_specificity),
        preds, target, task, min_specificity, thresholds, num_classes, num_labels, ignore_index, validate_args,
    )


def specificity_at_sensitivity(
    preds,
    target,
    task: str,
    min_sensitivity: float,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Highest specificity (and its threshold) with sensitivity >= ``min_sensitivity``."""
    return _dispatch(
        (binary_specificity_at_sensitivity, multiclass_specificity_at_sensitivity, multilabel_specificity_at_sensitivity),
        preds, target, task, min_sensitivity, thresholds, num_classes, num_labels, ignore_index, validate_args,
    )
