"""Accuracy. Parity: reference ``functional/classification/accuracy.py``
(_accuracy_reduce:37-88, binary_accuracy:91, multiclass_accuracy, multilabel_accuracy,
task facade:462)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...utilities.compute import _adjust_weights_safe_divide, _safe_divide
from ...utilities.enums import ClassificationTask
from .stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multiclass_stat_scores_update,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)

Array = jax.Array


def _accuracy_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
) -> Array:
    """Reduce stat scores into accuracy (reference accuracy.py:37-88)."""
    if average == "binary":
        return _safe_divide(tp + tn, tp + tn + fp + fn)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp_s, fn_s = tp.sum(axis), fn.sum(axis)
        if multilabel:
            fp_s, tn_s = fp.sum(axis), tn.sum(axis)
            return _safe_divide(tp_s + tn_s, tp_s + tn_s + fp_s + fn_s)
        return _safe_divide(tp_s, tp_s + fn_s)
    score = _safe_divide(tp + tn, tp + tn + fp + fn) if multilabel else _safe_divide(tp, tp + fn)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn, top_k)


def binary_accuracy(
    preds,
    target,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Binary accuracy.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_accuracy
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_accuracy(preds, target)
        Array(1., dtype=float32)
    """
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, w = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, w, multidim_average)
    return _accuracy_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_accuracy(
    preds,
    target,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multiclass accuracy.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_accuracy
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_accuracy(preds, target, num_classes=3)
        Array(1., dtype=float32)
    """
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    preds_oh, target, w = _multiclass_stat_scores_format(preds, target, num_classes, top_k, ignore_index)
    tp, fp, tn, fn = _multiclass_stat_scores_update(preds_oh, target, w, num_classes, multidim_average)
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, top_k=top_k)


def multilabel_accuracy(
    preds,
    target,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel accuracy.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_accuracy
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_accuracy(preds, target, num_labels=3)
        Array(0.7777778, dtype=float32)
    """
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, w = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, w, multidim_average)
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def accuracy(
    preds,
    target,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task facade (reference accuracy.py:462).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import accuracy
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> accuracy(preds, target, task='multiclass', num_classes=3)
        Array(1., dtype=float32)
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_accuracy(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_accuracy(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_accuracy(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
