"""Shared machinery for curve operating-point metrics (reference
``functional/classification/{recall_fixed_precision,precision_fixed_recall,
sensitivity_specificity,specificity_sensitivity}.py``).

All four metrics share one shape: compute a (PR or ROC) curve, mask points violating a
floor constraint on one coordinate, and pick the best remaining point on the other.
The reference does this with host-side Python ``max()`` over zipped tuples
(recall_fixed_precision.py:58-77); here it is one vectorized masked lexicographic
argmax over static-shape arrays (binned states keep everything jit-compatible).
"""

from __future__ import annotations

from typing import Callable, List, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def _masked_lex_best(
    objective: Array,
    constraint: Array,
    thresholds: Array,
    min_constraint: float,
    nan_threshold_when_zero: bool = True,
    fallback_threshold: float = float("nan"),
) -> Tuple[Array, Array]:
    """Maximize ``objective`` subject to ``constraint >= min_constraint``.

    Ties on the objective break first by higher constraint, then by higher threshold
    (the reference's lexicographic ``max()`` over ``(obj, con, thr)`` tuples).
    Returns ``(best_objective, best_threshold)``; no feasible point → ``(0, fallback)``.
    """
    n = min(objective.shape[0], constraint.shape[0], thresholds.shape[0])
    obj, con, thr = objective[:n], constraint[:n], thresholds[:n]
    valid = ~(jnp.isnan(obj) | jnp.isnan(con))
    mask = (con >= min_constraint) & valid
    neg = -jnp.inf
    obj_m = jnp.where(mask, obj, neg)
    best_obj = obj_m.max()
    tie1 = mask & (obj_m == best_obj)
    con_m = jnp.where(tie1, con, neg)
    best_con = con_m.max()
    tie2 = tie1 & (con_m == best_con)
    thr_m = jnp.where(tie2, thr, neg)
    best_thr = thr_m.max()
    feasible = mask.any()
    best_obj = jnp.where(feasible, best_obj, 0.0)
    best_thr = jnp.where(feasible, best_thr, fallback_threshold)
    if nan_threshold_when_zero:
        best_thr = jnp.where(best_obj == 0.0, jnp.nan if jnp.isnan(fallback_threshold) else fallback_threshold, best_thr)
    return best_obj, best_thr


def _apply_over_classes(
    reduce_fn: Callable,
    a: Union[Array, List[Array]],
    b: Union[Array, List[Array]],
    thr: Union[Array, List[Array]],
) -> Tuple[Array, Array]:
    """Run a per-curve reduce over per-class curves (stacked 2-D arrays or lists)."""
    if isinstance(a, list):
        pairs = [reduce_fn(ai, bi, ti) for ai, bi, ti in zip(a, b, thr)]
    else:
        if a.ndim == 1:
            return reduce_fn(a, b, thr)
        # binned: a/b are (C, T); thresholds shared (T,)
        pairs = [reduce_fn(a[i], b[i], thr) for i in range(a.shape[0])]
    vals = jnp.stack([p[0] for p in pairs])
    thrs = jnp.stack([jnp.asarray(p[1], jnp.float32) for p in pairs])
    return vals, thrs
