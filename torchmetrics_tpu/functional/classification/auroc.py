"""AUROC. Parity: reference ``functional/classification/auroc.py``
(_reduce_auroc:45-70, _binary_auroc_compute:83-107, multiclass/multilabel below)."""

from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...utilities.checks import _is_traced
from ...utilities.compute import _auc_compute, _safe_divide
from ...utilities.prints import rank_zero_warn
from .precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from .roc import _binary_roc_compute, _multiclass_roc_compute, _multilabel_roc_compute

Array = jax.Array


def _reduce_auroc(fpr, tpr, average: Optional[str] = "macro", weights=None, direction: float = 1.0) -> Array:
    """Reduce per-class AUCs (reference auroc.py:45-70)."""
    if not isinstance(fpr, list):
        res = jax.vmap(lambda x, y: _auc_compute(x, y, direction=direction))(fpr, tpr)
    else:
        res = jnp.stack([_auc_compute(x, y, direction=direction) for x, y in zip(fpr, tpr)])
    if average is None or average == "none":
        return res
    if not _is_traced(res) and bool(jnp.isnan(res).any()):
        # host-only advisory; the masked reduction below is jit-safe either way
        rank_zero_warn(
            f"Average precision score for one or more classes was `nan`. Ignoring these classes in {average}-average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return (jnp.where(idx, res, 0.0).sum()) / idx.sum()
    if average == "weighted" and weights is not None:
        weights = jnp.where(idx, jnp.asarray(weights, jnp.float32), 0.0)
        weights = _safe_divide(weights, weights.sum())
        return (jnp.where(idx, res, 0.0) * weights).sum()
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _binary_auroc_arg_validation(max_fpr: Optional[float] = None, thresholds=None, ignore_index=None) -> None:
    if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
        raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _binary_auroc_compute(state, thresholds: Optional[Array], max_fpr: Optional[float] = None, pos_label: int = 1) -> Array:
    fpr, tpr, _ = _binary_roc_compute(state, thresholds, pos_label)
    if max_fpr is None or max_fpr == 1 or float(jnp.sum(fpr)) == 0 or float(jnp.sum(tpr)) == 0:
        return _auc_compute(fpr, tpr, direction=1.0)
    # partial AUC with McClish correction (reference auroc.py:94-107)
    stop = int(np.searchsorted(np.asarray(fpr), max_fpr, side="right"))
    weight = (max_fpr - float(fpr[stop - 1])) / (float(fpr[stop]) - float(fpr[stop - 1]))
    interp_tpr = float(tpr[stop - 1]) * (1 - weight) + float(tpr[stop]) * weight
    tpr_p = jnp.concatenate([tpr[:stop], jnp.asarray([interp_tpr], tpr.dtype)])
    fpr_p = jnp.concatenate([fpr[:stop], jnp.asarray([max_fpr], fpr.dtype)])
    partial_auc = _auc_compute(fpr_p, tpr_p, direction=1.0)
    min_area = 0.5 * max_fpr**2
    return 0.5 * (1 + (partial_auc - min_area) / (max_fpr - min_area))


def binary_auroc(
    preds, target, max_fpr: Optional[float] = None, thresholds=None, ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Binary auroc.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_auroc
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_auroc(preds, target)
        Array(1., dtype=float32)
    """
    if validate_args:
        _binary_auroc_arg_validation(max_fpr, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    return _binary_auroc_compute(state, thresholds, max_fpr)


def _multiclass_auroc_arg_validation(num_classes, average="macro", thresholds=None, ignore_index=None) -> None:
    if average not in ("macro", "weighted", "none", None):
        raise ValueError(f"Expected argument `average` to be one of ('macro', 'weighted', 'none', None) but got {average}")
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)


def _multiclass_auroc_compute(
    state, num_classes: int, average: Optional[str] = "macro", thresholds: Optional[Array] = None
) -> Array:
    fpr, tpr, _ = _multiclass_roc_compute(state, num_classes, thresholds)
    # support per class = positives per class
    if not isinstance(state, tuple) and thresholds is not None:
        weights = (state[0, :, 1, 0] + state[0, :, 1, 1]).astype(jnp.float32)
    else:
        weights = jnp.asarray(np.bincount(np.asarray(state[1]), minlength=num_classes), jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights=weights)


def multiclass_auroc(
    preds, target, num_classes: int, average: Optional[str] = "macro", thresholds=None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Multiclass auroc.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_auroc
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_auroc(preds, target, num_classes=3)
        Array(1., dtype=float32)
    """
    if validate_args:
        _multiclass_auroc_arg_validation(num_classes, average, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w)
    return _multiclass_auroc_compute(state, num_classes, average, thresholds)


def _multilabel_auroc_arg_validation(num_labels, average="macro", thresholds=None, ignore_index=None) -> None:
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None) but got {average}"
        )
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)


def _multilabel_auroc_compute(
    state, num_labels: int, average: Optional[str] = "macro", thresholds: Optional[Array] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    if average == "micro":
        if not isinstance(state, tuple) and thresholds is not None:
            return _binary_auroc_compute(state.sum(1), thresholds, max_fpr=None)
        preds = np.asarray(state[0]).reshape(-1)
        target = np.asarray(state[1]).reshape(-1)
        if ignore_index is not None:
            keep = target != ignore_index
            preds, target = preds[keep], target[keep]
        return _binary_auroc_compute((jnp.asarray(preds), jnp.asarray(target)), None, max_fpr=None)
    fpr, tpr, _ = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    if not isinstance(state, tuple) and thresholds is not None:
        weights = (state[0, :, 1, 0] + state[0, :, 1, 1]).astype(jnp.float32)
    else:
        t = np.asarray(state[1])
        if ignore_index is not None:
            t = np.where(t == ignore_index, 0, t)
        weights = jnp.asarray((t == 1).sum(0), jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights=weights)


def multilabel_auroc(
    preds, target, num_labels: int, average: Optional[str] = "macro", thresholds=None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Multilabel auroc.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_auroc
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_auroc(preds, target, num_labels=3)
        Array(0.8333333, dtype=float32)
    """
    if validate_args:
        _multilabel_auroc_arg_validation(num_labels, average, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    return _multilabel_auroc_compute(state, num_labels, average, thresholds, ignore_index)


def auroc(
    preds,
    target,
    task: str,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task facade.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import auroc
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> auroc(preds, target, task='binary')
        Array(1., dtype=float32)
    """
    from ...utilities.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_auroc(preds, target, max_fpr, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_auroc(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_auroc(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
