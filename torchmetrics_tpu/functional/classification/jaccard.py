"""Jaccard index (IoU). Parity: reference ``functional/classification/jaccard.py``
(_jaccard_index_reduce:38-98)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...utilities.compute import _safe_divide
from ...utilities.enums import ClassificationTask
from .confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
    _multilabel_confusion_matrix_arg_validation,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_tensor_validation,
    _multilabel_confusion_matrix_update,
)

Array = jax.Array


def _jaccard_index_reduce(
    confmat: Array, average: Optional[str], ignore_index: Optional[int] = None, zero_division: float = 0.0
) -> Array:
    allowed_average = ["binary", "micro", "macro", "weighted", "none", None]
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    confmat = confmat.astype(jnp.float32)
    if average == "binary":
        return _safe_divide(confmat[1, 1], confmat[0, 1] + confmat[1, 0] + confmat[1, 1], zero_division=zero_division)

    ignore_index_cond = ignore_index is not None and 0 <= ignore_index < confmat.shape[0]
    multilabel = confmat.ndim == 3
    if multilabel:
        num = confmat[:, 1, 1]
        denom = confmat[:, 1, 1] + confmat[:, 0, 1] + confmat[:, 1, 0]
    else:
        num = jnp.diag(confmat)
        denom = confmat.sum(0) + confmat.sum(1) - num

    if average == "micro":
        num = num.sum()
        denom = denom.sum() - (denom[ignore_index] if ignore_index_cond else 0.0)

    jaccard = _safe_divide(num, denom, zero_division=zero_division)

    if average is None or average == "none" or average == "micro":
        return jaccard
    if average == "weighted":
        weights = confmat[:, 1, 1] + confmat[:, 1, 0] if multilabel else confmat.sum(1)
    else:
        weights = jnp.ones_like(jaccard)
        if ignore_index_cond:
            weights = weights.at[ignore_index].set(0.0)
        if not multilabel:
            weights = jnp.where(confmat.sum(1) + confmat.sum(0) == 0, 0.0, weights)
    return ((weights * jaccard) / weights.sum()).sum()


def binary_jaccard_index(
    preds, target, threshold: float = 0.5, ignore_index: Optional[int] = None,
    validate_args: bool = True, zero_division: float = 0.0,
) -> Array:
    """Binary jaccard index.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_jaccard_index
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_jaccard_index(preds, target)
        Array(1., dtype=float32)
    """
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize=None)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target, w = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target, w)
    return _jaccard_index_reduce(confmat, average="binary", zero_division=zero_division)


def multiclass_jaccard_index(
    preds, target, num_classes: int, average: Optional[str] = "macro",
    ignore_index: Optional[int] = None, validate_args: bool = True, zero_division: float = 0.0,
) -> Array:
    """Multiclass jaccard index.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_jaccard_index
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_jaccard_index(preds, target, num_classes=3)
        Array(1., dtype=float32)
    """
    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize=None)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, w = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, w, num_classes)
    return _jaccard_index_reduce(confmat, average=average, ignore_index=ignore_index, zero_division=zero_division)


def multilabel_jaccard_index(
    preds, target, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
    ignore_index: Optional[int] = None, validate_args: bool = True, zero_division: float = 0.0,
) -> Array:
    """Multilabel jaccard index.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_jaccard_index
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_jaccard_index(preds, target, num_labels=3)
        Array(0.6666667, dtype=float32)
    """
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize=None)
        _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, w = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, w, num_labels)
    return _jaccard_index_reduce(confmat, average=average, zero_division=zero_division)


def jaccard_index(
    preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
    num_labels: Optional[int] = None, average: Optional[str] = "macro",
    ignore_index: Optional[int] = None, validate_args: bool = True, zero_division: float = 0.0,
) -> Array:
    """Task facade."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_jaccard_index(preds, target, threshold, ignore_index, validate_args, zero_division)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_jaccard_index(preds, target, num_classes, average, ignore_index, validate_args, zero_division)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_jaccard_index(
            preds, target, num_labels, threshold, average, ignore_index, validate_args, zero_division
        )
    raise ValueError(f"Not handled value: {task}")
