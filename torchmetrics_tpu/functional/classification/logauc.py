"""Log-scale partial AUROC (reference ``functional/classification/logauc.py``).

Area under TPR vs log10(FPR) restricted to ``fpr_range``, normalized by the log-range
width — emphasizes the low-FPR regime (virtual screening, anomaly detection).
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ...utilities.compute import _auc_compute, interp
from ...utilities.prints import rank_zero_warn
from .precision_recall_curve import (
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from .roc import _binary_roc_compute, _multiclass_roc_compute, _multilabel_roc_compute

Array = jax.Array


def _validate_fpr_range(fpr_range: Tuple[float, float]) -> None:
    if not isinstance(fpr_range, tuple) or len(fpr_range) != 2:
        raise ValueError(f"The `fpr_range` should be a tuple of two floats, but got {type(fpr_range)}.")
    if not (0 <= fpr_range[0] < fpr_range[1] <= 1):
        raise ValueError(f"The `fpr_range` should be a tuple of two floats in the range [0, 1], but got {fpr_range}.")


def _binary_logauc_compute(fpr: Array, tpr: Array, fpr_range: Tuple[float, float] = (0.001, 0.1)) -> Array:
    if fpr.size < 2 or tpr.size < 2:
        rank_zero_warn(
            "At least two values on for the fpr and tpr are required to compute the log AUC. Returns 0 score."
        )
        return jnp.zeros(())
    bounds_lin = jnp.asarray(fpr_range, jnp.result_type(fpr.dtype, jnp.float32))
    # anchor the curve exactly at the range bounds, then integrate on the log axis
    tpr = jnp.sort(jnp.concatenate([tpr, interp(bounds_lin, fpr, tpr)]))
    fpr = jnp.sort(jnp.concatenate([fpr, bounds_lin]))
    keep = (fpr >= fpr_range[0]) & (fpr <= fpr_range[1])  # host-side: dynamic shape ok
    x = jnp.log10(fpr[keep])
    y = tpr[keep]
    bounds = jnp.log10(bounds_lin)
    return jnp.trapezoid(y, x) / (bounds[1] - bounds[0])


def _reduce_logauc(
    fpr: Union[Array, List[Array]],
    tpr: Union[Array, List[Array]],
    fpr_range: Tuple[float, float] = (0.001, 0.1),
    average: Optional[str] = "macro",
) -> Array:
    if not isinstance(fpr, list) and fpr.ndim == 1:
        return _binary_logauc_compute(fpr, tpr, fpr_range)
    scores = jnp.stack([_binary_logauc_compute(f, t, fpr_range) for f, t in zip(fpr, tpr)])
    if average == "macro":
        return scores.mean()
    if average in (None, "none"):
        return scores
    raise ValueError(f"Expected argument `average` to be one of ('macro', 'none', None) but got {average}")


def binary_logauc(
    preds, target, fpr_range: Tuple[float, float] = (0.001, 0.1), thresholds=None, ignore_index=None,
    validate_args: bool = True,
) -> Array:
    """Binary logauc.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_logauc
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_logauc(preds, target)
        Array(1., dtype=float32)
    """
    if validate_args:
        _validate_fpr_range(fpr_range)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    fpr, tpr, _ = _binary_roc_compute(state, thresholds)
    return _binary_logauc_compute(fpr, tpr, fpr_range)


def multiclass_logauc(
    preds, target, num_classes: int, fpr_range: Tuple[float, float] = (0.001, 0.1), average: Optional[str] = "macro",
    thresholds=None, ignore_index=None, validate_args: bool = True,
) -> Array:
    """Multiclass logauc.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_logauc
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_logauc(preds, target, num_classes=3)
        Array(1., dtype=float32)
    """
    if validate_args:
        _validate_fpr_range(fpr_range)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        import numpy as np

        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w)
    fpr, tpr, _ = _multiclass_roc_compute(state, num_classes, thresholds)
    return _reduce_logauc(fpr, tpr, fpr_range, average)


def multilabel_logauc(
    preds, target, num_labels: int, fpr_range: Tuple[float, float] = (0.001, 0.1), average: Optional[str] = "macro",
    thresholds=None, ignore_index=None, validate_args: bool = True,
) -> Array:
    """Multilabel logauc.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_logauc
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_logauc(preds, target, num_labels=3)
        Array(0.6666667, dtype=float32)
    """
    if validate_args:
        _validate_fpr_range(fpr_range)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    fpr, tpr, _ = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    return _reduce_logauc(fpr, tpr, fpr_range, average)


def logauc(
    preds, target, task: str, thresholds=None, num_classes=None, num_labels=None,
    fpr_range: Tuple[float, float] = (0.001, 0.1), average: Optional[str] = None,
    ignore_index=None, validate_args: bool = True,
):
    """Task dispatch (reference logauc.py facade; its default is ``average=None``
    — per-class scores — even though the per-task functions default to macro)."""
    from ...utilities.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_logauc(preds, target, fpr_range, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_logauc(preds, target, num_classes, fpr_range, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_logauc(preds, target, num_labels, fpr_range, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
