"""Confusion matrix. Parity: reference
``functional/classification/confusion_matrix.py`` (binary:51, multiclass:191,
multilabel:335 in the class file; kernels here).

TPU note: the multiclass kernel is a single fused-index scatter-add
(``_bincount_2d``) — one XLA scatter for the whole batch, static ``(C, C)`` output; no
boolean indexing, ``ignore_index`` handled by zero weights.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...utilities.checks import _check_same_shape, _is_traced
from ...utilities.compute import _safe_divide, normalize_logits_if_needed
from ...utilities.data import _bincount_2d
from ...utilities.enums import ClassificationTask
from ...utilities.prints import rank_zero_warn

Array = jax.Array


def _confusion_matrix_reduce(confmat: Array, normalize: Optional[str] = None) -> Array:
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument `normalize` needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32)
        if normalize == "true":
            return _safe_divide(confmat, confmat.sum(axis=-1, keepdims=True))
        if normalize == "pred":
            return _safe_divide(confmat, confmat.sum(axis=-2, keepdims=True))
        if normalize == "all":
            return _safe_divide(confmat, confmat.sum(axis=(-2, -1), keepdims=True))
    return confmat


# --------------------------------------------------------------------- binary


def _binary_confusion_matrix_arg_validation(
    threshold: float = 0.5, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if normalize not in ("true", "pred", "all", "none", None):
        raise ValueError(f"Argument `normalize` needs to one of the following: ('true', 'pred', 'all', 'none', None)")


def _binary_confusion_matrix_tensor_validation(preds, target, ignore_index: Optional[int] = None) -> None:
    from .stat_scores import _binary_stat_scores_tensor_validation

    _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)


def _binary_confusion_matrix_format(
    preds, target, threshold: float = 0.5, ignore_index: Optional[int] = None, convert_to_labels: bool = True
) -> Tuple[Array, Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target).reshape(-1)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = normalize_logits_if_needed(preds, "sigmoid")
        if convert_to_labels:
            preds = preds > threshold
    preds = preds.reshape(-1)
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.int32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.int32)
    return preds.astype(jnp.int32) if convert_to_labels else preds, target.astype(jnp.int32), w


def _binary_confusion_matrix_update(preds: Array, target: Array, weights: Array) -> Array:
    return _bincount_2d(target, preds, 2, 2, weights=None if weights is None else weights)


def _binary_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def binary_confusion_matrix(
    preds,
    target,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Binary confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_confusion_matrix
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_confusion_matrix(preds, target)
        Array([[3., 0.],
               [0., 3.]], dtype=float32)
    """
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target, w = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target, w)
    return _binary_confusion_matrix_compute(confmat, normalize)


# ------------------------------------------------------------------ multiclass


def _multiclass_confusion_matrix_arg_validation(
    num_classes: int, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if normalize not in ("true", "pred", "all", "none", None):
        raise ValueError(f"Argument `normalize` needs to one of the following: ('true', 'pred', 'all', 'none', None)")


def _multiclass_confusion_matrix_tensor_validation(
    preds, target, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    from .stat_scores import _multiclass_stat_scores_tensor_validation

    _multiclass_stat_scores_tensor_validation(preds, target, num_classes, "global", ignore_index)


def _multiclass_confusion_matrix_format(
    preds, target, ignore_index: Optional[int] = None, convert_to_labels: bool = True
) -> Tuple[Array, Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim + 1 and convert_to_labels:
        preds = jnp.argmax(preds, axis=1)
    preds = preds.reshape(-1) if convert_to_labels else preds
    target = target.reshape(-1)
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.int32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.int32)
    return preds, target.astype(jnp.int32), w


def _multiclass_confusion_matrix_update(preds: Array, target: Array, weights: Array, num_classes: int) -> Array:
    return _bincount_2d(target, preds, num_classes, num_classes, weights=None if weights is None else weights)


def _multiclass_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multiclass_confusion_matrix(
    preds,
    target,
    num_classes: int,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multiclass confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_confusion_matrix
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_confusion_matrix(preds, target, num_classes=3)
        Array([[1., 0., 0.],
               [0., 2., 0.],
               [0., 0., 1.]], dtype=float32)
    """
    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, w = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, w, num_classes)
    return _multiclass_confusion_matrix_compute(confmat, normalize)


# ------------------------------------------------------------------ multilabel


def _multilabel_confusion_matrix_arg_validation(
    num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if normalize not in ("true", "pred", "all", "none", None):
        raise ValueError(f"Argument `normalize` needs to one of the following: ('true', 'pred', 'all', 'none', None)")


def _multilabel_confusion_matrix_tensor_validation(
    preds, target, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    from .stat_scores import _multilabel_stat_scores_tensor_validation

    _multilabel_stat_scores_tensor_validation(preds, target, num_labels, "global", ignore_index)


def _multilabel_confusion_matrix_format(
    preds, target, num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None, should_threshold: bool = True
) -> Tuple[Array, Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = normalize_logits_if_needed(preds, "sigmoid")
        if should_threshold:
            preds = preds > threshold
    n, c = preds.shape[0], preds.shape[1]
    preds = jnp.moveaxis(preds.reshape(n, c, -1), 1, -1).reshape(-1, c)  # (N*S, C)
    target = jnp.moveaxis(target.reshape(n, c, -1), 1, -1).reshape(-1, c)
    if ignore_index is not None:
        w = (target != ignore_index).astype(jnp.int32)
        target = jnp.where(w == 1, target, 0)
    else:
        w = jnp.ones(target.shape, jnp.int32)
    return preds.astype(jnp.int32) if should_threshold else preds, target.astype(jnp.int32), w


def _multilabel_confusion_matrix_update(preds: Array, target: Array, weights: Array, num_labels: int) -> Array:
    """Per-label 2×2 confusion: ``(C, 2, 2)`` via elementwise sums (no scatter)."""
    w = weights
    tp = (w * preds * target).sum(0)
    fp = (w * preds * (1 - target)).sum(0)
    fn = (w * (1 - preds) * target).sum(0)
    tn = (w * (1 - preds) * (1 - target)).sum(0)
    return jnp.stack([tn, fp, fn, tp], axis=-1).reshape(num_labels, 2, 2)


def _multilabel_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multilabel_confusion_matrix(
    preds,
    target,
    num_labels: int,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_confusion_matrix
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_confusion_matrix(preds, target, num_labels=3)
        Array([[[2, 0],
                [0, 1]],
        <BLANKLINE>
               [[1, 1],
                [0, 1]],
        <BLANKLINE>
               [[1, 0],
                [1, 1]]], dtype=int32)
    """
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize)
        _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, w = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, w, num_labels)
    return _multilabel_confusion_matrix_compute(confmat, normalize)


def confusion_matrix(
    preds,
    target,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task facade."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_confusion_matrix(preds, target, threshold, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_confusion_matrix(preds, target, num_classes, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_confusion_matrix(preds, target, num_labels, threshold, normalize, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
