"""Average precision. Parity: reference
``functional/classification/average_precision.py`` (_reduce_average_precision:43-68,
_binary_average_precision_compute:72-79, multiclass:168, multilabel below)."""

from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...utilities.checks import _is_traced
from ...utilities.compute import _safe_divide
from ...utilities.prints import rank_zero_warn
from .precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)

Array = jax.Array


def _nan_to_zero(x: Array) -> Array:
    return jnp.where(jnp.isnan(x), jnp.zeros_like(x), x)


def _reduce_average_precision(precision, recall, average: Optional[str] = "macro", weights=None) -> Array:
    if not isinstance(precision, list):
        p, r = _nan_to_zero(precision), _nan_to_zero(recall)
        res = -jnp.sum((r[:, 1:] - r[:, :-1]) * p[:, :-1], axis=1)
    else:
        # unbinned per-class curves: NaNs must PROPAGATE (reference
        # average_precision.py:53-56 sums the raw curves) — a class with no
        # positives yields a NaN AP which the macro/weighted reduction then
        # skips, instead of diluting the average with a spurious 0
        res = jnp.stack([-jnp.sum((r[1:] - r[:-1]) * p[:-1]) for p, r in zip(precision, recall)])
    if average is None or average == "none":
        return res
    # the NaN-class warning needs a concrete value; under jit (the fused
    # collection path) the masked reduction below is already branchless, so the
    # warning is simply skipped rather than breaking the trace
    if not _is_traced(res) and bool(jnp.isnan(res).any()):
        rank_zero_warn(
            f"Average precision score for one or more classes was `nan`. Ignoring these classes in {average}-average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return jnp.where(idx, res, 0.0).sum() / idx.sum()
    if average == "weighted" and weights is not None:
        weights = jnp.where(idx, jnp.asarray(weights, jnp.float32), 0.0)
        weights = _safe_divide(weights, weights.sum())
        return (jnp.where(idx, res, 0.0) * weights).sum()
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _binary_average_precision_compute(state, thresholds: Optional[Array]) -> Array:
    precision, recall, _ = _binary_precision_recall_curve_compute(state, thresholds)
    p, r = _nan_to_zero(precision), _nan_to_zero(recall)
    return -jnp.sum((r[1:] - r[:-1]) * p[:-1])


def binary_average_precision(
    preds, target, thresholds=None, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Array:
    """Binary average precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import binary_average_precision
        >>> preds = jnp.asarray([0.11, 0.22, 0.84, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> binary_average_precision(preds, target)
        Array(1., dtype=float32)
    """
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, w = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _binary_precision_recall_curve_update(preds, target, thresholds, w)
    return _binary_average_precision_compute(state, thresholds)


def _multiclass_average_precision_arg_validation(num_classes, average="macro", thresholds=None, ignore_index=None):
    if average not in ("macro", "weighted", "none", None):
        raise ValueError(f"Expected argument `average` to be one of ('macro', 'weighted', 'none', None) but got {average}")
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)


def _multiclass_average_precision_compute(
    state, num_classes: int, average: Optional[str] = "macro", thresholds: Optional[Array] = None
) -> Array:
    precision, recall, _ = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    if not isinstance(state, tuple) and thresholds is not None:
        weights = (state[0, :, 1, 0] + state[0, :, 1, 1]).astype(jnp.float32)
    else:
        weights = jnp.asarray(np.bincount(np.asarray(state[1]), minlength=num_classes), jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=weights)


def multiclass_average_precision(
    preds, target, num_classes: int, average: Optional[str] = "macro", thresholds=None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Multiclass average precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multiclass_average_precision
        >>> preds = jnp.asarray([[0.75, 0.05, 0.20], [0.10, 0.80, 0.10], [0.20, 0.30, 0.50], [0.25, 0.40, 0.35]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> multiclass_average_precision(preds, target, num_classes=3)
        Array(1., dtype=float32)
    """
    if validate_args:
        _multiclass_average_precision_arg_validation(num_classes, average, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, w = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        keep = np.asarray(w) == 1
        preds, target = preds[keep], target[keep]
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, w)
    return _multiclass_average_precision_compute(state, num_classes, average, thresholds)


def _multilabel_average_precision_arg_validation(num_labels, average="macro", thresholds=None, ignore_index=None):
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None) but got {average}"
        )
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)


def _multilabel_average_precision_compute(
    state, num_labels: int, average: Optional[str] = "macro", thresholds: Optional[Array] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    if average == "micro":
        if not isinstance(state, tuple) and thresholds is not None:
            return _binary_average_precision_compute(state.sum(1), thresholds)
        preds = np.asarray(state[0]).reshape(-1)
        target = np.asarray(state[1]).reshape(-1)
        if ignore_index is not None:
            keep = target != ignore_index
            preds, target = preds[keep], target[keep]
        return _binary_average_precision_compute((jnp.asarray(preds), jnp.asarray(target)), None)
    precision, recall, _ = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    if not isinstance(state, tuple) and thresholds is not None:
        weights = (state[0, :, 1, 0] + state[0, :, 1, 1]).astype(jnp.float32)
    else:
        t = np.asarray(state[1])
        if ignore_index is not None:
            t = np.where(t == ignore_index, 0, t)
        weights = jnp.asarray((t == 1).sum(0), jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=weights)


def multilabel_average_precision(
    preds, target, num_labels: int, average: Optional[str] = "macro", thresholds=None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Multilabel average precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import multilabel_average_precision
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 0, 0], [0, 1, 1]])
        >>> multilabel_average_precision(preds, target, num_labels=3)
        Array(0.8333333, dtype=float32)
    """
    if validate_args:
        _multilabel_average_precision_arg_validation(num_labels, average, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, w = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, w)
    return _multilabel_average_precision_compute(state, num_labels, average, thresholds, ignore_index)


def average_precision(
    preds,
    target,
    task: str,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task facade."""
    from ...utilities.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_average_precision(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_average_precision(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_average_precision(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
