"""PSNR with blocked effect (reference ``functional/image/psnrb.py``)."""

from __future__ import annotations

import math
from typing import Tuple

import jax.numpy as jnp
import numpy as np


def _compute_bef(x: jnp.ndarray, block_size: int = 8) -> jnp.ndarray:
    """Block-boundary effect factor. Boundary column/row index sets are static
    (shape-derived), so the gather patterns compile cleanly."""
    _, channels, height, width = x.shape
    if channels > 1:
        raise ValueError(f"`psnrb` metric expects grayscale images, but got images with {channels} channels.")

    h = np.arange(width - 1)
    h_b = np.arange(block_size - 1, width - 1, block_size)
    h_bc = np.setdiff1d(h, h_b)
    v = np.arange(height - 1)
    v_b = np.arange(block_size - 1, height - 1, block_size)
    v_bc = np.setdiff1d(v, v_b)

    d_b = ((x[:, :, :, h_b] - x[:, :, :, h_b + 1]) ** 2).sum()
    d_bc = ((x[:, :, :, h_bc] - x[:, :, :, h_bc + 1]) ** 2).sum()
    d_b = d_b + ((x[:, :, v_b, :] - x[:, :, v_b + 1, :]) ** 2).sum()
    d_bc = d_bc + ((x[:, :, v_bc, :] - x[:, :, v_bc + 1, :]) ** 2).sum()

    n_hb = height * (width / block_size) - 1
    n_hbc = (height * (width - 1)) - n_hb
    n_vb = width * (height / block_size) - 1
    n_vbc = (width * (height - 1)) - n_vb
    d_b = d_b / (n_hb + n_vb)
    d_bc = d_bc / (n_hbc + n_vbc)
    t = math.log2(block_size) / math.log2(min(height, width))
    return jnp.where(d_b > d_bc, t * (d_b - d_bc), 0.0)


def _psnrb_compute(sum_squared_error, bef, num_obs, data_range) -> jnp.ndarray:
    sum_squared_error = sum_squared_error / num_obs + bef
    return 10 * jnp.log10(data_range**2 / sum_squared_error)


def _psnrb_update(preds, target, block_size: int = 8) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    sum_squared_error = jnp.sum((preds - target) ** 2)
    num_obs = jnp.asarray(target.size)
    bef = _compute_bef(preds, block_size=block_size)
    return sum_squared_error, bef, num_obs


def peak_signal_noise_ratio_with_blocked_effect(preds, target, data_range, block_size: int = 8) -> jnp.ndarray:
    """PSNR-B: PSNR penalized by the block-boundary effect factor (grayscale only).
    ``data_range`` as a tuple clamps inputs to that interval."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range_val = jnp.asarray(data_range[1] - data_range[0], jnp.float32)
    else:
        data_range_val = jnp.asarray(float(data_range), jnp.float32)
    sum_squared_error, bef, num_obs = _psnrb_update(preds, target, block_size=block_size)
    return _psnrb_compute(sum_squared_error, bef, num_obs, data_range_val)
