"""Peak signal-to-noise ratio (reference ``functional/image/psnr.py``)."""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp

from ...utilities.prints import rank_zero_warn
from .utils import reduce


def _psnr_compute(
    sum_squared_error: jnp.ndarray,
    num_obs: jnp.ndarray,
    data_range: jnp.ndarray,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
) -> jnp.ndarray:
    psnr_base_e = 2 * jnp.log(data_range) - jnp.log(sum_squared_error / num_obs)
    psnr_vals = psnr_base_e * (10 / jnp.log(jnp.asarray(base)))
    return reduce(psnr_vals, reduction)


def _psnr_update(preds, target, dim: Optional[Union[int, Tuple[int, ...]]] = None):
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        preds = preds.astype(jnp.float32)
    if not jnp.issubdtype(target.dtype, jnp.floating):
        target = target.astype(jnp.float32)
    if dim is None:
        sum_squared_error = jnp.sum((preds - target) ** 2)
        num_obs = jnp.asarray(target.size)
        return sum_squared_error, num_obs
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=dim)
    dim_list = [dim] if isinstance(dim, int) else list(dim)
    if not dim_list:
        num_obs = jnp.asarray(target.size)
    else:
        # shapes are trace-time static: a plain python product, never a
        # device op + int() readback of its result
        n = 1
        for d in dim_list:
            n *= target.shape[d]
        num_obs = jnp.asarray(n)
        num_obs = jnp.broadcast_to(num_obs, sum_squared_error.shape)
    return sum_squared_error, num_obs


def peak_signal_noise_ratio(
    preds,
    target,
    data_range: Union[float, Tuple[float, float]],
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> jnp.ndarray:
    """Compute PSNR; ``data_range`` as a tuple clamps inputs to that interval."""
    if dim is None and reduction != "elementwise_mean":
        rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range_val = jnp.asarray(data_range[1] - data_range[0], jnp.float32)
    else:
        data_range_val = jnp.asarray(float(data_range), jnp.float32)
    sum_squared_error, num_obs = _psnr_update(preds, target, dim=dim)
    return _psnr_compute(sum_squared_error, num_obs, data_range_val, base=base, reduction=reduction)


def _compat_peak_signal_noise_ratio(
    preds,
    target,
    data_range: Union[float, Tuple[float, float]] = 3.0,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> jnp.ndarray:
    """Alias exported as top-level ``functional.peak_signal_noise_ratio``: the
    reference exports its deprecated wrapper there, whose ``data_range`` defaults
    to 3.0 (reference ``functional/image/_deprecated.py:80-86``), unlike the
    strict ``functional.image`` export.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import peak_signal_noise_ratio
        >>> preds = jnp.asarray([[0.0, 1.0], [2.0, 3.0]])
        >>> target = jnp.asarray([[3.0, 2.0], [1.0, 0.0]])
        >>> peak_signal_noise_ratio(preds, target)
        Array(2.552725, dtype=float32)
    """
    return peak_signal_noise_ratio(preds, target, data_range, base, reduction, dim)
