"""Sliding-window RMSE (reference ``functional/image/rmse_sw.py``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from .utils import _check_image_pair, uniform_filter


def _rmse_sw_update(
    preds,
    target,
    window_size: int,
    rmse_val_sum: Optional[jnp.ndarray],
    rmse_map: Optional[jnp.ndarray],
    total_images: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    preds, target = _check_image_pair(preds, target)
    if round(window_size / 2) >= target.shape[2] or round(window_size / 2) >= target.shape[3]:
        raise ValueError(
            f"Parameter `round(window_size / 2)` is expected to be smaller than"
            f" {min(target.shape[2], target.shape[3])} but got {round(window_size / 2)}."
        )
    total_images = (total_images + target.shape[0]) if total_images is not None else jnp.asarray(float(target.shape[0]))
    error = (target - preds) ** 2
    error = uniform_filter(error, window_size)
    _rmse_map = jnp.sqrt(error)
    crop_slide = round(window_size / 2)
    rmse_val = _rmse_map[:, :, crop_slide:-crop_slide, crop_slide:-crop_slide].sum(0).mean()
    rmse_val_sum = rmse_val_sum + rmse_val if rmse_val_sum is not None else rmse_val
    rmse_map = rmse_map + _rmse_map.sum(0) if rmse_map is not None else _rmse_map.sum(0)
    return rmse_val_sum, rmse_map, total_images


def _rmse_sw_compute(rmse_val_sum: Optional[jnp.ndarray], rmse_map: jnp.ndarray, total_images: jnp.ndarray):
    rmse = rmse_val_sum / total_images if rmse_val_sum is not None else None
    return rmse, rmse_map / total_images


def root_mean_squared_error_using_sliding_window(
    preds, target, window_size: int = 8, return_rmse_map: bool = False
):
    """RMSE over a uniform sliding window (optionally returning the error map).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import root_mean_squared_error_using_sliding_window
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> root_mean_squared_error_using_sliding_window(preds, target)
        Array(0.4098781, dtype=float32)
    """
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    rmse_val_sum, rmse_map, total_images = _rmse_sw_update(
        preds, target, window_size, rmse_val_sum=None, rmse_map=None, total_images=None
    )
    rmse, rmse_map = _rmse_sw_compute(rmse_val_sum, rmse_map, total_images)
    if return_rmse_map:
        return rmse, rmse_map
    return rmse
