"""ERGAS (reference ``functional/image/ergas.py``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .utils import _check_image_pair, reduce


def _ergas_update(preds, target):
    return _check_image_pair(preds, target)


def _ergas_compute(preds, target, ratio: float = 4, reduction: Optional[str] = "elementwise_mean"):
    b, c, h, w = preds.shape
    preds = preds.reshape(b, c, h * w)
    target = target.reshape(b, c, h * w)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=2)
    rmse_per_band = jnp.sqrt(sum_squared_error / (h * w))
    mean_target = jnp.mean(target, axis=2)
    ergas_score = 100 / ratio * jnp.sqrt(jnp.sum((rmse_per_band / mean_target) ** 2, axis=1) / c)
    return reduce(ergas_score, reduction)


def error_relative_global_dimensionless_synthesis(
    preds, target, ratio: float = 4, reduction: Optional[str] = "elementwise_mean"
) -> jnp.ndarray:
    """ERGAS: band-wise relative RMSE aggregated over channels.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import error_relative_global_dimensionless_synthesis
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> error_relative_global_dimensionless_synthesis(preds, target)
        Array(20.90032, dtype=float32)
    """
    preds, target = _ergas_update(preds, target)
    return _ergas_compute(preds, target, ratio, reduction)
