"""DISTS — Deep Image Structure and Texture Similarity (reference
``functional/image/dists.py``; Ding et al., 2020).

VGG16 trunk with hanning-window L2-pooling in place of maxpools, tapped at the five
relu stages plus the raw input; per-channel texture (mean) and structure (covariance)
similarities weighted by learned alpha/beta. Weights load from a converted pickle
(the reference pulls the VGG backbone from torchvision and ships alpha/beta in-tree;
neither is downloadable in an air-gapped pod) — ``pretrained=False`` gives
deterministic random parameters for machinery testing.
"""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .lpips import _VGG_SPEC, _conv

_DISTS_CHNS = (3, 64, 128, 256, 512, 512)
_DISTS_TAPS = (4, 9, 16, 23, 30)  # vgg16.features indices after relu{1_2,2_2,3_3,4_3,5_3}
_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


def _l2pool_filter(channels: int, filter_size: int = 5) -> jnp.ndarray:
    a = np.hanning(filter_size)[1:-1]
    g = a[:, None] * a[None, :]
    g = (g / g.sum()).astype(np.float32)
    return jnp.asarray(np.broadcast_to(g[None, None], (channels, 1, g.shape[0], g.shape[1])).copy())


def _l2pool(x: jnp.ndarray, channels: int, filter_size: int = 5, stride: int = 2) -> jnp.ndarray:
    pad = (filter_size - 2) // 2
    out = lax.conv_general_dilated(
        x**2, _l2pool_filter(channels, filter_size), (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"), feature_group_count=channels,
        precision=lax.Precision.HIGHEST,
    )
    return jnp.sqrt(out + 1e-12)


def _dists_backbone(backbone: List, x: jnp.ndarray) -> List[jnp.ndarray]:
    """VGG16 stages with L2-pooling; returns [input, relu1_2, ..., relu5_3]."""
    feats = [x]
    h = (x - jnp.asarray(_MEAN)[None, :, None, None]) / jnp.asarray(_STD)[None, :, None, None]
    for idx, layer in enumerate(_VGG_SPEC):
        kind = layer[0]
        if kind == "conv":
            _, _, _, _, stride, pad = layer
            h = _conv(h, backbone[idx]["w"], backbone[idx]["b"], stride, pad)
        elif kind == "relu":
            h = jax.nn.relu(h)
        elif kind == "maxpool":
            h = _l2pool(h, h.shape[1])  # DISTS swaps maxpool for L2-pooling
        if idx + 1 in _DISTS_TAPS:
            feats.append(h)
    return feats


class DISTSNetwork:
    """Jitted DISTS scorer with learned per-channel alpha/beta weights."""

    def __init__(self, pretrained: bool = True, weights_path: Optional[str] = None, seed: int = 0) -> None:
        if pretrained:
            if weights_path is None:
                raise ModuleNotFoundError(
                    "Pretrained DISTS weights (VGG backbone + alpha/beta) are not bundled and "
                    "cannot be downloaded in an air-gapped environment. Convert them offline with "
                    "`convert_dists_weights` and pass `weights_path`, or use `pretrained=False`."
                )
            with open(weights_path, "rb") as f:
                payload = pickle.load(f)
            self.backbone = jax.tree.map(jnp.asarray, payload["backbone"])
            self.alpha = jnp.asarray(payload["alpha"]).reshape(1, -1)
            self.beta = jnp.asarray(payload["beta"]).reshape(1, -1)
        else:
            from .lpips import LPIPSNetwork

            self.backbone = LPIPSNetwork("vgg", pretrained=False, seed=seed).backbone
            key = jax.random.PRNGKey(seed)
            k1, k2 = jax.random.split(key)
            total = sum(_DISTS_CHNS)
            self.alpha = 0.1 + 0.01 * jax.random.normal(k1, (1, total))
            self.beta = 0.1 + 0.01 * jax.random.normal(k2, (1, total))
        self._apply = jax.jit(self._forward)

    def _forward(self, backbone, alpha, beta, x, y):
        feats0 = _dists_backbone(backbone, x)
        feats1 = _dists_backbone(backbone, y)
        c1 = c2 = 1e-6
        w_sum = alpha.sum() + beta.sum()
        alphas = jnp.split(alpha / w_sum, np.cumsum(_DISTS_CHNS)[:-1].tolist(), axis=1)
        betas = jnp.split(beta / w_sum, np.cumsum(_DISTS_CHNS)[:-1].tolist(), axis=1)
        dist1 = jnp.zeros((x.shape[0],))
        dist2 = jnp.zeros((x.shape[0],))
        for k in range(len(_DISTS_CHNS)):
            x_mean = feats0[k].mean(axis=(2, 3))
            y_mean = feats1[k].mean(axis=(2, 3))
            s1 = (2 * x_mean * y_mean + c1) / (x_mean**2 + y_mean**2 + c1)
            dist1 = dist1 + (alphas[k] * s1).sum(axis=1)
            x_var = ((feats0[k] - x_mean[:, :, None, None]) ** 2).mean(axis=(2, 3))
            y_var = ((feats1[k] - y_mean[:, :, None, None]) ** 2).mean(axis=(2, 3))
            xy_cov = (feats0[k] * feats1[k]).mean(axis=(2, 3)) - x_mean * y_mean
            s2 = (2 * xy_cov + c2) / (x_var + y_var + c2)
            dist2 = dist2 + (betas[k] * s2).sum(axis=1)
        return 1 - (dist1 + dist2)

    def __call__(self, preds, target) -> jnp.ndarray:
        return self._apply(self.backbone, self.alpha, self.beta, jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32))


def convert_dists_weights(vgg_features_state_dict: Dict, dists_state_dict: Dict, out_path: str) -> None:
    """Convert torchvision vgg16 ``features`` + the reference's ``dists_models/weights.pt``
    (alpha/beta) into the pickle this scorer loads (run offline)."""
    backbone = []
    for idx, layer in enumerate(_VGG_SPEC):
        if layer[0] == "conv":
            backbone.append({
                "w": np.asarray(vgg_features_state_dict[f"{idx}.weight"]),
                "b": np.asarray(vgg_features_state_dict[f"{idx}.bias"]),
            })
        else:
            backbone.append({})
    with open(out_path, "wb") as f:
        pickle.dump({
            "backbone": backbone,
            "alpha": np.asarray(dists_state_dict["alpha"]).reshape(-1),
            "beta": np.asarray(dists_state_dict["beta"]).reshape(-1),
        }, f)


_NET_CACHE: Dict[Tuple, DISTSNetwork] = {}


def deep_image_structure_and_texture_similarity(
    preds, target, reduction: Optional[str] = None,
    weights_path: Optional[str] = None, pretrained: bool = True,
) -> jnp.ndarray:
    """DISTS between two NCHW image batches in [0, 1]."""
    key = (pretrained, weights_path)
    if key not in _NET_CACHE:
        _NET_CACHE[key] = DISTSNetwork(pretrained=pretrained, weights_path=weights_path)
    scores = _NET_CACHE[key](preds, target)
    if reduction == "sum":
        return scores.sum()
    if reduction == "mean":
        return scores.mean()
    if reduction is None or reduction == "none":
        return scores
    raise ValueError(f"Argument `reduction` must be one of ('sum', 'mean', 'none', None), but got {reduction}")
