"""Spectral Distortion Index / D_lambda (reference ``functional/image/d_lambda.py``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .uqi import universal_image_quality_index
from .utils import reduce


def _spectral_distortion_index_update(preds, target):
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        raise TypeError(
            f"Expected `ms` and `fused` to have the same data type. Got ms: {preds.dtype} and fused: {target.dtype}."
        )
    if preds.ndim != 4:
        raise ValueError(
            f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape} and target: {target.shape}."
        )
    if preds.shape[:2] != target.shape[:2]:
        raise ValueError(
            "Expected `preds` and `target` to have same batch and channel sizes."
            f"Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _pairwise_band_uqi(img: jnp.ndarray) -> jnp.ndarray:
    """(C, C) matrix of mean cross-band UQI scores, computed for all upper-triangle
    pairs in one batched call (the reference loops bands with repeated concat)."""
    length = img.shape[1]
    m = jnp.zeros((length, length))
    batch = img.shape[0]
    pairs = [(k, r) for k in range(length) for r in range(k + 1, length)]
    if not pairs:
        return m
    stack1 = jnp.concatenate([img[:, k : k + 1] for k, _ in pairs], axis=0)
    stack2 = jnp.concatenate([img[:, r : r + 1] for _, r in pairs], axis=0)
    scores = universal_image_quality_index(stack1, stack2, reduction="none")
    scores = scores.reshape(len(pairs), -1).mean(axis=1)  # per-pair mean over (B, 1, H', W')
    rows = jnp.asarray([k for k, _ in pairs])
    cols = jnp.asarray([r for _, r in pairs])
    m = m.at[rows, cols].set(scores)
    return m + m.T


def _spectral_distortion_index_compute(
    preds, target, p: int = 1, reduction: Optional[str] = "elementwise_mean"
) -> jnp.ndarray:
    length = preds.shape[1]
    m1 = _pairwise_band_uqi(target)
    m2 = _pairwise_band_uqi(preds)
    diff = jnp.abs(m1 - m2) ** p
    if length == 1:
        output = diff ** (1.0 / p)
    else:
        output = (1.0 / (length * (length - 1)) * jnp.sum(diff)) ** (1.0 / p)
    return reduce(output, reduction)


def spectral_distortion_index(preds, target, p: int = 1, reduction: Optional[str] = "elementwise_mean") -> jnp.ndarray:
    """D_lambda: difference of cross-band UQI structure between fused and reference."""
    if not isinstance(p, int) or p <= 0:
        raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
    preds, target = _spectral_distortion_index_update(preds, target)
    return _spectral_distortion_index_compute(preds, target, p, reduction)
