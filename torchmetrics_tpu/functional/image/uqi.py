"""Universal Image Quality Index (reference ``functional/image/uqi.py``)."""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from .utils import _check_image_pair, _gaussian_kernel_2d, conv2d, reduce, reflect_pad_2d


def _uqi_update(preds, target):
    return _check_image_pair(preds, target)


def _uqi_compute(
    preds,
    target,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
):
    if len(kernel_size) != 2 or len(sigma) != 2:
        raise ValueError(
            "Expected `kernel_size` and `sigma` to have the length of two."
            f" Got kernel_size: {len(kernel_size)} and sigma: {len(sigma)}."
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    channel = preds.shape[1]
    dtype = preds.dtype
    kernel = _gaussian_kernel_2d(channel, kernel_size, sigma, dtype)
    pad_h = (kernel_size[0] - 1) // 2
    pad_w = (kernel_size[1] - 1) // 2
    preds = reflect_pad_2d(preds, pad_w, pad_h)
    target = reflect_pad_2d(target, pad_w, pad_h)

    batch = preds.shape[0]
    input_list = jnp.concatenate([preds, target, preds * preds, target * target, preds * target])
    outputs = conv2d(input_list, kernel, groups=channel)
    mu_pred, mu_target, pred_sq, target_sq, pred_target = (
        outputs[i * batch : (i + 1) * batch] for i in range(5)
    )
    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target
    sigma_pred_sq = jnp.clip(pred_sq - mu_pred_sq, 0.0)
    sigma_target_sq = jnp.clip(target_sq - mu_target_sq, 0.0)
    sigma_pred_target = pred_target - mu_pred_target

    upper = 2 * sigma_pred_target
    lower = sigma_pred_sq + sigma_target_sq
    eps = jnp.finfo(sigma_pred_sq.dtype).eps
    uqi_idx = ((2 * mu_pred_target) * upper) / ((mu_pred_sq + mu_target_sq) * lower + eps)
    uqi_idx = uqi_idx[..., pad_h:-pad_h, pad_w:-pad_w]
    return reduce(uqi_idx, reduction)


def universal_image_quality_index(
    preds,
    target,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
) -> jnp.ndarray:
    """Universal Image Quality Index — SSIM without the stability constants.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import universal_image_quality_index
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> universal_image_quality_index(preds, target)
        Array(0.05859915, dtype=float32)
    """
    preds, target = _uqi_update(preds, target)
    return _uqi_compute(preds, target, kernel_size, sigma, reduction)
