"""Image kernel utilities (reference ``functional/image/utils.py``).

Depthwise separable gaussian/uniform filtering expressed as
``lax.conv_general_dilated`` with ``feature_group_count`` — XLA lowers these onto the
TPU convolution units; the three padding flavors used by the reference (torch
reflect = jnp 'reflect', scipy-style symmetric = jnp 'symmetric', asymmetric
symmetric) map onto ``jnp.pad`` modes.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array


def _gaussian(kernel_size: int, sigma: float, dtype=jnp.float32) -> Array:
    """1D gaussian kernel ``(1, kernel_size)``."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-((dist / sigma) ** 2) / 2)
    return (gauss / gauss.sum())[None, :]


def _gaussian_kernel_2d(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32) -> Array:
    """Separable 2D gaussian kernel ``(channel, 1, h, w)`` (grouped-conv layout)."""
    kernel_x = _gaussian(kernel_size[0], sigma[0], dtype)
    kernel_y = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = jnp.matmul(kernel_x.T, kernel_y)  # (h, w)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _gaussian_kernel_3d(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32) -> Array:
    """Separable 3D gaussian kernel ``(channel, 1, d, h, w)``: ``kernel_size[i]`` /
    ``sigma[i]`` act on spatial axis ``i`` of NCDHW — (depth, height, width)."""
    g_d = _gaussian(kernel_size[0], sigma[0], dtype).reshape(-1)
    g_h = _gaussian(kernel_size[1], sigma[1], dtype).reshape(-1)
    g_w = _gaussian(kernel_size[2], sigma[2], dtype).reshape(-1)
    kernel = g_d[:, None, None] * g_h[None, :, None] * g_w[None, None, :]
    return jnp.broadcast_to(kernel, (channel, 1, *kernel.shape))


def conv2d(inputs: Array, kernel: Array, groups: int = 1) -> Array:
    """NCHW valid conv with OIHW kernel (grouped when groups == channels).

    ``Precision.HIGHEST`` keeps fp32 multiplies on TPU — the MXU's default bf16 path
    shifts conv-based image metrics by up to 1e-2, past the parity envelope."""
    return lax.conv_general_dilated(
        inputs,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
        precision=lax.Precision.HIGHEST,
    )


def conv3d(inputs: Array, kernel: Array, groups: int = 1) -> Array:
    """NCDHW valid conv with OIDHW kernel (fp32 multiplies — see ``conv2d``)."""
    return lax.conv_general_dilated(
        inputs,
        kernel,
        window_strides=(1, 1, 1),
        padding="VALID",
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=groups,
        precision=lax.Precision.HIGHEST,
    )


def reflect_pad_2d(inputs: Array, pad_h: int, pad_w: int) -> Array:
    """torch ``F.pad(mode='reflect')`` equivalent (no edge duplication)."""
    return jnp.pad(inputs, ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def reflect_pad_3d(inputs: Array, pad_d: int, pad_h: int, pad_w: int) -> Array:
    return jnp.pad(inputs, ((0, 0), (0, 0), (pad_d, pad_d), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _symmetric_pad_2d(inputs: Array, pad: int, outer_pad: int = 0) -> Array:
    """scipy-style symmetric padding with asymmetric tail (reference
    ``_reflection_pad_2d``: left ``pad``, right ``pad + outer_pad - 1``)."""
    right = pad + outer_pad - 1
    return jnp.pad(inputs, ((0, 0), (0, 0), (pad, right), (pad, right)), mode="symmetric")


def uniform_filter(inputs: Array, window_size: int) -> Array:
    """Uniform (box) filter with scipy-style symmetric padding — output matches the
    input's spatial shape (reference ``_uniform_filter``)."""
    padded = _symmetric_pad_2d(inputs, window_size // 2, window_size % 2)
    channel = inputs.shape[1]
    kernel = jnp.ones((channel, 1, window_size, window_size), inputs.dtype) / (window_size**2)
    return conv2d(padded, kernel, groups=channel)


def avg_pool2d(inputs: Array) -> Array:
    """2x2 stride-2 average pool (NCHW), floor mode like torch's default."""
    out = lax.reduce_window(inputs, 0.0, lax.add, (1, 1, 2, 2), (1, 1, 2, 2), "VALID")
    return out / 4.0


def avg_pool3d(inputs: Array) -> Array:
    out = lax.reduce_window(inputs, 0.0, lax.add, (1, 1, 2, 2, 2), (1, 1, 2, 2, 2), "VALID")
    return out / 8.0


def reduce(x: Array, reduction) -> Array:
    """Reference ``utilities/distributed.py:22`` semantics, plus the ``'mean'``
    alias the image metrics accept; delegates to the canonical implementation."""
    from ...utilities.compute import reduce as _reduce

    return _reduce(x, "elementwise_mean" if reduction == "mean" else reduction)


def _check_image_pair(preds, target, require_dtype_match: bool = True, ndim: Tuple[int, ...] = (4,)):
    import jax.numpy as _jnp

    preds = _jnp.asarray(preds)
    target = _jnp.asarray(target)
    if require_dtype_match and preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    if tuple(preds.shape) != tuple(target.shape):
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, "
            f"but got {tuple(preds.shape)} and {tuple(target.shape)}."
        )
    if preds.ndim not in ndim:
        raise ValueError(
            f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target
