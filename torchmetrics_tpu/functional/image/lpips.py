"""LPIPS (reference ``functional/image/lpips.py``; Zhang et al., CVPR 2018).

The backbone feature stacks (AlexNet / VGG16 / SqueezeNet-1.1 classifier trunks) are
expressed as declarative layer specs run through one jitted interpreter — adding a
backbone is a data change, not code. Weights load from a converted pickle (the
reference pulls torchvision pretrained backbones over the network, which an air-gapped
pod cannot); ``pretrained=False`` gives deterministic random parameters so the scoring
machinery stays testable offline.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# torchvision `features` layer specs: (kind, *args). Conv = (c_in, c_out, k, stride, pad)
_ALEX_SPEC = [
    ("conv", 3, 64, 11, 4, 2), ("relu",), ("maxpool", 3, 2),
    ("conv", 64, 192, 5, 1, 2), ("relu",), ("maxpool", 3, 2),
    ("conv", 192, 384, 3, 1, 1), ("relu",),
    ("conv", 384, 256, 3, 1, 1), ("relu",),
    ("conv", 256, 256, 3, 1, 1), ("relu",),
]
_ALEX_TAPS = (2, 5, 8, 10, 12)  # slice end indices -> relu1..relu5
_ALEX_CHNS = (64, 192, 384, 256, 256)

_VGG_SPEC = (
    [("conv", 3, 64, 3, 1, 1), ("relu",), ("conv", 64, 64, 3, 1, 1), ("relu",), ("maxpool", 2, 2)]
    + [("conv", 64, 128, 3, 1, 1), ("relu",), ("conv", 128, 128, 3, 1, 1), ("relu",), ("maxpool", 2, 2)]
    + [("conv", 128, 256, 3, 1, 1), ("relu",), ("conv", 256, 256, 3, 1, 1), ("relu",),
       ("conv", 256, 256, 3, 1, 1), ("relu",), ("maxpool", 2, 2)]
    + [("conv", 256, 512, 3, 1, 1), ("relu",), ("conv", 512, 512, 3, 1, 1), ("relu",),
       ("conv", 512, 512, 3, 1, 1), ("relu",), ("maxpool", 2, 2)]
    + [("conv", 512, 512, 3, 1, 1), ("relu",), ("conv", 512, 512, 3, 1, 1), ("relu",),
       ("conv", 512, 512, 3, 1, 1), ("relu",)]
)
_VGG_TAPS = (4, 9, 16, 23, 30)
_VGG_CHNS = (64, 128, 256, 512, 512)

_SQUEEZE_SPEC = (
    [("conv", 3, 64, 3, 2, 0), ("relu",), ("maxpool", 3, 2),
     ("fire", 64, 16, 64, 64), ("fire", 128, 16, 64, 64), ("maxpool", 3, 2),
     ("fire", 128, 32, 128, 128), ("fire", 256, 32, 128, 128), ("maxpool", 3, 2),
     ("fire", 256, 48, 192, 192), ("fire", 384, 48, 192, 192),
     ("fire", 384, 64, 256, 256), ("fire", 512, 64, 256, 256)]
)
_SQUEEZE_TAPS = (2, 5, 8, 10, 11, 12, 13)
_SQUEEZE_CHNS = (64, 128, 256, 384, 384, 512, 512)

_NETS = {
    "alex": (_ALEX_SPEC, _ALEX_TAPS, _ALEX_CHNS),
    "vgg": (_VGG_SPEC, _VGG_TAPS, _VGG_CHNS),
    "squeeze": (_SQUEEZE_SPEC, _SQUEEZE_TAPS, _SQUEEZE_CHNS),
}

_SHIFT = np.asarray([-0.030, -0.088, -0.188], np.float32)
_SCALE = np.asarray([0.458, 0.448, 0.450], np.float32)


def _conv(x, w, b, stride, pad):
    out = lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"), precision=lax.Precision.HIGHEST,
    )
    return out + b[None, :, None, None]


def _maxpool(x, window, stride):
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, window, window), (1, 1, stride, stride), "VALID")


def _backbone_forward(spec, params: List, taps: Sequence[int], x) -> List[jnp.ndarray]:
    feats = []
    for idx, layer in enumerate(spec):
        kind = layer[0]
        p = params[idx]
        if kind == "conv":
            _, _, _, _, stride, pad = layer
            x = _conv(x, p["w"], p["b"], stride, pad)
        elif kind == "relu":
            x = jax.nn.relu(x)
        elif kind == "maxpool":
            x = _maxpool(x, layer[1], layer[2])
        elif kind == "fire":
            s = jax.nn.relu(_conv(x, p["sq_w"], p["sq_b"], 1, 0))
            e1 = jax.nn.relu(_conv(s, p["e1_w"], p["e1_b"], 1, 0))
            e3 = jax.nn.relu(_conv(s, p["e3_w"], p["e3_b"], 1, 1))
            x = jnp.concatenate([e1, e3], axis=1)
        # spec position idx+1 == number of torchvision layers consumed
        if idx + 1 in taps:
            feats.append(x)
    return feats


def _normalize_tensor(feat, eps: float = 1e-8):
    norm_factor = jnp.sqrt(eps + jnp.sum(feat**2, axis=1, keepdims=True))
    return feat / norm_factor


class LPIPSNetwork:
    """Jitted LPIPS scorer: scaling layer -> backbone taps -> unit-normalize ->
    squared diff -> 1x1 linear heads -> spatial average -> layer sum."""

    def __init__(
        self,
        net_type: str = "alex",
        pretrained: bool = True,
        weights_path: Optional[str] = None,
        seed: int = 0,
    ) -> None:
        if net_type not in _NETS:
            raise ValueError(f"Argument `net_type` must be one of {list(_NETS)}, but got {net_type}")
        self.net_type = net_type
        self.spec, self.taps, self.chns = _NETS[net_type]
        if pretrained:
            if weights_path is None:
                raise ModuleNotFoundError(
                    "Pretrained LPIPS weights are not bundled and cannot be downloaded in an "
                    "air-gapped environment. Convert them offline with "
                    "`convert_lpips_weights` and pass `weights_path`, or use `pretrained=False` "
                    "(random backbone — machinery only)."
                )
            with open(weights_path, "rb") as f:
                payload = pickle.load(f)
            self.backbone = jax.tree.map(jnp.asarray, payload["backbone"])
            self.lins = jax.tree.map(jnp.asarray, payload["lins"])
        else:
            self.backbone, self.lins = self._random_params(jax.random.PRNGKey(seed))
        self._apply = jax.jit(self._forward)

    def _random_params(self, key):
        backbone = []
        for layer in self.spec:
            if layer[0] == "conv":
                _, c_in, c_out, k, _, _ = layer
                key, k1 = jax.random.split(key)
                backbone.append({
                    "w": jax.random.normal(k1, (c_out, c_in, k, k), jnp.float32) / np.sqrt(c_in * k * k),
                    "b": jnp.zeros(c_out),
                })
            elif layer[0] == "fire":
                _, c_in, sq, e1, e3 = layer
                key, k1, k2, k3 = jax.random.split(key, 4)
                backbone.append({
                    "sq_w": jax.random.normal(k1, (sq, c_in, 1, 1), jnp.float32) / np.sqrt(c_in),
                    "sq_b": jnp.zeros(sq),
                    "e1_w": jax.random.normal(k2, (e1, sq, 1, 1), jnp.float32) / np.sqrt(sq),
                    "e1_b": jnp.zeros(e1),
                    "e3_w": jax.random.normal(k3, (e3, sq, 3, 3), jnp.float32) / np.sqrt(sq * 9),
                    "e3_b": jnp.zeros(e3),
                })
            else:
                backbone.append({})
        lins = []
        for c in self.chns:
            key, k1 = jax.random.split(key)
            lins.append({"w": jnp.abs(jax.random.normal(k1, (1, c, 1, 1), jnp.float32)) / np.sqrt(c)})
        return backbone, lins

    def _forward(self, backbone, lins, img1, img2):
        scale = jnp.asarray(_SCALE)[None, :, None, None]
        shift = jnp.asarray(_SHIFT)[None, :, None, None]
        in0 = (img1 - shift) / scale
        in1 = (img2 - shift) / scale
        feats0 = _backbone_forward(self.spec, backbone, self.taps, in0)
        feats1 = _backbone_forward(self.spec, backbone, self.taps, in1)
        res = jnp.zeros(img1.shape[0])
        for f0, f1, lin in zip(feats0, feats1, lins):
            diff = (_normalize_tensor(f0) - _normalize_tensor(f1)) ** 2
            head = lax.conv_general_dilated(
                diff, lin["w"], (1, 1), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"),
                precision=lax.Precision.HIGHEST,
            )
            res = res + head.mean(axis=(2, 3))[:, 0]
        return res

    def __call__(self, img1, img2, normalize: bool = False) -> jnp.ndarray:
        img1 = jnp.asarray(img1, jnp.float32)
        img2 = jnp.asarray(img2, jnp.float32)
        if normalize:  # inputs in [0, 1] -> [-1, 1]
            img1 = 2 * img1 - 1
            img2 = 2 * img2 - 1
        return self._apply(self.backbone, self.lins, img1, img2)


def convert_lpips_weights(backbone_state_dict: Dict, lpips_state_dict: Dict, net_type: str, out_path: str) -> None:
    """Convert torchvision ``<net>.features`` + reference ``lpips_models/<net>.pth``
    state_dicts into the pickle this scorer loads (run offline where torch weights
    are available)."""
    spec, _, chns = _NETS[net_type]
    backbone = []
    tv_idx = 0
    for layer in spec:
        if layer[0] == "conv":
            backbone.append({
                "w": np.asarray(backbone_state_dict[f"{tv_idx}.weight"]),
                "b": np.asarray(backbone_state_dict[f"{tv_idx}.bias"]),
            })
        elif layer[0] == "fire":
            backbone.append({
                "sq_w": np.asarray(backbone_state_dict[f"{tv_idx}.squeeze.weight"]),
                "sq_b": np.asarray(backbone_state_dict[f"{tv_idx}.squeeze.bias"]),
                "e1_w": np.asarray(backbone_state_dict[f"{tv_idx}.expand1x1.weight"]),
                "e1_b": np.asarray(backbone_state_dict[f"{tv_idx}.expand1x1.bias"]),
                "e3_w": np.asarray(backbone_state_dict[f"{tv_idx}.expand3x3.weight"]),
                "e3_b": np.asarray(backbone_state_dict[f"{tv_idx}.expand3x3.bias"]),
            })
        else:
            backbone.append({})
        if layer[0] in ("conv", "relu", "maxpool", "fire"):
            tv_idx += 1
    lins = [{"w": np.asarray(lpips_state_dict[f"lin{i}.model.1.weight"])} for i in range(len(chns))]
    with open(out_path, "wb") as f:
        pickle.dump({"backbone": backbone, "lins": lins}, f)


_NET_CACHE: Dict[Tuple, "LPIPSNetwork"] = {}


def learned_perceptual_image_patch_similarity(
    img1,
    img2,
    net_type: str = "alex",
    reduction: str = "mean",
    normalize: bool = False,
    weights_path: Optional[str] = None,
    pretrained: bool = True,
) -> jnp.ndarray:
    """One-shot LPIPS between two image batches (see ``LPIPSNetwork``). The network
    (params + jitted forward) is cached per configuration — per-call construction
    would re-trace the whole backbone every batch."""
    key = (net_type, pretrained, weights_path)
    if key not in _NET_CACHE:
        _NET_CACHE[key] = LPIPSNetwork(net_type, pretrained=pretrained, weights_path=weights_path)
    net = _NET_CACHE[key]
    loss = net(img1, img2, normalize=normalize)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    raise ValueError(f"Argument `reduction` must be one of ['mean', 'sum'], but got {reduction}")
