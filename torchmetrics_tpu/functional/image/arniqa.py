"""ARNIQA — no-reference image quality (reference ``functional/image/arniqa.py``).

The full model is in-tree: a jnp ResNet-50 encoder (``image/_resnet.py``) applied
to the image and its antialias-bilinear half-scale version, L2-normalized features
concatenated and fed to a linear regressor, score rescaled to [0, 1] by the
regressor dataset's MOS range (reference ``_ARNIQA.forward``,
``functional/image/arniqa.py:131-150``). Only the *trained weights* are external:
they are loaded from the torch-hub cache layout the reference downloads into
(``~/.cache/torch/hub/checkpoints/ARNIQA.pth`` + ``regressor_<dataset>.pth``), or
passed directly via ``encoder_weights`` / ``regressor_weights``; with neither
available the call gates with a clear error. A custom ``scorer`` callable
bypasses the model entirely (the pluggable-embedder convention shared with the
other model-backed metrics).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

_REGRESSOR_DATASETS = {"kadid10k": (1.0, 5.0), "koniq10k": (1.0, 100.0)}
_IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
_IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def _hub_checkpoint(name: str) -> Optional[str]:
    base = os.path.expanduser(os.environ.get("TORCH_HOME", "~/.cache/torch"))
    path = os.path.join(base, "hub", "checkpoints", name)
    return path if os.path.exists(path) else None


_PARAM_CACHE: Dict = {}


def _load_arniqa_params(
    regressor_dataset: str,
    encoder_weights: Optional[Any],
    regressor_weights: Optional[Any],
) -> Tuple[Dict, jnp.ndarray, jnp.ndarray]:
    from ...image._resnet import convert_resnet50_state_dict

    # cache converted params for hashable sources (paths / default hub lookup):
    # without it every metric update() repeats a full checkpoint load + ResNet-50
    # conversion + device upload
    hashable = all(w is None or isinstance(w, (str, os.PathLike)) for w in (encoder_weights, regressor_weights))
    cache_key = (regressor_dataset, encoder_weights, regressor_weights) if hashable else None
    if cache_key is not None and cache_key in _PARAM_CACHE:
        return _PARAM_CACHE[cache_key]

    def _to_state_dict(source: Any, default_name: str) -> Optional[Dict]:
        if source is None:
            source = _hub_checkpoint(default_name)
            if source is None:
                return None
        if isinstance(source, (str, os.PathLike)):
            import torch

            source = torch.load(source, map_location="cpu", weights_only=False)
        if hasattr(source, "state_dict"):
            source = source.state_dict()
        return {k: np.asarray(v) for k, v in dict(source).items()}

    enc_sd = _to_state_dict(encoder_weights, "ARNIQA.pth")
    reg_sd = _to_state_dict(regressor_weights, f"regressor_{regressor_dataset}.pth")
    if enc_sd is None or reg_sd is None:
        raise ModuleNotFoundError(
            "ARNIQA's pretrained weights are not in the torch-hub cache and this "
            "environment has no network egress to download them. Fetch ARNIQA.pth and "
            f"regressor_{regressor_dataset}.pth offline into ~/.cache/torch/hub/checkpoints, "
            "pass `encoder_weights`/`regressor_weights`, or pass a custom `scorer` callable."
        )
    # published checkpoint: keys prefixed "model.", SimCLR projector dropped
    enc_sd = {k.replace("model.", ""): v for k, v in enc_sd.items() if "projector" not in k}
    params = convert_resnet50_state_dict(enc_sd)
    w = jnp.asarray(reg_sd.get("weight", reg_sd.get("weights"))).reshape(1, -1)
    b = jnp.asarray(reg_sd.get("bias", reg_sd.get("biases"))).reshape(1)
    out = (params, w, b)
    if cache_key is not None:
        _PARAM_CACHE[cache_key] = out
    return out


def _arniqa_forward(
    img: jnp.ndarray,
    params: Dict,
    w: jnp.ndarray,
    b: jnp.ndarray,
    regressor_dataset: str,
    normalize: bool,
) -> jnp.ndarray:
    from ...image._resnet import resnet50_features
    from ._resize import resize_bilinear_antialias

    h, width = img.shape[-2:]
    img_ds = resize_bilinear_antialias(img, (h // 2, width // 2))
    if normalize:
        mean = jnp.asarray(_IMAGENET_MEAN)[None, :, None, None]
        std = jnp.asarray(_IMAGENET_STD)[None, :, None, None]
        img = (img - mean) / std
        img_ds = (img_ds - mean) / std
    f_full = resnet50_features(params, img)
    f_half = resnet50_features(params, img_ds)
    f_full = f_full / jnp.clip(jnp.linalg.norm(f_full, axis=1, keepdims=True), 1e-12)
    f_half = f_half / jnp.clip(jnp.linalg.norm(f_half, axis=1, keepdims=True), 1e-12)
    feats = jnp.concatenate([f_full, f_half], axis=1)
    score = feats @ w.T + b
    lo, hi = _REGRESSOR_DATASETS[regressor_dataset]
    return ((score - lo) / (hi - lo)).reshape(-1)


def arniqa(
    img,
    regressor_dataset: str = "koniq10k",
    reduction: str = "mean",
    normalize: bool = True,
    autocast: bool = False,
    scorer: Optional[Callable] = None,
    encoder_weights: Optional[Any] = None,
    regressor_weights: Optional[Any] = None,
) -> jnp.ndarray:
    """ARNIQA quality score in [0, 1] for ``(N, 3, H, W)`` images (NCHW, [0, 1]
    when ``normalize=True``, else already imagenet-normalized).

    ``scorer`` (``imgs -> (N,)``) bypasses the in-tree model; otherwise weights
    resolve from ``encoder_weights``/``regressor_weights`` (path, state_dict or
    module) or the torch-hub cache.
    """
    if not isinstance(normalize, bool):
        raise ValueError(f"Argument `normalize` should be a bool but got {normalize}")
    if regressor_dataset not in _REGRESSOR_DATASETS:
        raise ValueError(
            f"Argument `regressor_dataset` must be one of ('kadid10k', 'koniq10k'), but got {regressor_dataset}"
        )
    if reduction not in ("mean", "sum", "none", None):
        raise ValueError(f"Argument `reduction` must be one of ('mean', 'sum', 'none', None), but got {reduction}")
    img = jnp.asarray(img)
    if img.ndim == 3:
        img = img[None]
    if scorer is not None:
        scores = jnp.asarray(scorer(img))
    else:
        params, w, b = _load_arniqa_params(regressor_dataset, encoder_weights, regressor_weights)
        scores = _arniqa_forward(img, params, w, b, regressor_dataset, normalize)
    if reduction == "mean":
        return scores.mean()
    if reduction == "sum":
        return scores.sum()
    return scores
