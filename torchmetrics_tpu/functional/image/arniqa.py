"""ARNIQA — no-reference image quality (reference ``functional/image/arniqa.py``).

ARNIQA regresses quality from a pretrained ResNet-50 encoder fine-tuned on quality
datasets; both the encoder and the regressor head are downloaded weights, which an
air-gapped environment cannot fetch. The surface gates with a clear error; a custom
scorer callable is accepted for parity with the pluggable-embedder convention used by
the other model-backed metrics.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp


def arniqa(
    img,
    regressor_dataset: str = "koniq10k",
    reduction: str = "mean",
    normalize: bool = True,
    autocast: bool = False,
    scorer: Optional[Callable] = None,
) -> jnp.ndarray:
    """ARNIQA quality score in [0, 1]. Pass ``scorer`` (``imgs -> (N,)``) to supply
    the model; the pretrained default requires downloaded weights. ``normalize`` and
    ``autocast`` belong to the gated pretrained pipeline (they control its input
    rescaling and mixed precision) and do not affect a custom ``scorer``."""
    if not isinstance(normalize, bool):
        raise ValueError(f"Argument `normalize` should be a bool but got {normalize}")
    if regressor_dataset not in ("kadid10k", "koniq10k"):
        raise ValueError(
            f"Argument `regressor_dataset` must be one of ('kadid10k', 'koniq10k'), but got {regressor_dataset}"
        )
    if reduction not in ("mean", "sum", "none", None):
        raise ValueError(f"Argument `reduction` must be one of ('mean', 'sum', 'none', None), but got {reduction}")
    if scorer is None:
        raise ModuleNotFoundError(
            "ARNIQA's pretrained ResNet-50 encoder and regressor weights cannot be downloaded in "
            "an air-gapped environment. Pass a custom `scorer` callable (imgs -> (N,) scores)."
        )
    scores = jnp.asarray(scorer(jnp.asarray(img)))
    if reduction == "mean":
        return scores.mean()
    if reduction == "sum":
        return scores.sum()
    return scores
