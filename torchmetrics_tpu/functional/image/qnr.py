"""Quality with No Reference / QNR (reference ``functional/image/qnr.py``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .d_lambda import spectral_distortion_index
from .d_s import spatial_distortion_index


def quality_with_no_reference(
    preds,
    ms,
    pan,
    pan_lr=None,
    alpha: float = 1,
    beta: float = 1,
    norm_order: int = 1,
    window_size: int = 7,
    reduction: Optional[str] = "elementwise_mean",
) -> jnp.ndarray:
    """QNR = (1 - D_lambda)^alpha * (1 - D_s)^beta."""
    if not isinstance(alpha, (int, float)) or alpha < 0:
        raise ValueError(f"Expected `alpha` to be a non-negative real number. Got alpha: {alpha}.")
    if not isinstance(beta, (int, float)) or beta < 0:
        raise ValueError(f"Expected `beta` to be a non-negative real number. Got beta: {beta}.")
    d_lambda = spectral_distortion_index(preds, ms, norm_order, reduction)
    d_s = spatial_distortion_index(preds, ms, pan, pan_lr, norm_order, window_size, reduction)
    return (1 - d_lambda) ** alpha * (1 - d_s) ** beta
