"""Spectral Angle Mapper (reference ``functional/image/sam.py``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .utils import _check_image_pair, reduce


def _sam_update(preds, target):
    preds, target = _check_image_pair(preds, target)
    if preds.shape[1] <= 1:
        raise ValueError(
            "Expected channel dimension of `preds` and `target` to be larger than 1."
            f" Got preds: {preds.shape[1]} and target: {target.shape[1]}."
        )
    return preds, target


def _sam_compute(preds, target, reduction: Optional[str] = "elementwise_mean"):
    dot_product = (preds * target).sum(axis=1)
    preds_norm = jnp.linalg.norm(preds, axis=1)
    target_norm = jnp.linalg.norm(target, axis=1)
    sam_score = jnp.arccos(jnp.clip(dot_product / (preds_norm * target_norm), -1, 1))
    return reduce(sam_score, reduction)


def spectral_angle_mapper(preds, target, reduction: Optional[str] = "elementwise_mean") -> jnp.ndarray:
    """Per-pixel spectral angle between prediction and target spectra (radians).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import spectral_angle_mapper
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> spectral_angle_mapper(preds, target)
        Array(0.65371835, dtype=float32)
    """
    preds, target = _sam_update(preds, target)
    return _sam_compute(preds, target, reduction)
