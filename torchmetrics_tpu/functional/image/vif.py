"""Visual Information Fidelity (reference ``functional/image/vif.py``).

The four-scale pyramid is unrolled at trace time; each scale is a handful of valid
convolutions with a gaussian window. The reference's in-place boolean masking becomes
``jnp.where`` selects, so the whole per-channel score is one fused XLA program.
"""

from __future__ import annotations

import jax.numpy as jnp

from .utils import conv2d


def _filter(win_size: float, sigma: float, dtype=jnp.float32) -> jnp.ndarray:
    coords = jnp.arange(win_size, dtype=dtype) - (win_size - 1) / 2
    g = coords**2
    g = jnp.exp(-(g[None, :] + g[:, None]) / (2.0 * sigma**2))
    return g / g.sum()


def _vif_per_channel(preds: jnp.ndarray, target: jnp.ndarray, sigma_n_sq: float) -> jnp.ndarray:
    dtype = preds.dtype
    preds = preds[:, None]
    target = target[:, None]
    eps = jnp.asarray(1e-10, dtype)
    sigma_n_sq = jnp.asarray(sigma_n_sq, dtype)
    preds_vif = jnp.zeros(preds.shape[0], dtype)
    target_vif = jnp.zeros(preds.shape[0], dtype)
    for scale in range(4):
        n = 2.0 ** (4 - scale) + 1
        kernel = _filter(n, n / 5, dtype=dtype)[None, None, :]
        if scale > 0:
            target = conv2d(target, kernel)[:, :, ::2, ::2]
            preds = conv2d(preds, kernel)[:, :, ::2, ::2]
        mu_target = conv2d(target, kernel)
        mu_preds = conv2d(preds, kernel)
        mu_target_sq = mu_target**2
        mu_preds_sq = mu_preds**2
        mu_target_preds = mu_target * mu_preds
        sigma_target_sq = jnp.clip(conv2d(target**2, kernel) - mu_target_sq, 0.0)
        sigma_preds_sq = jnp.clip(conv2d(preds**2, kernel) - mu_preds_sq, 0.0)
        sigma_target_preds = conv2d(target * preds, kernel) - mu_target_preds

        g = sigma_target_preds / (sigma_target_sq + eps)
        sigma_v_sq = sigma_preds_sq - g * sigma_target_preds

        mask = sigma_target_sq < eps
        g = jnp.where(mask, 0.0, g)
        sigma_v_sq = jnp.where(mask, sigma_preds_sq, sigma_v_sq)
        sigma_target_sq = jnp.where(mask, 0.0, sigma_target_sq)
        mask = sigma_preds_sq < eps
        g = jnp.where(mask, 0.0, g)
        sigma_v_sq = jnp.where(mask, 0.0, sigma_v_sq)
        mask = g < 0
        sigma_v_sq = jnp.where(mask, sigma_preds_sq, sigma_v_sq)
        g = jnp.where(mask, 0.0, g)
        sigma_v_sq = jnp.clip(sigma_v_sq, eps)

        preds_vif = preds_vif + jnp.sum(
            jnp.log10(1.0 + (g**2.0) * sigma_target_sq / (sigma_v_sq + sigma_n_sq)), axis=(1, 2, 3)
        )
        target_vif = target_vif + jnp.sum(jnp.log10(1.0 + sigma_target_sq / sigma_n_sq), axis=(1, 2, 3))
    return preds_vif / target_vif


def visual_information_fidelity(preds, target, sigma_n_sq: float = 2.0, reduction: str = "mean") -> jnp.ndarray:
    """VIF: information preserved in the distorted image vs the reference.
    Inputs must be at least 41x41 (four dyadic scales)."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if preds.shape[-2] < 41 or preds.shape[-1] < 41:
        raise ValueError(f"Invalid size of preds. Expected at least 41x41, but got {preds.shape[-2]}x{preds.shape[-1]}!")
    if target.shape[-2] < 41 or target.shape[-1] < 41:
        raise ValueError(
            f"Invalid size of target. Expected at least 41x41, but got {target.shape[-2]}x{target.shape[-1]}!"
        )
    if reduction not in ("mean", "none"):
        raise ValueError(f"Argument `reduction` must be one of ['mean', 'none'], got {reduction}")
    per_channel = [
        _vif_per_channel(preds[:, i], target[:, i], sigma_n_sq) for i in range(preds.shape[1])
    ]
    score = jnp.mean(jnp.stack(per_channel), axis=0) if len(per_channel) > 1 else per_channel[0]
    return jnp.mean(score) if reduction == "mean" else score
