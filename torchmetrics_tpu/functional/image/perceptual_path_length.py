"""Perceptual Path Length (reference ``functional/image/perceptual_path_length.py``).

PPL probes a latent-space generator: interpolate latent pairs epsilon apart, generate
both endpoints, and score the perceptual distance / epsilon^2 with quantile filtering.
The similarity network is LPIPS (converted weights required offline) or any callable
``(img1, img2) -> (N,)``; the generator is any object with ``sample(num_samples)`` and
``__call__(z[, labels])``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


class GeneratorType:
    """Protocol for PPL generators: ``sample(num_samples) -> (N, z)`` latents and a
    forward producing images scaled to [0, 255]; ``num_classes`` when conditional."""

    @property
    def num_classes(self) -> int:
        raise NotImplementedError

    def sample(self, num_samples: int):
        raise NotImplementedError


def _validate_generator_model(generator, conditional: bool = False) -> None:
    if not hasattr(generator, "sample"):
        raise NotImplementedError(
            "The generator must have a `sample` method with signature `sample(num_samples: int) -> Tensor` where the"
            " returned tensor has shape `(num_samples, z_size)`."
        )
    if not callable(generator.sample):
        raise ValueError("The generator's `sample` method must be callable.")
    if conditional and not hasattr(generator, "num_classes"):
        raise AttributeError("The generator must have a `num_classes` attribute when `conditional=True`.")
    if conditional and not isinstance(generator.num_classes, int):
        raise ValueError("The generator's `num_classes` attribute must be an integer when `conditional=True`.")


def _perceptual_path_length_validate_arguments(
    num_samples: int = 10_000,
    conditional: bool = False,
    batch_size: int = 128,
    interpolation_method: str = "lerp",
    epsilon: float = 1e-4,
    resize: Optional[int] = 64,
    lower_discard: Optional[float] = 0.01,
    upper_discard: Optional[float] = 0.99,
) -> None:
    if not (isinstance(num_samples, int) and num_samples > 0):
        raise ValueError(f"Argument `num_samples` must be a positive integer, but got {num_samples}.")
    if not isinstance(conditional, bool):
        raise ValueError(f"Argument `conditional` must be a boolean, but got {conditional}.")
    if not (isinstance(batch_size, int) and batch_size > 0):
        raise ValueError(f"Argument `batch_size` must be a positive integer, but got {batch_size}.")
    if interpolation_method not in ["lerp", "slerp_any", "slerp_unit"]:
        raise ValueError(
            f"Argument `interpolation_method` must be one of 'lerp', 'slerp_any', 'slerp_unit',"
            f"got {interpolation_method}."
        )
    if not (isinstance(epsilon, float) and epsilon > 0):
        raise ValueError(f"Argument `epsilon` must be a positive float, but got {epsilon}.")
    if resize is not None and not (isinstance(resize, int) and resize > 0):
        raise ValueError(f"Argument `resize` must be a positive integer or `None`, but got {resize}.")
    if lower_discard is not None and not (isinstance(lower_discard, float) and 0 <= lower_discard <= 1):
        raise ValueError(
            f"Argument `lower_discard` must be a float between 0 and 1 or `None`, but got {lower_discard}."
        )
    if upper_discard is not None and not (isinstance(upper_discard, float) and 0 <= upper_discard <= 1):
        raise ValueError(
            f"Argument `upper_discard` must be a float between 0 and 1 or `None`, but got {upper_discard}."
        )


def _interpolate(latents1, latents2, epsilon: float = 1e-4, interpolation_method: str = "lerp") -> jnp.ndarray:
    """Step of size epsilon along the latent path (torch-fidelity noise semantics)."""
    eps = 1e-7
    latents1 = jnp.asarray(latents1)
    latents2 = jnp.asarray(latents2)
    if latents1.shape != latents2.shape:
        raise ValueError("Latents must have the same shape.")
    if interpolation_method == "lerp":
        return latents1 + (latents2 - latents1) * epsilon
    if interpolation_method == "slerp_any":
        raw_norm1 = jnp.linalg.norm(latents1, axis=-1, keepdims=True)
        raw_norm2 = jnp.linalg.norm(latents2, axis=-1, keepdims=True)
        l1n = latents1 / jnp.clip(raw_norm1, eps)
        l2n = latents2 / jnp.clip(raw_norm2, eps)
        d = (l1n * l2n).sum(axis=-1, keepdims=True)
        # degenerate (zero-norm) or collinear pairs fall back to lerp
        mask = (raw_norm1 < eps) | (raw_norm2 < eps) | (d > 1 - eps) | (d < -1 + eps)
        omega = jnp.arccos(jnp.clip(d, -1, 1))
        denom = jnp.clip(jnp.sin(omega), eps)
        out = (jnp.sin((1 - epsilon) * omega) / denom) * latents1 + (jnp.sin(epsilon * omega) / denom) * latents2
        lerped = _interpolate(latents1, latents2, epsilon, "lerp")
        return jnp.where(mask, lerped, out)
    if interpolation_method == "slerp_unit":
        out = _interpolate(latents1, latents2, epsilon, "slerp_any")
        return out / jnp.clip(jnp.linalg.norm(out, axis=-1, keepdims=True), eps)
    raise ValueError(
        f"Interpolation method {interpolation_method} not supported. Choose from 'lerp', 'slerp_any', 'slerp_unit'."
    )


def perceptual_path_length(
    generator,
    num_samples: int = 10_000,
    conditional: bool = False,
    batch_size: int = 64,
    interpolation_method: str = "lerp",
    epsilon: float = 1e-4,
    resize: Optional[int] = 64,
    lower_discard: Optional[float] = 0.01,
    upper_discard: Optional[float] = 0.99,
    sim_net: Union[Callable, str] = "vgg",
    sim_net_weights_path: Optional[str] = None,
    seed: int = 0,
    device: Optional[Any] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    r"""PPL = E[D(G(I(z1,z2,t)), G(I(z1,z2,t+eps))) / eps^2] with quantile filtering.

    ``sim_net`` is a net-type string (LPIPS — converted weights required offline via
    ``sim_net_weights_path``) or any callable ``(img1, img2) -> (N,)`` over images in
    [-1, 1].
    """
    _perceptual_path_length_validate_arguments(
        num_samples, conditional, batch_size, interpolation_method, epsilon, resize, lower_discard, upper_discard
    )
    _validate_generator_model(generator, conditional)

    if callable(sim_net) and not isinstance(sim_net, str):
        net = sim_net
    elif sim_net in ("alex", "vgg", "squeeze"):
        from .lpips import LPIPSNetwork

        if sim_net_weights_path is None:
            raise ModuleNotFoundError(
                "PPL's default LPIPS similarity needs converted pretrained weights, which cannot "
                "be downloaded in an air-gapped environment. Convert them offline with "
                "`convert_lpips_weights` and pass `sim_net_weights_path`, or pass a custom "
                "similarity callable as `sim_net`."
            )
        net = LPIPSNetwork(sim_net, pretrained=True, weights_path=sim_net_weights_path)
    else:
        raise ValueError(f"sim_net must be a callable or one of 'alex', 'vgg', 'squeeze', got {sim_net}")

    latent1 = jnp.asarray(generator.sample(num_samples))
    latent2 = jnp.asarray(generator.sample(num_samples))
    latent2 = _interpolate(latent1, latent2, epsilon, interpolation_method=interpolation_method)
    if conditional:
        labels = jnp.asarray(np.random.default_rng(seed).integers(0, generator.num_classes, num_samples))

    distances = []
    num_batches = math.ceil(num_samples / batch_size)
    for batch_idx in range(num_batches):
        sl = slice(batch_idx * batch_size, (batch_idx + 1) * batch_size)
        z = jnp.concatenate([latent1[sl], latent2[sl]], axis=0)
        if conditional:
            lab = jnp.concatenate([labels[sl], labels[sl]], axis=0)
            outputs = jnp.asarray(generator(z, lab))
        else:
            outputs = jnp.asarray(generator(z))
        out1, out2 = jnp.split(outputs, 2, axis=0)
        # generator domain [0, 255] -> similarity domain [-1, 1]
        out1 = 2 * (out1 / 255) - 1
        out2 = 2 * (out2 / 255) - 1
        if resize is not None:
            out1 = jax.image.resize(out1, (*out1.shape[:2], resize, resize), method="bilinear")
            out2 = jax.image.resize(out2, (*out2.shape[:2], resize, resize), method="bilinear")
        distances.append(jnp.asarray(net(out1, out2)) / epsilon**2)
    dist_arr = jnp.concatenate(distances)
    mean, std = _quantile_filtered_stats(dist_arr, lower_discard, upper_discard)
    return mean, std, dist_arr


def _quantile_filtered_stats(dist, lower_discard: Optional[float], upper_discard: Optional[float]):
    """Mean and (unbiased, torch-parity) std of the quantile-filtered distances."""
    lower = jnp.quantile(dist, lower_discard) if lower_discard is not None else dist.min()
    upper = jnp.quantile(dist, upper_discard) if upper_discard is not None else dist.max()
    kept = dist[jnp.asarray((np.asarray(dist) >= np.asarray(lower)) & (np.asarray(dist) <= np.asarray(upper)))]
    return kept.mean(), kept.std(ddof=1)
