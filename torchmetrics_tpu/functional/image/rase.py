"""Relative Average Spectral Error (reference ``functional/image/rase.py``)."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from .rmse_sw import _rmse_sw_compute, _rmse_sw_update
from .utils import uniform_filter


def _rase_update(
    preds, target, window_size: int, rmse_map: jnp.ndarray, target_sum: jnp.ndarray, total_images: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    _, rmse_map, total_images = _rmse_sw_update(
        preds, target, window_size, rmse_val_sum=None, rmse_map=rmse_map, total_images=total_images
    )
    target_sum = target_sum + jnp.sum(uniform_filter(target, window_size) / (window_size**2), axis=0)
    return rmse_map, target_sum, total_images


def _rase_compute(rmse_map: jnp.ndarray, target_sum: jnp.ndarray, total_images: jnp.ndarray, window_size: int):
    _, rmse_map = _rmse_sw_compute(rmse_val_sum=None, rmse_map=rmse_map, total_images=total_images)
    target_mean = target_sum / total_images
    target_mean = target_mean.mean(0)  # mean over image channels
    rase_map = 100 / target_mean * jnp.sqrt(jnp.mean(rmse_map**2, axis=0))
    crop_slide = round(window_size / 2)
    return jnp.mean(rase_map[crop_slide:-crop_slide, crop_slide:-crop_slide])


def relative_average_spectral_error(preds, target, window_size: int = 8) -> jnp.ndarray:
    """RASE: percentage RMSE relative to the local target mean.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import relative_average_spectral_error
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> relative_average_spectral_error(preds, target)
        Array(5315.8853, dtype=float32)
    """
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    img_shape = target.shape[1:]
    rmse_map = jnp.zeros(img_shape, target.dtype)
    target_sum = jnp.zeros(img_shape, target.dtype)
    total_images = jnp.asarray(0.0)
    rmse_map, target_sum, total_images = _rase_update(preds, target, window_size, rmse_map, target_sum, total_images)
    return _rase_compute(rmse_map, target_sum, total_images, window_size)
