"""Image tower — stateless kernels (reference ``src/torchmetrics/functional/image/``)."""

from .arniqa import arniqa
from .d_lambda import spectral_distortion_index
from .dists import deep_image_structure_and_texture_similarity
from .d_s import spatial_distortion_index
from .ergas import error_relative_global_dimensionless_synthesis
from .gradients import image_gradients
from .lpips import learned_perceptual_image_patch_similarity
from .perceptual_path_length import perceptual_path_length
from .psnr import peak_signal_noise_ratio
from .psnrb import peak_signal_noise_ratio_with_blocked_effect
from .qnr import quality_with_no_reference
from .rase import relative_average_spectral_error
from .rmse_sw import root_mean_squared_error_using_sliding_window
from .sam import spectral_angle_mapper
from .scc import spatial_correlation_coefficient
from .ssim import multiscale_structural_similarity_index_measure, structural_similarity_index_measure
from .tv import total_variation
from .uqi import universal_image_quality_index
from .vif import visual_information_fidelity

__all__ = [
    "arniqa",
    "deep_image_structure_and_texture_similarity",
    "error_relative_global_dimensionless_synthesis",
    "image_gradients",
    "learned_perceptual_image_patch_similarity",
    "multiscale_structural_similarity_index_measure",
    "peak_signal_noise_ratio",
    "perceptual_path_length",
    "peak_signal_noise_ratio_with_blocked_effect",
    "quality_with_no_reference",
    "relative_average_spectral_error",
    "root_mean_squared_error_using_sliding_window",
    "spatial_correlation_coefficient",
    "spatial_distortion_index",
    "spectral_angle_mapper",
    "spectral_distortion_index",
    "structural_similarity_index_measure",
    "total_variation",
    "universal_image_quality_index",
    "visual_information_fidelity",
]
