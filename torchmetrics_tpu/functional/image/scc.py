"""Spatial Correlation Coefficient (reference ``functional/image/scc.py``)."""

from __future__ import annotations

import math
from typing import Optional, Tuple, Union

import jax.numpy as jnp

from .utils import conv2d, reduce


def _scc_update(preds, target, hp_filter, window_size: int):
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        target = target.astype(preds.dtype)
    if tuple(preds.shape) != tuple(target.shape):
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, "
            f"but got {tuple(preds.shape)} and {tuple(target.shape)}."
        )
    if preds.ndim not in (3, 4):
        raise ValueError(
            "Expected `preds` and `target` to have batch of colored images with BxCxHxW shape"
            "  or batch of grayscale images of BxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    if preds.ndim == 3:
        preds = preds[:, None]
        target = target[:, None]
    if not window_size > 0:
        raise ValueError(f"Expected `window_size` to be a positive integer. Got {window_size}.")
    if window_size > preds.shape[2] or window_size > preds.shape[3]:
        raise ValueError(
            f"Expected `window_size` to be less than or equal to the size of the image."
            f" Got window_size: {window_size} and image size: {preds.shape[2]}x{preds.shape[3]}."
        )
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    hp_filter = jnp.asarray(hp_filter, preds.dtype)[None, None, :]
    return preds, target, hp_filter


def _symmetric_reflect_pad_2d(img, pad: Union[int, Tuple[int, ...]]):
    if isinstance(pad, int):
        pad = (pad, pad, pad, pad)
    if len(pad) != 4:
        raise ValueError(f"Expected padding to have length 4, but got {len(pad)}")
    return jnp.pad(img, ((0, 0), (0, 0), (pad[2], pad[3]), (pad[0], pad[1])), mode="symmetric")


def _signal_convolve_2d(img, kernel):
    """scipy.signal-style 2D convolution: symmetric pad + flipped kernel."""
    left = math.floor((kernel.shape[3] - 1) / 2)
    right = math.ceil((kernel.shape[3] - 1) / 2)
    top = math.floor((kernel.shape[2] - 1) / 2)
    bottom = math.ceil((kernel.shape[2] - 1) / 2)
    padded = _symmetric_reflect_pad_2d(img, pad=(left, right, top, bottom))
    kernel = kernel[:, :, ::-1, ::-1]
    return conv2d(padded, kernel)


def _hp_2d_laplacian(img, kernel):
    return _signal_convolve_2d(img, kernel) * 2.0


def _local_variance_covariance(preds, target, window):
    left = math.ceil((window.shape[3] - 1) / 2)
    right = math.floor((window.shape[3] - 1) / 2)
    preds = jnp.pad(preds, ((0, 0), (0, 0), (left, right), (left, right)))
    target = jnp.pad(target, ((0, 0), (0, 0), (left, right), (left, right)))
    preds_mean = conv2d(preds, window)
    target_mean = conv2d(target, window)
    preds_var = conv2d(preds**2, window) - preds_mean**2
    target_var = conv2d(target**2, window) - target_mean**2
    target_preds_cov = conv2d(target * preds, window) - target_mean * preds_mean
    return preds_var, target_var, target_preds_cov


def _scc_per_channel_compute(preds, target, hp_filter, window_size: int):
    dtype = preds.dtype
    window = jnp.ones((1, 1, window_size, window_size), dtype) / (window_size**2)
    preds_hp = _hp_2d_laplacian(preds, hp_filter)
    target_hp = _hp_2d_laplacian(target, hp_filter)
    preds_var, target_var, target_preds_cov = _local_variance_covariance(preds_hp, target_hp, window)
    preds_var = jnp.clip(preds_var, 0)
    target_var = jnp.clip(target_var, 0)
    den = jnp.sqrt(target_var) * jnp.sqrt(preds_var)
    return jnp.where(den == 0, 0.0, target_preds_cov / jnp.where(den == 0, 1.0, den))


def spatial_correlation_coefficient(
    preds,
    target,
    hp_filter: Optional[jnp.ndarray] = None,
    window_size: int = 8,
    reduction: Optional[str] = "mean",
) -> jnp.ndarray:
    """SCC: local correlation of high-pass-filtered images (sewar semantics).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import spatial_correlation_coefficient
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> spatial_correlation_coefficient(preds, target)
        Array(-0.03273273, dtype=float32)
    """
    if hp_filter is None:
        hp_filter = jnp.asarray([[-1, -1, -1], [-1, 8, -1], [-1, -1, -1]])
    if reduction is None:
        reduction = "none"
    if reduction not in ("mean", "none"):
        raise ValueError(f"Expected reduction to be 'mean' or 'none', but got {reduction}")
    preds, target, hp_filter = _scc_update(preds, target, hp_filter, window_size)
    per_channel = [
        _scc_per_channel_compute(preds[:, i : i + 1], target[:, i : i + 1], hp_filter, window_size)
        for i in range(preds.shape[1])
    ]
    scc = jnp.concatenate(per_channel, axis=1)
    if reduction == "none":
        return scc.mean(axis=(1, 2, 3))
    return scc.mean()
