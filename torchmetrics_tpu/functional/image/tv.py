"""Total variation (reference ``functional/image/tv.py``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def _total_variation_update(img) -> Tuple[jnp.ndarray, int]:
    img = jnp.asarray(img)
    if img.ndim != 4:
        raise RuntimeError(f"Expected input `img` to be an 4D tensor, but got {img.shape}")
    diff1 = img[..., 1:, :] - img[..., :-1, :]
    diff2 = img[..., :, 1:] - img[..., :, :-1]
    res1 = jnp.abs(diff1).sum(axis=(1, 2, 3))
    res2 = jnp.abs(diff2).sum(axis=(1, 2, 3))
    return res1 + res2, img.shape[0]


def _total_variation_compute(score, num_elements, reduction: Optional[str]):
    if reduction == "mean":
        return score.sum() / num_elements
    if reduction == "sum":
        return score.sum()
    if reduction is None or reduction == "none":
        return score
    raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")


def total_variation(img, reduction: Optional[str] = "sum") -> jnp.ndarray:
    """Anisotropic total variation of an NCHW image batch.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import total_variation
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> total_variation(preds)
        Array(471.78348, dtype=float32)
    """
    score, num_elements = _total_variation_update(img)
    return _total_variation_compute(score, num_elements, reduction)
