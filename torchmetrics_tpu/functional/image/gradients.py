"""Image gradients (reference ``functional/image/gradients.py``)."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def image_gradients(img) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-step finite-difference (dy, dx), zero-padded at the far edge (TF semantics).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import image_gradients
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> [g.shape for g in image_gradients(preds)]
        [(1, 3, 16, 16), (1, 3, 16, 16)]
    """
    if not hasattr(img, "shape"):
        raise TypeError(f"The `img` expects a value of <Tensor> type but got {type(img)}")
    img = jnp.asarray(img)
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")
    dy = img[..., 1:, :] - img[..., :-1, :]
    dx = img[..., :, 1:] - img[..., :, :-1]
    dy = jnp.pad(dy, ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(dx, ((0, 0), (0, 0), (0, 0), (0, 1)))
    return dy, dx
