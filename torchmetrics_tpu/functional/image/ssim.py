"""SSIM + Multi-Scale SSIM (reference ``functional/image/ssim.py``).

One grouped convolution over the stacked ``(5*B, C, ...)`` moment batch computes all
five local moments in a single XLA conv — same trick as the reference, but the
gaussian window, padding, elementwise SSIM map, and the MS-SSIM scale pyramid all
fuse into one jitted program (no per-scale Python dispatch cost at runtime beyond
trace time).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax.numpy as jnp

from .utils import (
    _check_image_pair,
    _gaussian_kernel_2d,
    _gaussian_kernel_3d,
    avg_pool2d,
    avg_pool3d,
    conv2d,
    conv3d,
    reduce,
    reflect_pad_2d,
    reflect_pad_3d,
)


def _ssim_check_inputs(preds, target):
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        target = target.astype(preds.dtype)
    if tuple(preds.shape) != tuple(target.shape):
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, "
            f"but got {tuple(preds.shape)} and {tuple(target.shape)}."
        )
    if preds.ndim not in (4, 5):
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW or BxCxDxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _ssim_update(
    preds,
    target,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
):
    is_3d = preds.ndim == 5
    if not isinstance(kernel_size, Sequence):
        kernel_size = 3 * [kernel_size] if is_3d else 2 * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = 3 * [sigma] if is_3d else 2 * [sigma]
    if len(kernel_size) != preds.ndim - 2 or len(kernel_size) not in (2, 3):
        raise ValueError(
            f"`kernel_size` has dimension {len(kernel_size)}, but expected to be two less that target dimensionality,"
            f" which is: {preds.ndim}"
        )
    if len(sigma) != preds.ndim - 2:
        raise ValueError(
            f"`sigma` has dimension {len(sigma)}, but expected to be two less that target dimensionality,"
            f" which is: {preds.ndim}"
        )
    if return_full_image and return_contrast_sensitivity:
        raise ValueError("Arguments `return_full_image` and `return_contrast_sensitivity` are mutually exclusive.")
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    if data_range is None:
        data_range = jnp.maximum(preds.max() - preds.min(), target.max() - target.min())
    elif isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range = data_range[1] - data_range[0]

    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2
    channel = preds.shape[1]
    dtype = preds.dtype
    gauss_kernel_size = [int(3.5 * s + 0.5) * 2 + 1 for s in sigma]

    # kernel_size[i] / sigma[i] act on spatial axis i: (H, W) for NCHW inputs,
    # (D, H, W) for NCDHW — pads, kernel dims and crops all share this mapping
    eff_kernel = gauss_kernel_size if gaussian_kernel else kernel_size
    pad_h = (eff_kernel[0] - 1) // 2
    pad_w = (eff_kernel[1] - 1) // 2

    if is_3d:
        pad_d, pad_h, pad_w = pad_h, pad_w, (eff_kernel[2] - 1) // 2
        preds = reflect_pad_3d(preds, pad_d, pad_h, pad_w)
        target = reflect_pad_3d(target, pad_d, pad_h, pad_w)
        kernel = (
            _gaussian_kernel_3d(channel, gauss_kernel_size, sigma, dtype)
            if gaussian_kernel
            else jnp.ones((channel, 1, *kernel_size), dtype) / jnp.prod(jnp.asarray(kernel_size, dtype))
        )
        conv = conv3d
    else:
        preds = reflect_pad_2d(preds, pad_h, pad_w)
        target = reflect_pad_2d(target, pad_h, pad_w)
        kernel = (
            _gaussian_kernel_2d(channel, gauss_kernel_size, sigma, dtype)
            if gaussian_kernel
            else jnp.ones((channel, 1, *kernel_size), dtype) / jnp.prod(jnp.asarray(kernel_size, dtype))
        )
        conv = conv2d

    batch = preds.shape[0]
    input_list = jnp.concatenate([preds, target, preds * preds, target * target, preds * target])
    outputs = conv(input_list, kernel.astype(dtype), groups=channel)
    mu_pred, mu_target, pred_sq, target_sq, pred_target = (
        outputs[i * batch : (i + 1) * batch] for i in range(5)
    )

    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target
    sigma_pred_sq = jnp.clip(pred_sq - mu_pred_sq, 0.0)
    sigma_target_sq = jnp.clip(target_sq - mu_target_sq, 0.0)
    sigma_pred_target = pred_target - mu_pred_target

    upper = 2 * sigma_pred_target.astype(dtype) + c2
    lower = (sigma_pred_sq + sigma_target_sq).astype(dtype) + c2
    ssim_full = ((2 * mu_pred_target + c1) * upper) / ((mu_pred_sq + mu_target_sq + c1) * lower)
    sim = ssim_full.reshape(batch, -1).mean(-1)

    if return_contrast_sensitivity:
        contrast = upper / lower
        # the contrast term is cropped back to the unpadded region (reference
        # ssim.py:176-181); the padded border would bias the MS-SSIM pyramid
        if is_3d:
            # NCDHW: axes are (depth, height, width) — crop in the same order the
            # padding was applied (anisotropic kernels would otherwise crop wrong axes)
            contrast = contrast[..., pad_d:-pad_d, pad_h:-pad_h, pad_w:-pad_w]
        else:
            contrast = contrast[..., pad_h:-pad_h, pad_w:-pad_w]
        return sim, contrast.reshape(batch, -1).mean(-1)
    if return_full_image:
        return sim, ssim_full
    return sim


def _ssim_compute(similarities, reduction: Optional[str] = "elementwise_mean"):
    return reduce(similarities, reduction)


def structural_similarity_index_measure(
    preds,
    target,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
):
    """Compute SSIM over NCHW (or NCDHW) image batches.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import structural_similarity_index_measure
        >>> preds = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 37 % 97) / 97
        >>> target = (jnp.arange(768, dtype=jnp.float32).reshape(1, 3, 16, 16) * 31 % 89) / 89
        >>> structural_similarity_index_measure(preds, target, data_range=1.0)
        Array(-0.0257605, dtype=float32)
    """
    preds, target = _ssim_check_inputs(preds, target)
    pack = _ssim_update(
        preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2,
        return_full_image, return_contrast_sensitivity,
    )
    if isinstance(pack, tuple):
        similarity, image = pack
        return _ssim_compute(similarity, reduction), image
    return _ssim_compute(pack, reduction)


def _multiscale_ssim_update(
    preds,
    target,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
):
    is_3d = preds.ndim == 5
    if not isinstance(kernel_size, Sequence):
        kernel_size = 3 * [kernel_size] if is_3d else 2 * [kernel_size]
    if preds.shape[-1] < 2 ** len(betas) or preds.shape[-2] < 2 ** len(betas):
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)}, the image height and width dimensions must be"
            f" larger than or equal to {2 ** len(betas)}."
        )
    _betas_div = max(1, (len(betas) - 1)) ** 2
    if preds.shape[-2] // _betas_div <= kernel_size[0] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[0]},"
            f" the image height must be larger than {(kernel_size[0] - 1) * _betas_div}."
        )
    if preds.shape[-1] // _betas_div <= kernel_size[1] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[1]},"
            f" the image width must be larger than {(kernel_size[1] - 1) * _betas_div}."
        )

    mcs_list = []
    sim = None
    for _ in range(len(betas)):
        sim, contrast = _ssim_update(
            preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2,
            return_contrast_sensitivity=True,
        )
        if normalize == "relu":
            sim = jnp.maximum(sim, 0.0)
            contrast = jnp.maximum(contrast, 0.0)
        mcs_list.append(contrast)
        if len(kernel_size) == 2:
            preds = avg_pool2d(preds)
            target = avg_pool2d(target)
        else:
            preds = avg_pool3d(preds)
            target = avg_pool3d(target)
    mcs_list[-1] = sim
    mcs_stack = jnp.stack(mcs_list)
    if normalize == "simple":
        mcs_stack = (mcs_stack + 1) / 2
    betas_arr = jnp.asarray(betas).reshape(-1, 1)
    return jnp.prod(mcs_stack**betas_arr, axis=0)


def multiscale_structural_similarity_index_measure(
    preds,
    target,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = "relu",
):
    """Compute Multi-Scale SSIM (Wang et al. scale pyramid with contrast terms)."""
    if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
        raise ValueError("Argument `betas` is expected to be of a tuple of floats")
    if normalize and normalize not in ("relu", "simple"):
        raise ValueError("Argument `normalize` to be expected either `None`, `relu` or `simple`")
    preds, target = _ssim_check_inputs(preds, target)
    mcs = _multiscale_ssim_update(
        preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, betas, normalize
    )
    return reduce(mcs, reduction)
