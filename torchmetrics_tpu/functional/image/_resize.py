"""Interpolation-parity resize kernels for feature-extractor metrics.

The reference extractor (``image/fid.py:88-101``) resizes inputs with one of two
forks before the Inception trunk:

- ``antialias=True`` (its default): torch ``F.interpolate(mode="bilinear",
  align_corners=False, antialias=True)`` — the PIL-style triangle filter whose
  support widens by the downscale ratio.
- ``antialias=False``: torch-fidelity's TF1-compatible bilinear
  (``half_pixel_centers=False``: ``src = out_idx * in/out``, two taps, clamped),
  matching the original TF-1 FID implementation.

FID is only comparable across implementations when this resize matches (SURVEY §7
names it a hard part), so both forks are reproduced here. TPU-first design: since
both filters are separable and the sizes are static under ``jit``, each becomes two
dense matmuls with host-precomputed 1-D weight matrices — no gathers, straight onto
the MXU — rather than a port of the reference's per-pixel gather kernels.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["resize_bilinear_antialias", "resize_bilinear_tf1"]


@lru_cache(maxsize=64)
def _antialias_weights_1d(in_size: int, out_size: int) -> np.ndarray:
    """(out, in) row-normalized triangle-filter weights, PIL/torch-aa semantics."""
    scale = in_size / out_size
    support = max(scale, 1.0)  # filter widens only when downscaling
    centers = (np.arange(out_size) + 0.5) * scale  # continuous source coordinate + 0.5
    lo = np.maximum((centers - support + 0.5).astype(np.int64), 0)
    hi = np.minimum((centers + support + 0.5).astype(np.int64), in_size)
    w = np.zeros((out_size, in_size), np.float64)
    for i in range(out_size):
        taps = np.arange(lo[i], hi[i])
        dist = (taps + 0.5 - centers[i]) / support
        vals = np.maximum(0.0, 1.0 - np.abs(dist))
        total = vals.sum()
        if total > 0:
            w[i, taps] = vals / total
    return w.astype(np.float32)


@lru_cache(maxsize=64)
def _tf1_weights_1d(in_size: int, out_size: int) -> np.ndarray:
    """(out, in) two-tap bilinear weights with TF1 legacy coordinates (no half-pixel
    offset): ``src = i * in/out``, clamped to the last source row."""
    scale = in_size / out_size if out_size > 1 else 0.0
    src = np.arange(out_size) * scale
    lo = np.floor(src).astype(np.int64)
    lo = np.minimum(lo, in_size - 1)
    hi = np.minimum(lo + 1, in_size - 1)
    frac = (src - lo).astype(np.float64)
    w = np.zeros((out_size, in_size), np.float64)
    w[np.arange(out_size), lo] += 1.0 - frac
    w[np.arange(out_size), hi] += frac
    return w.astype(np.float32)


def _separable_resize(imgs, size: Tuple[int, int], weights_fn) -> jnp.ndarray:
    """Apply (out_h, in_h) and (out_w, in_w) weight matrices over the last two axes."""
    out_h, out_w = size
    in_h, in_w = imgs.shape[-2:]
    wh = jnp.asarray(weights_fn(in_h, out_h))
    ww = jnp.asarray(weights_fn(in_w, out_w))
    out = jnp.einsum("...hw,Hh->...Hw", imgs, wh, precision="highest")
    return jnp.einsum("...Hw,Ww->...HW", out, ww, precision="highest")


def resize_bilinear_antialias(imgs, size: Tuple[int, int]) -> jnp.ndarray:
    """Antialiased bilinear resize over the trailing (H, W) axes, matching torch
    ``F.interpolate(mode="bilinear", align_corners=False, antialias=True)``."""
    return _separable_resize(jnp.asarray(imgs), size, _antialias_weights_1d)


def resize_bilinear_tf1(imgs, size: Tuple[int, int]) -> jnp.ndarray:
    """TF1-compatible bilinear resize over the trailing (H, W) axes (legacy TF
    coordinates, ``half_pixel_centers=False``), matching torch-fidelity's
    ``interpolate_bilinear_2d_like_tensorflow1x``."""
    return _separable_resize(jnp.asarray(imgs), size, _tf1_weights_1d)
