"""Spatial Distortion Index / D_s (reference ``functional/image/d_s.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .uqi import universal_image_quality_index
from .utils import reduce, uniform_filter


def _spatial_distortion_index_update(preds, ms, pan, pan_lr=None):
    preds = jnp.asarray(preds)
    ms = jnp.asarray(ms)
    pan = jnp.asarray(pan)
    pan_lr = jnp.asarray(pan_lr) if pan_lr is not None else None
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` to have BxCxHxW shape. Got preds: {preds.shape}.")
    for name, other in (("ms", ms), ("pan", pan)) + ((("pan_lr", pan_lr),) if pan_lr is not None else ()):
        if preds.dtype != other.dtype:
            raise TypeError(
                f"Expected `preds` and `{name}` to have the same data type."
                f" Got preds: {preds.dtype} and {name}: {other.dtype}."
            )
        if other.ndim != 4:
            raise ValueError(f"Expected `{name}` to have BxCxHxW shape. Got {name}: {other.shape}.")
        if preds.shape[:2] != other.shape[:2]:
            raise ValueError(
                f"Expected `preds` and `{name}` to have the same batch and channel sizes."
                f" Got preds: {preds.shape} and {name}: {other.shape}."
            )
    pan_h, pan_w = pan.shape[-2:]
    ms_h, ms_w = ms.shape[-2:]
    if preds.shape[-2:] != pan.shape[-2:]:
        raise ValueError(
            f"Expected `preds` and `pan` to have the same dimension. Got preds: {preds.shape} and pan: {pan.shape}."
        )
    if pan_h % ms_h != 0:
        raise ValueError(
            f"Expected height of `pan` to be multiple of height of `ms`. Got preds: {pan_h} and ms: {ms_h}."
        )
    if pan_w % ms_w != 0:
        raise ValueError(f"Expected width of `pan` to be multiple of width of `ms`. Got preds: {pan_w} and ms: {ms_w}.")
    if pan_lr is not None and pan_lr.shape[-2:] != (ms_h, ms_w):
        raise ValueError(
            f"Expected `ms` and `pan_lr` to have the same height and width."
            f" Got ms: {ms.shape} and pan_lr: {pan_lr.shape}."
        )
    return preds, ms, pan, pan_lr


def _spatial_distortion_index_compute(
    preds, ms, pan, pan_lr=None, norm_order: int = 1, window_size: int = 7,
    reduction: Optional[str] = "elementwise_mean",
) -> jnp.ndarray:
    length = preds.shape[1]
    ms_h, ms_w = ms.shape[-2:]
    if window_size >= ms_h or window_size >= ms_w:
        raise ValueError(
            f"Expected `window_size` to be smaller than dimension of `ms`. Got window_size: {window_size}."
        )
    if pan_lr is None:
        pan_degraded = uniform_filter(pan, window_size=window_size)
        pan_degraded = jax.image.resize(
            pan_degraded, (*pan_degraded.shape[:2], ms_h, ms_w), method="bilinear"
        )
    else:
        pan_degraded = pan_lr
    m1 = jnp.stack([
        universal_image_quality_index(ms[:, i : i + 1], pan_degraded[:, i : i + 1]) for i in range(length)
    ])
    m2 = jnp.stack([
        universal_image_quality_index(preds[:, i : i + 1], pan[:, i : i + 1]) for i in range(length)
    ])
    diff = jnp.abs(m1 - m2) ** norm_order
    return reduce(diff, reduction) ** (1 / norm_order)


def spatial_distortion_index(
    preds, ms, pan, pan_lr=None, norm_order: int = 1, window_size: int = 7,
    reduction: Optional[str] = "elementwise_mean",
) -> jnp.ndarray:
    """D_s: spatial distortion of a pan-sharpened image vs its panchromatic source."""
    if not isinstance(norm_order, int) or norm_order <= 0:
        raise ValueError(f"Expected `norm_order` to be a positive integer. Got norm_order: {norm_order}.")
    if not isinstance(window_size, int) or window_size <= 0:
        raise ValueError(f"Expected `window_size` to be a positive integer. Got window_size: {window_size}.")
    preds, ms, pan, pan_lr = _spatial_distortion_index_update(preds, ms, pan, pan_lr)
    return _spatial_distortion_index_compute(preds, ms, pan, pan_lr, norm_order, window_size, reduction)
