"""Functional retrieval metrics (reference ``functional/retrieval/``).

Every public function scores ONE query (1-D preds/target), mirroring the reference
API; all of them are thin wrappers over the vectorized padded kernels in
``_kernels.py`` (one row = one query).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ._kernels import (
    _ap_kernel,
    _auroc_kernel,
    _fall_out_kernel,
    _hit_rate_kernel,
    _ndcg_kernel,
    _precision_kernel,
    _r_precision_kernel,
    _recall_kernel,
    _rr_kernel,
)
from .utils import _check_retrieval_functional_inputs

Array = jax.Array


def _validate_top_k(top_k: Optional[int]) -> None:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")


def _as_row(preds, target, allow_non_binary_target=False):
    p, t = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target)
    return p[None, :], t[None, :], jnp.ones((1, p.shape[0]), bool)


def retrieval_average_precision(preds, target, top_k: Optional[int] = None) -> Array:
    """AP of one query (reference functional/retrieval/average_precision.py:16).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import retrieval_average_precision
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1])
        >>> target = jnp.asarray([False, True, True, False])
        >>> retrieval_average_precision(preds, target)
        Array(1., dtype=float32)
    """
    _validate_top_k(top_k)
    p, t, m = _as_row(preds, target)
    return _ap_kernel(p, t, m, top_k)[0]


def retrieval_reciprocal_rank(preds, target, top_k: Optional[int] = None) -> Array:
    """RR of one query (reference functional/retrieval/reciprocal_rank.py:16).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import retrieval_reciprocal_rank
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1])
        >>> target = jnp.asarray([False, True, True, False])
        >>> retrieval_reciprocal_rank(preds, target)
        Array(1., dtype=float32, weak_type=True)
    """
    _validate_top_k(top_k)
    p, t, m = _as_row(preds, target)
    return _rr_kernel(p, t, m, top_k)[0]


def retrieval_precision(preds, target, top_k: Optional[int] = None, adaptive_k: bool = False) -> Array:
    """Precision@k of one query (reference functional/retrieval/precision.py:20).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import retrieval_precision
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1])
        >>> target = jnp.asarray([False, True, True, False])
        >>> retrieval_precision(preds, target, top_k=2)
        Array(1., dtype=float32)
    """
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    _validate_top_k(top_k)
    p, t, m = _as_row(preds, target)
    return _precision_kernel(p, t, m, top_k, adaptive_k)[0]


def retrieval_recall(preds, target, top_k: Optional[int] = None) -> Array:
    """Recall@k of one query (reference functional/retrieval/recall.py:20).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import retrieval_recall
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1])
        >>> target = jnp.asarray([False, True, True, False])
        >>> retrieval_recall(preds, target, top_k=2)
        Array(1., dtype=float32)
    """
    _validate_top_k(top_k)
    p, t, m = _as_row(preds, target)
    return _recall_kernel(p, t, m, top_k)[0]


def retrieval_hit_rate(preds, target, top_k: Optional[int] = None) -> Array:
    """HitRate@k of one query (reference functional/retrieval/hit_rate.py:20).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import retrieval_hit_rate
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1])
        >>> target = jnp.asarray([False, True, True, False])
        >>> retrieval_hit_rate(preds, target, top_k=2)
        Array(1., dtype=float32)
    """
    _validate_top_k(top_k)
    p, t, m = _as_row(preds, target)
    return _hit_rate_kernel(p, t, m, top_k)[0]


def retrieval_fall_out(preds, target, top_k: Optional[int] = None) -> Array:
    """FallOut@k of one query (reference functional/retrieval/fall_out.py:20).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import retrieval_fall_out
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1])
        >>> target = jnp.asarray([False, True, True, False])
        >>> retrieval_fall_out(preds, target, top_k=2)
        Array(0., dtype=float32)
    """
    _validate_top_k(top_k)
    p, t, m = _as_row(preds, target)
    return _fall_out_kernel(p, t, m, top_k)[0]


def retrieval_r_precision(preds, target) -> Array:
    """R-Precision of one query (reference functional/retrieval/r_precision.py:16).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import retrieval_r_precision
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1])
        >>> target = jnp.asarray([False, True, True, False])
        >>> retrieval_r_precision(preds, target)
        Array(1., dtype=float32)
    """
    p, t, m = _as_row(preds, target)
    return _r_precision_kernel(p, t, m)[0]


def retrieval_normalized_dcg(preds, target, top_k: Optional[int] = None) -> Array:
    """NDCG of one query; non-binary gains allowed (reference functional/retrieval/ndcg.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import retrieval_normalized_dcg
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1])
        >>> target = jnp.asarray([False, True, True, False])
        >>> retrieval_normalized_dcg(preds, target)
        Array(1., dtype=float32)
    """
    _validate_top_k(top_k)
    p, t, m = _as_row(preds, target, allow_non_binary_target=True)
    return _ndcg_kernel(p, t, m, top_k)[0]


def retrieval_auroc(preds, target, top_k: Optional[int] = None, max_fpr: Optional[float] = None) -> Array:
    """AUROC of one query over top-k docs (reference functional/retrieval/auroc.py:16)."""
    _validate_top_k(top_k)
    if max_fpr is not None:
        # partial AUC needs the ROC curve; delegate to the classification kernel
        from ..classification.auroc import binary_auroc

        p, t = _check_retrieval_functional_inputs(preds, target)
        k = min(top_k or p.shape[-1], p.shape[-1])
        order = jnp.argsort(-p)[:k]
        tk = t[order]
        if (int(tk.max(initial=0)) != 1) or (int(tk.min(initial=1)) != 0):
            return jnp.zeros(())
        return binary_auroc(p[order], tk, max_fpr=max_fpr)
    p, t, m = _as_row(preds, target)
    return _auroc_kernel(p, t, m, top_k)[0]


def retrieval_precision_recall_curve(
    preds, target, max_k: Optional[int] = None, adaptive_k: bool = False
) -> Tuple[Array, Array, Array]:
    """Precision@k / Recall@k for k = 1..max_k of one query
    (reference functional/retrieval/precision_recall_curve.py:24)."""
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    p, t, m = _as_row(preds, target)
    n = p.shape[-1]
    if max_k is None:
        max_k = n
    if not (isinstance(max_k, int) and max_k > 0):
        raise ValueError("`max_k` has to be a positive integer or None")
    if adaptive_k and max_k > n:
        max_k = n
    ks = jnp.arange(1, max_k + 1)
    tgt = jnp.where(p > 0, t, 0)
    from .utils import _ranked_by_preds

    ranked, rmask = _ranked_by_preds(p, tgt, m)
    rel = ((ranked > 0) & rmask).astype(jnp.float32)[0]
    cum = jnp.cumsum(rel)
    cum_k = cum[jnp.minimum(ks - 1, n - 1)]
    precision = cum_k / ks.astype(jnp.float32)
    total = (jnp.where(m, t, 0) > 0).sum().astype(jnp.float32)
    recall = jnp.where(total > 0, cum_k / jnp.maximum(total, 1.0), jnp.zeros_like(cum_k))
    return precision, recall, ks


__all__ = [
    "retrieval_average_precision",
    "retrieval_auroc",
    "retrieval_fall_out",
    "retrieval_hit_rate",
    "retrieval_normalized_dcg",
    "retrieval_precision",
    "retrieval_precision_recall_curve",
    "retrieval_r_precision",
    "retrieval_recall",
    "retrieval_reciprocal_rank",
]
