"""Retrieval machinery: input checks, query padding, tie-aware rank helpers.

TPU-native core (SURVEY §7 step 6): the reference processes queries with a host loop
over ``torch.split`` chunks (retrieval/base.py:148-182). Here queries are padded into a
dense ``(Q, L)`` matrix with a validity mask; every metric is a vectorized masked
kernel over that matrix — one XLA call for the whole corpus, no host loop.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

NEG_INF = -jnp.inf


def _check_retrieval_functional_inputs(preds, target, allow_non_binary_target: bool = False) -> Tuple[Array, Array]:
    """Validate a single query's (preds, target) (reference utilities/checks.py:44)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if preds.size == 0:
        raise ValueError("`preds` and `target` must be non-empty")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    if not (jnp.issubdtype(target.dtype, jnp.integer) or target.dtype == jnp.bool_):
        raise ValueError("`target` must be a tensor of booleans or integers")
    target = target.astype(jnp.int32)
    if not allow_non_binary_target and (int(target.max()) > 1 or int(target.min()) < 0):
        raise ValueError("`target` must contain `binary` values")
    return preds.reshape(-1).astype(jnp.float32), target.reshape(-1)


def _check_retrieval_inputs(
    indexes, preds, target, allow_non_binary_target: bool = False, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """Validate (indexes, preds, target) and apply ignore_index filtering
    (reference utilities/checks.py:64)."""
    indexes = jnp.asarray(indexes)
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    if not (jnp.issubdtype(target.dtype, jnp.integer) or target.dtype == jnp.bool_):
        raise ValueError("`target` must be a tensor of booleans or integers")
    indexes = indexes.reshape(-1)
    preds = preds.reshape(-1).astype(jnp.float32)
    target = target.reshape(-1).astype(jnp.int32)
    if ignore_index is not None:
        keep = np.asarray(target) != ignore_index  # host filter: cat-states are host lists anyway
        indexes, preds, target = indexes[keep], preds[keep], target[keep]
    if preds.size == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty")
    if not allow_non_binary_target and (int(target.max()) > 1 or int(target.min()) < 0):
        raise ValueError("`target` must contain `binary` values")
    return indexes, preds, target


def _pad_queries(indexes, preds, target) -> Tuple[Array, Array, Array]:
    """Group flat (indexes, preds, target) into padded ``(Q, L)`` arrays + bool mask.

    Host-side scatter (numpy) — runs once per ``compute``; every downstream metric is
    then a single static-shape XLA kernel.
    """
    idx = np.asarray(indexes)
    p = np.asarray(preds)
    t = np.asarray(target)
    uniq, inv, counts = np.unique(idx, return_inverse=True, return_counts=True)
    q = uniq.shape[0]
    max_len = int(counts.max()) if q else 1
    order = np.argsort(inv, kind="stable")
    inv_sorted = inv[order]
    pos_in_query = np.arange(idx.shape[0]) - np.concatenate([[0], np.cumsum(counts)[:-1]])[inv_sorted]
    preds2d = np.zeros((q, max_len), np.float32)
    target2d = np.zeros((q, max_len), t.dtype)
    mask2d = np.zeros((q, max_len), bool)
    preds2d[inv_sorted, pos_in_query] = p[order]
    target2d[inv_sorted, pos_in_query] = t[order]
    mask2d[inv_sorted, pos_in_query] = True
    return jnp.asarray(preds2d), jnp.asarray(target2d), jnp.asarray(mask2d)


def _ranked_by_preds(preds: Array, target: Array, mask: Array) -> Tuple[Array, Array]:
    """Per-row targets/mask reordered by descending preds; padded entries sink last."""
    eff = jnp.where(mask, preds, NEG_INF)
    order = jnp.argsort(-eff, axis=-1, stable=True)
    return jnp.take_along_axis(target, order, axis=-1), jnp.take_along_axis(mask, order, axis=-1)


def _row_segment_ids(sorted_vals: Array) -> Array:
    """Tie-group ids per row for row-wise sorted values (0-based, ascending)."""
    first = jnp.ones_like(sorted_vals[..., :1], bool)
    change = sorted_vals[..., 1:] != sorted_vals[..., :-1]
    return jnp.cumsum(jnp.concatenate([first, change], axis=-1).astype(jnp.int32), axis=-1) - 1


def _tie_average_ranks(preds: Array, mask: Array) -> Array:
    """Average ranks (1-based, ascending preds) with ties averaged, per row.

    Padded entries get rank 0 and must be excluded by the caller via ``mask``.
    """
    n = preds.shape[-1]
    eff = jnp.where(mask, preds, NEG_INF)  # padded sort first (ascending)
    order = jnp.argsort(eff, axis=-1, stable=True)
    sorted_vals = jnp.take_along_axis(eff, order, axis=-1)
    seg = _row_segment_ids(sorted_vals)
    ordinal = jnp.arange(1, n + 1, dtype=jnp.float32)
    seg_sum = jax.vmap(lambda s, v: jax.ops.segment_sum(v, s, num_segments=n))(seg, jnp.broadcast_to(ordinal, seg.shape))
    seg_cnt = jax.vmap(lambda s: jax.ops.segment_sum(jnp.ones(n, jnp.float32), s, num_segments=n))(seg)
    avg_per_seg = seg_sum / jnp.maximum(seg_cnt, 1.0)
    avg_sorted = jnp.take_along_axis(avg_per_seg, seg, axis=-1)
    ranks = jnp.zeros_like(avg_sorted)
    ranks = jnp.put_along_axis(ranks, order, avg_sorted, axis=-1, inplace=False)
    # shift so ranks count only real entries (padded occupy the lowest ordinals)
    n_pad = (~mask).sum(axis=-1, keepdims=True).astype(jnp.float32)
    return jnp.where(mask, ranks - n_pad, 0.0)
